"""The trainer workload: config -> mesh -> data -> sharded steps -> checkpoints.

This is the TPU-native replacement for the external trainer containers the
reference schedules (reference: examples/llama2-7b/finetuned-model.yaml uses
substratusai/model-trainer-huggingface; here training is in-framework). It
honors the container contract (/content/params.json in, /content/artifacts
out) so the operator layer schedules it exactly like the reference schedules
its trainer images.

Entry point: ``python -m runbooks_tpu.train.trainer`` (reads params.json), or
``run_training(TrainJobConfig(...))`` programmatically.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Dict, Iterator, Optional

import jax
import numpy as np

from runbooks_tpu.models.config import ModelConfig, get_config
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh
from runbooks_tpu.train import data as data_mod
from runbooks_tpu.train.checkpoint import CheckpointManager
from runbooks_tpu.train.lora import (
    LoraConfig,
    create_lora_train_state,
    make_lora_train_step,
)
from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
from runbooks_tpu.train.step import create_train_state, make_train_step
from runbooks_tpu.utils import contract


@dataclasses.dataclass(frozen=True)
class TrainJobConfig:
    model: str = "debug"
    model_overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)
    mesh: MeshConfig = MeshConfig()
    optimizer: OptimizerConfig = OptimizerConfig()
    lora: Optional[LoraConfig] = None

    batch_size: int = 8           # global batch (microbatched when
                                  # accumulate_steps > 1)
    seq_len: int = 512
    steps: int = 100
    # Training fast path (docs/training-performance.md):
    # accumulate_steps=k runs k microbatches of batch_size/k per optimizer
    # step (peak activation memory of one microbatch); loss_chunk=c
    # computes the loss via the chunked fused cross-entropy (the
    # [b, s, vocab] f32 logits tensor is never materialized); 0 = off.
    # prefetch_depth>0 tokenizes/packs ahead on a background thread and
    # double-buffers jax.device_put with the mesh batch shardings.
    accumulate_steps: int = 1
    loss_chunk: int = 0
    prefetch_depth: int = 2
    # Overlapped collective-matmul tensor parallelism ("off"|"ring"|"auto",
    # docs/tensor-parallel-performance.md): overrides the model config's
    # collective_matmul when set. "auto" rings whenever mesh_tensor > 1.
    collective_matmul: Optional[str] = None
    data_path: Optional[str] = None       # default: contract data dir
    tokenizer: Optional[str] = None
    text_key: str = "text"                # jsonl field holding the document
    # str.format template over jsonl record fields (reference analog: the
    # trainer images' prompt_template param).
    prompt_template: Optional[str] = None
    seed: int = 0

    checkpoint_every: int = 50
    artifacts_dir: Optional[str] = None   # default: contract artifacts dir
    log_every: int = 10
    resume: bool = True
    # XLA/JAX profiler capture: trace steps [profile_start, profile_stop)
    # into {artifacts}/profile (viewable in XProf/TensorBoard). Net-new vs
    # the reference, which has no profiling hooks (SURVEY.md §5.1).
    profile_start: int = 0
    profile_stop: int = 0

    @classmethod
    def from_params(cls, params: Dict[str, Any]) -> "TrainJobConfig":
        """Build from a flat params.json dict (the operator-facing config
        surface, like the reference's params -> PARAM_* convention)."""
        kwargs: Dict[str, Any] = {}
        params = dict(params)
        # The reference's spec style is camelCase; the env round-trip
        # (PARAM_ACCUMULATESTEPS) lowercases it. Accept both spellings for
        # the controller-validated key so a validated spec cannot silently
        # train without accumulation.
        for alias in ("accumulateSteps", "accumulatesteps"):
            if alias in params:
                params.setdefault("accumulate_steps", params.pop(alias))
        from runbooks_tpu.models.config import COLLECTIVE_MATMUL_PARAM_KEYS

        for alias in COLLECTIVE_MATMUL_PARAM_KEYS[1:]:
            if alias in params:
                params.setdefault("collective_matmul", params.pop(alias))
        simple = {f.name for f in dataclasses.fields(cls)
                  if f.name not in ("mesh", "optimizer", "lora",
                                    "model_overrides")}
        for k, v in params.items():
            if k in simple:
                kwargs[k] = v
        # YAML specs quote freely ("8"); a str here would TypeError deep in
        # run_training instead of at the validated boundary.
        for key in ("accumulate_steps", "loss_chunk", "prefetch_depth",
                    "batch_size", "seq_len", "steps"):
            if key in kwargs:
                kwargs[key] = int(kwargs[key])
        mesh_keys = {f.name for f in dataclasses.fields(MeshConfig)}
        mesh_args = {k[len("mesh_"):]: int(v) for k, v in params.items()
                     if k.startswith("mesh_") and k[len("mesh_"):] in mesh_keys}
        if mesh_args:
            kwargs["mesh"] = MeshConfig(**mesh_args)
        opt_keys = {f.name for f in dataclasses.fields(OptimizerConfig)}
        opt_args = {k: v for k, v in params.items() if k in opt_keys}
        if opt_args:
            kwargs["optimizer"] = OptimizerConfig(**opt_args)
        if params.get("lora"):
            lora = params["lora"]
            kwargs["lora"] = (LoraConfig(**lora) if isinstance(lora, dict)
                              else LoraConfig())
        if params.get("model_overrides"):
            kwargs["model_overrides"] = dict(params["model_overrides"])
        return cls(**kwargs)


def _batches(job: TrainJobConfig, model_cfg: ModelConfig) -> Iterator[dict]:
    path = job.data_path or contract.data_dir()
    import os

    if path and os.path.exists(path):
        tok = data_mod.load_tokenizer(job.tokenizer)
        vocab = getattr(tok, "vocab_size", model_cfg.vocab_size)
        if vocab > model_cfg.vocab_size:
            # A real error, not an assert: `python -O` strips asserts and
            # out-of-range token ids would then index-wrap into garbage
            # embeddings mid-training.
            raise ValueError(
                f"tokenizer vocab {vocab} exceeds model vocab "
                f"{model_cfg.vocab_size}")
        return data_mod.dataset(path, job.seq_len, job.batch_size,
                                tokenizer=tok, epochs=None,
                                text_key=job.text_key,
                                prompt_template=job.prompt_template)
    return data_mod.synthetic_batches(model_cfg.vocab_size, job.seq_len,
                                      job.batch_size, job.seed)


def run_training(job: TrainJobConfig,
                 base_params=None) -> Dict[str, Any]:
    """Run the training job; returns final metrics summary (also written to
    {artifacts}/metrics.json)."""
    import os

    model_cfg = get_config(job.model, **job.model_overrides)
    if job.collective_matmul is not None:
        # Fail at the validated boundary, not mid-compile: the
        # controller's validate_params enforces the same enum.
        from runbooks_tpu.models.config import check_collective_matmul

        model_cfg = dataclasses.replace(
            model_cfg,
            collective_matmul=check_collective_matmul(job.collective_matmul))
    if job.accumulate_steps < 1:
        raise ValueError(
            f"accumulate_steps must be >= 1, got {job.accumulate_steps}")
    if job.batch_size % job.accumulate_steps:
        raise ValueError(
            f"accumulate_steps={job.accumulate_steps} must divide "
            f"batch_size={job.batch_size}")
    mesh = make_mesh(job.mesh)
    optimizer = make_optimizer(job.optimizer)
    artifacts = job.artifacts_dir or contract.artifacts_dir()
    os.makedirs(artifacts, exist_ok=True)
    # Persistent compile cache in the durable artifacts mount: a restarted
    # Job (slice restart / resume) skips the full XLA recompile.
    from runbooks_tpu.utils.jax_cache import enable_compilation_cache

    enable_compilation_cache(os.path.join(artifacts, "jax_cache"))
    ckpt = CheckpointManager(artifacts)

    rng = jax.random.key(job.seed)
    lora_mode = job.lora is not None
    if lora_mode:
        if base_params is None:
            from runbooks_tpu.models.transformer import init_params
            from runbooks_tpu.models.transformer import param_logical_axes
            from runbooks_tpu.parallel.sharding import tree_shardings

            shapes = jax.eval_shape(
                lambda r: init_params(model_cfg, r), rng)
            base_shardings = tree_shardings(
                shapes, param_logical_axes(model_cfg), mesh)
            with jax.set_mesh(mesh):
                base_params = jax.jit(
                    lambda r: init_params(model_cfg, r),
                    out_shardings=base_shardings)(rng)
        else:
            from runbooks_tpu.models.transformer import param_logical_axes
            from runbooks_tpu.parallel.sharding import tree_shardings

            base_shardings = tree_shardings(
                jax.eval_shape(lambda: base_params),
                param_logical_axes(model_cfg), mesh)
            base_params = jax.device_put(base_params, base_shardings)
        state, shardings = create_lora_train_state(
            model_cfg, job.lora, base_params, optimizer, mesh, rng)
        step_fn = make_lora_train_step(
            model_cfg, job.lora, optimizer, mesh, shardings, base_shardings,
            accumulate_steps=job.accumulate_steps, loss_chunk=job.loss_chunk)
    else:
        state, shardings = create_train_state(model_cfg, optimizer, mesh, rng)
        step_fn = make_train_step(model_cfg, optimizer, mesh, shardings,
                                  accumulate_steps=job.accumulate_steps,
                                  loss_chunk=job.loss_chunk)

    start_step = 0
    if job.resume and ckpt.latest_step() is not None:
        state = ckpt.restore(state)
        start_step = int(state.step)

    batches = _batches(job, model_cfg)
    prefetcher = None
    if job.prefetch_depth > 0:
        # Async input pipeline: tokenize/pack runs ahead on a background
        # thread and batches land on device (sharded device_put) while the
        # previous step computes — host work overlaps device compute
        # instead of serializing with it inside the step loop.
        batches = prefetcher = data_mod.Prefetcher(
            batches, depth=job.prefetch_depth,
            place=data_mod.device_placer(mesh))
    history = []
    tokens_per_step = job.batch_size * job.seq_len
    flops_per_token = 3.0 * model_cfg.flops_per_token(job.seq_len)
    from runbooks_tpu.utils.hw import chip_peak_flops

    peak_flops = chip_peak_flops(jax.devices()[0]) * len(jax.devices())
    t_start = time.perf_counter()
    tokens_done = 0
    compile_time_s = None

    profiling = False
    try:
        with jax.set_mesh(mesh):
            for i in range(start_step, job.steps):
                if job.profile_stop > job.profile_start \
                        and i == job.profile_start:
                    jax.profiler.start_trace(
                        os.path.join(artifacts, "profile"))
                    profiling = True
                batch = next(batches)
                if prefetcher is None:
                    batch = {k: np.asarray(v) for k, v in batch.items()}
                if lora_mode:
                    state, metrics = step_fn(state, base_params, batch)
                else:
                    state, metrics = step_fn(state, batch)
                if i == start_step:
                    # The first step folds the XLA compile; pulling the
                    # loss waits for it, then the throughput window resets
                    # so tokens/sec and MFU report steady-state compute
                    # (compile time lands in its own field).
                    float(metrics["loss"])
                    compile_time_s = time.perf_counter() - t_start
                    t_start = time.perf_counter()
                else:
                    tokens_done += tokens_per_step
                if profiling and i + 1 == job.profile_stop:
                    jax.block_until_ready(metrics["loss"])
                    jax.profiler.stop_trace()
                    profiling = False
                if (i + 1) % job.log_every == 0 or i + 1 == job.steps:
                    # Only log points sync on the device (float pulls the
                    # scalar); between them steps dispatch async with
                    # metrics buffered as device arrays.
                    loss = float(metrics["loss"])
                    dt = time.perf_counter() - t_start
                    if tokens_done:
                        tps = tokens_done / max(dt, 1e-9)
                    else:  # single measured step: only the compile window
                        tps = tokens_per_step / max(compile_time_s, 1e-9)
                    achieved = tps * flops_per_token
                    entry = {"step": i + 1, "loss": round(loss, 4),
                             "tokens_per_sec": round(tps, 1),
                             "tflops_per_sec": round(achieved / 1e12, 2)}
                    if peak_flops:
                        entry["mfu"] = round(achieved / peak_flops, 4)
                    if not history and compile_time_s is not None:
                        entry["compile_time_s"] = round(compile_time_s, 2)
                    history.append(entry)
                    print(json.dumps(entry), flush=True)
                if (i + 1) % job.checkpoint_every == 0 or i + 1 == job.steps:
                    ckpt.save(i + 1, state)
    finally:
        if prefetcher is not None:
            prefetcher.close()

    if profiling:  # profile window ran past the last step
        jax.profiler.stop_trace()
    ckpt.wait()
    summary = {
        "final_loss": history[-1]["loss"] if history else None,
        "steps": job.steps,
        "tokens_per_sec": history[-1]["tokens_per_sec"] if history else None,
        "compile_time_s": compile_time_s,
        "accumulate_steps": job.accumulate_steps,
        "model": job.model,
        "lora": lora_mode,
        "history": history,
    }
    with open(os.path.join(artifacts, "metrics.json"), "w") as f:
        json.dump(summary, f, indent=2)
    if lora_mode:
        # Export merged params reference for serving (artifact contract).
        merged_note = {"note": "merged weights = base + lora; see checkpoints"}
        with open(os.path.join(artifacts, "lora.json"), "w") as f:
            json.dump(dataclasses.asdict(job.lora) | merged_note, f)
    ckpt.close()
    return summary


def main() -> int:
    params = contract.load_params()
    job = TrainJobConfig.from_params(params)
    summary = run_training(job)
    print(json.dumps({"done": True, **{k: v for k, v in summary.items()
                                       if k != "history"}}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
