"""The trainer workload: config -> mesh -> data -> sharded steps -> checkpoints.

This is the TPU-native replacement for the external trainer containers the
reference schedules (reference: examples/llama2-7b/finetuned-model.yaml uses
substratusai/model-trainer-huggingface; here training is in-framework). It
honors the container contract (/content/params.json in, /content/artifacts
out) so the operator layer schedules it exactly like the reference schedules
its trainer images.

Entry point: ``python -m runbooks_tpu.train.trainer`` (reads params.json), or
``run_training(TrainJobConfig(...))`` programmatically.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import signal
import threading
import time
from typing import Any, Dict, Iterator, Optional

import jax
import numpy as np

from runbooks_tpu.models.config import ModelConfig, get_config
from runbooks_tpu.obs import device as obs_device
from runbooks_tpu.obs import trace as obs_trace
from runbooks_tpu.obs.goodput import GoodputTracker
from runbooks_tpu.obs.metrics import REGISTRY
from runbooks_tpu.obs.profile import PROFILER, parse_profile_at_step
from runbooks_tpu.obs.trace import span
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh
from runbooks_tpu.train import data as data_mod
from runbooks_tpu.train.checkpoint import CheckpointManager
from runbooks_tpu.train.lora import (
    LoraConfig,
    create_lora_train_state,
    make_lora_train_step,
)
from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
from runbooks_tpu.train.step import create_train_state, make_train_step
from runbooks_tpu.utils import contract
from runbooks_tpu.utils.contract import EXIT_PREEMPTED


class SimulatedFault(RuntimeError):
    """Raised by the RBT_FAULT_INJECT hook's `kill` mode: a deterministic
    stand-in for an abrupt process death (no emergency checkpoint, no
    cleanup beyond `finally`), used by tests/test_fault_tolerance.py to
    prove step-exact resume."""


def _parse_fault_inject() -> Optional[dict]:
    """RBT_FAULT_INJECT=<mode>:<step>[+] — the deterministic fault-injection
    hook (docs/fault-tolerance.md). Modes:

      kill:K       raise SimulatedFault at the top of step K (the run dies
                   as a preemption would, mid-stream, without the graceful
                   paths)
      sigterm:K    deliver SIGTERM to this process at the top of step K
                   (exercises the real handler: emergency checkpoint +
                   preempted exit)
      nonfinite:K  poison step K's batch with NaN (exercises the non-finite
                   guard); `K+` poisons every step from K on (exercises the
                   consecutive-bad-step abort)
    """
    spec = os.environ.get("RBT_FAULT_INJECT", "")
    if not spec:
        return None
    mode, _, step = spec.partition(":")
    if mode not in ("kill", "sigterm", "nonfinite") or not step:
        raise ValueError(
            f"RBT_FAULT_INJECT={spec!r}: expected kill:K|sigterm:K|"
            "nonfinite:K[+]")
    return {"mode": mode, "step": int(step.rstrip("+")),
            "repeat": step.endswith("+")}


@dataclasses.dataclass(frozen=True)
class TrainJobConfig:
    model: str = "debug"
    model_overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)
    mesh: MeshConfig = MeshConfig()
    optimizer: OptimizerConfig = OptimizerConfig()
    lora: Optional[LoraConfig] = None

    batch_size: int = 8           # global batch (microbatched when
                                  # accumulate_steps > 1)
    seq_len: int = 512
    steps: int = 100
    # Training fast path (docs/training-performance.md):
    # accumulate_steps=k runs k microbatches of batch_size/k per optimizer
    # step (peak activation memory of one microbatch); loss_chunk=c
    # computes the loss via the chunked fused cross-entropy (the
    # [b, s, vocab] f32 logits tensor is never materialized); 0 = off.
    # prefetch_depth>0 tokenizes/packs ahead on a background thread and
    # double-buffers jax.device_put with the mesh batch shardings.
    accumulate_steps: int = 1
    loss_chunk: int = 0
    prefetch_depth: int = 2
    # Overlapped collective-matmul tensor parallelism ("off"|"ring"|"auto",
    # docs/tensor-parallel-performance.md): overrides the model config's
    # collective_matmul when set. "auto" rings whenever mesh_tensor > 1.
    collective_matmul: Optional[str] = None
    data_path: Optional[str] = None       # default: contract data dir
    tokenizer: Optional[str] = None
    text_key: str = "text"                # jsonl field holding the document
    # str.format template over jsonl record fields (reference analog: the
    # trainer images' prompt_template param).
    prompt_template: Optional[str] = None
    seed: int = 0

    checkpoint_every: int = 50
    artifacts_dir: Optional[str] = None   # default: contract artifacts dir
    log_every: int = 10
    resume: bool = True
    # Fault tolerance (docs/fault-tolerance.md): abort after this many
    # CONSECUTIVE non-finite loss/grad steps (each bad step skips the
    # update — params bitwise unchanged — so a transient bad batch costs
    # one step, not the run). maintenance_poll_s > 0 polls the GCE
    # metadata server for a pending maintenance event/preemption and
    # treats one like SIGTERM (emergency checkpoint + clean exit);
    # main() turns it on automatically when running on GCE.
    max_bad_steps: int = 3
    maintenance_poll_s: float = 0.0
    # XLA/JAX profiler capture: trace steps [profile_start, profile_stop)
    # into {artifacts}/profile (viewable in XProf/TensorBoard). Net-new vs
    # the reference, which has no profiling hooks (SURVEY.md §5.1).
    profile_start: int = 0
    profile_stop: int = 0

    @classmethod
    def from_params(cls, params: Dict[str, Any]) -> "TrainJobConfig":
        """Build from a flat params.json dict (the operator-facing config
        surface, like the reference's params -> PARAM_* convention)."""
        kwargs: Dict[str, Any] = {}
        params = dict(params)
        # The reference's spec style is camelCase; the env round-trip
        # (PARAM_ACCUMULATESTEPS) lowercases it. Accept both spellings for
        # the controller-validated key so a validated spec cannot silently
        # train without accumulation.
        for alias in ("accumulateSteps", "accumulatesteps"):
            if alias in params:
                params.setdefault("accumulate_steps", params.pop(alias))
        for alias in ("maxBadSteps", "maxbadsteps"):
            if alias in params:
                params.setdefault("max_bad_steps", params.pop(alias))
        from runbooks_tpu.models.config import COLLECTIVE_MATMUL_PARAM_KEYS

        for alias in COLLECTIVE_MATMUL_PARAM_KEYS[1:]:
            if alias in params:
                params.setdefault("collective_matmul", params.pop(alias))
        simple = {f.name for f in dataclasses.fields(cls)
                  if f.name not in ("mesh", "optimizer", "lora",
                                    "model_overrides")}
        for k, v in params.items():
            if k in simple:
                kwargs[k] = v
        # YAML specs quote freely ("8"); a str here would TypeError deep in
        # run_training instead of at the validated boundary.
        for key in ("accumulate_steps", "loss_chunk", "prefetch_depth",
                    "batch_size", "seq_len", "steps", "max_bad_steps"):
            if key in kwargs:
                kwargs[key] = int(kwargs[key])
        if "maintenance_poll_s" in kwargs:
            kwargs["maintenance_poll_s"] = float(kwargs["maintenance_poll_s"])
        mesh_keys = {f.name for f in dataclasses.fields(MeshConfig)}
        mesh_args = {k[len("mesh_"):]: int(v) for k, v in params.items()
                     if k.startswith("mesh_") and k[len("mesh_"):] in mesh_keys}
        if mesh_args:
            kwargs["mesh"] = MeshConfig(**mesh_args)
        opt_keys = {f.name for f in dataclasses.fields(OptimizerConfig)}
        opt_args = {k: v for k, v in params.items() if k in opt_keys}
        if opt_args:
            kwargs["optimizer"] = OptimizerConfig(**opt_args)
        if params.get("lora"):
            lora = params["lora"]
            kwargs["lora"] = (LoraConfig(**lora) if isinstance(lora, dict)
                              else LoraConfig())
        if params.get("model_overrides"):
            kwargs["model_overrides"] = dict(params["model_overrides"])
        return cls(**kwargs)


def _batches(job: TrainJobConfig, model_cfg: ModelConfig,
             skip: int = 0) -> Iterator[dict]:
    path = job.data_path or contract.data_dir()

    if path and os.path.exists(path):
        tok = data_mod.load_tokenizer(job.tokenizer)
        vocab = getattr(tok, "vocab_size", model_cfg.vocab_size)
        if vocab > model_cfg.vocab_size:
            # A real error, not an assert: `python -O` strips asserts and
            # out-of-range token ids would then index-wrap into garbage
            # embeddings mid-training.
            raise ValueError(
                f"tokenizer vocab {vocab} exceeds model vocab "
                f"{model_cfg.vocab_size}")
        it = data_mod.dataset(path, job.seq_len, job.batch_size,
                              tokenizer=tok, epochs=None,
                              text_key=job.text_key,
                              prompt_template=job.prompt_template)
    else:
        it = data_mod.synthetic_batches(model_cfg.vocab_size, job.seq_len,
                                        job.batch_size, job.seed)
    if skip:
        # Resume at the checkpoint's data cursor: batch `skip` comes first,
        # exactly as the uninterrupted run would have seen it.
        print(f"data: advancing to batch cursor {skip} "
              "(step-exact resume)", flush=True)
        it = data_mod.skip_batches(it, skip)
    return it


def run_training(job: TrainJobConfig,
                 base_params=None) -> Dict[str, Any]:
    """Run the training job; returns final metrics summary (also written to
    {artifacts}/metrics.json).

    Preemption-tolerant (docs/fault-tolerance.md): SIGTERM/SIGINT (and a
    pending GCE maintenance event, when polled) stop the loop at the next
    step boundary, force an emergency checkpoint carrying the data cursor,
    and return with summary["exit_reason"] set — main() maps that to the
    documented EXIT_PREEMPTED code so the controller's Job policy restarts
    the pod instead of failing the run."""
    model_cfg = get_config(job.model, **job.model_overrides)
    if job.collective_matmul is not None:
        # Fail at the validated boundary, not mid-compile: the
        # controller's validate_params enforces the same enum.
        from runbooks_tpu.models.config import check_collective_matmul

        model_cfg = dataclasses.replace(
            model_cfg,
            collective_matmul=check_collective_matmul(job.collective_matmul))
    if job.accumulate_steps < 1:
        raise ValueError(
            f"accumulate_steps must be >= 1, got {job.accumulate_steps}")
    if job.batch_size % job.accumulate_steps:
        raise ValueError(
            f"accumulate_steps={job.accumulate_steps} must divide "
            f"batch_size={job.batch_size}")
    mesh = make_mesh(job.mesh)
    optimizer = make_optimizer(job.optimizer)
    artifacts = job.artifacts_dir or contract.artifacts_dir()
    os.makedirs(artifacts, exist_ok=True)
    # Flight/trace identity (obs/flight.py): this run's span events —
    # in the always-on ring, in tail-sampled promotions, and in any
    # incident bundle — label as the training tier.
    from runbooks_tpu.obs import flight as obs_flight

    obs_flight.set_component("train")
    # The trace path is configured unconditionally: RBT_TRACE=1 writes
    # live spans there, and tail-sampling/incident promotion needs the
    # same per-run destination even when live tracing is off.
    obs_trace.configure(os.path.join(artifacts, "trace.jsonl"))
    # Persistent compile cache in the durable artifacts mount: a restarted
    # Job (slice restart / resume) skips the full XLA recompile.
    from runbooks_tpu.utils.jax_cache import enable_compilation_cache

    enable_compilation_cache(os.path.join(artifacts, "jax_cache"))
    ckpt = CheckpointManager(artifacts)

    rng = jax.random.key(job.seed)
    lora_mode = job.lora is not None
    if lora_mode:
        if base_params is None:
            from runbooks_tpu.models.transformer import init_params
            from runbooks_tpu.models.transformer import param_logical_axes
            from runbooks_tpu.parallel.sharding import tree_shardings

            shapes = jax.eval_shape(
                lambda r: init_params(model_cfg, r), rng)
            base_shardings = tree_shardings(
                shapes, param_logical_axes(model_cfg), mesh)
            from runbooks_tpu.train.step import layout_invariant_init

            with jax.set_mesh(mesh), layout_invariant_init():
                base_params = jax.jit(
                    lambda r: init_params(model_cfg, r),
                    out_shardings=base_shardings)(rng)
        else:
            from runbooks_tpu.models.transformer import param_logical_axes
            from runbooks_tpu.parallel.sharding import tree_shardings

            base_shardings = tree_shardings(
                jax.eval_shape(lambda: base_params),
                param_logical_axes(model_cfg), mesh)
            base_params = jax.device_put(base_params, base_shardings)
        state, shardings = create_lora_train_state(
            model_cfg, job.lora, base_params, optimizer, mesh, rng)
        step_fn = make_lora_train_step(
            model_cfg, job.lora, optimizer, mesh, shardings, base_shardings,
            accumulate_steps=job.accumulate_steps, loss_chunk=job.loss_chunk)
    else:
        state, shardings = create_train_state(model_cfg, optimizer, mesh, rng)
        step_fn = make_train_step(model_cfg, optimizer, mesh, shardings,
                                  accumulate_steps=job.accumulate_steps,
                                  loss_chunk=job.loss_chunk)

    # Device-level observability (obs/device.py): compile sentinel +
    # program census. After the first step folds the XLA compile, any
    # further compile in the steady loop is a stall the sentinel flags
    # (xla_unexpected_compiles_total) — exactly the failure mode the
    # at-scale postmortems lead with (PAPERS.md).
    obs_device.SENTINEL.install()
    obs_device.PROGRAMS.register("train", "train_step", step_fn)

    # May raise on a malformed value — before any state needing cleanup.
    fault = _parse_fault_inject()
    # RBT_PROFILE_AT_STEP=n[:k]: on-demand capture of k steps starting at
    # step n into {artifacts}/profiles/ (docs/observability.md). Parsed
    # here for the same reason as the fault hook.
    profile_at = parse_profile_at_step()

    start_step = 0
    consumed = 0          # batches pulled from the data stream (the cursor)
    restore_time_s = None
    stop = {"reason": None}
    restore_sigs = []
    poller_stop = None
    prefetcher = None
    history = []
    tokens_per_step = job.batch_size * job.seq_len
    flops_per_token = 3.0 * model_cfg.flops_per_token(job.seq_len)
    from runbooks_tpu.utils.hw import chip_peak_flops

    peak_flops = chip_peak_flops(jax.devices()[0]) * len(jax.devices())
    tokens_done = 0
    compile_time_s = None

    profiling = False
    profiling_at = False   # RBT_PROFILE_AT_STEP capture in flight
    exit_reason = None
    bad_streak = 0
    nonfinite_steps = 0
    pending_nf = None      # previous step's (index, nonfinite flag)
    last_saved = -1
    device_cost = None     # roofline attribution of the train step
    compiles_before = obs_device.SENTINEL.total
    unexpected_before = obs_device.SENTINEL.unexpected
    hbm_peak_bytes = 0

    # Goodput accounting (obs/goodput.py): productive step time ÷ wall
    # clock, with restart overhead (restore + compile) excluded so a
    # preempted-and-resumed run reports steady-state goodput, not a ratio
    # dragged down by however long the restore took. The clock starts
    # here — before restore — so restore genuinely lands inside the wall.
    goodput = GoodputTracker()
    # Per-log-window phase sums; each history entry reports window means.
    win = {"data": 0.0, "step": 0.0, "ckpt": 0.0, "steps": 0}

    def _summary_dict(in_progress: bool = False) -> Dict[str, Any]:
        s = {
            "final_loss": history[-1]["loss"] if history else None,
            "steps": job.steps,
            "tokens_per_sec": (history[-1]["tokens_per_sec"]
                               if history else None),
            "compile_time_s": compile_time_s,
            "restore_time_s": restore_time_s,
            "accumulate_steps": job.accumulate_steps,
            "model": job.model,
            "lora": lora_mode,
            "exit_reason": exit_reason,
            "nonfinite_steps": nonfinite_steps,
            "batches_consumed": consumed,
            "goodput": goodput.ratio() if goodput.steps else None,
            "goodput_detail": goodput.snapshot(),
            "device_obs": {
                # Analytic cross-check of the wall-clock MFU: FLOPs and
                # HBM bytes from the compiled step's cost_analysis, with
                # the roofline classification (docs/observability.md).
                "cost": device_cost,
                "formula_flops_per_step": flops_per_token * tokens_per_step,
                "compiles": obs_device.SENTINEL.total - compiles_before,
                "unexpected_compiles":
                    obs_device.SENTINEL.unexpected - unexpected_before,
                "hbm_peak_bytes": hbm_peak_bytes or None,
            },
            "history": history,
        }
        if in_progress:
            s["in_progress"] = True
        return s

    def _write_metrics(summary: Optional[Dict[str, Any]] = None) -> None:
        # Atomic (temp + os.replace) AND incremental (every log point):
        # a preempted run keeps its metrics history up to the last log
        # line instead of losing all of it — the checkpoint survived
        # preemption since PR 4; now the telemetry does too. A torn write
        # can never be observed: readers see the old file or the new one.
        path = os.path.join(artifacts, "metrics.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(summary if summary is not None
                      else _summary_dict(in_progress=True), f, indent=2)
        os.replace(tmp, path)

    def _check_nonfinite(pending) -> None:
        # Checked one step LATE on purpose: pulling the flag then only
        # waits on an already-finished step, so the guard adds no host/
        # device sync to the steady-state pipeline.
        nonlocal bad_streak, nonfinite_steps
        if pending is None:
            return
        step_idx, nf = pending
        if nf is None or float(nf) == 0.0:
            bad_streak = 0
            return
        bad_streak += 1
        nonfinite_steps += 1
        print(json.dumps({"step": step_idx + 1, "nonfinite": True,
                          "consecutive_bad": bad_streak}), flush=True)
        if bad_streak >= max(1, job.max_bad_steps):
            # The abort is an incident: bundle the flight ring, metrics,
            # and memory/program census beside the artifacts BEFORE
            # raising (debounced; capture never raises).
            from runbooks_tpu.obs import incident as obs_incident

            obs_incident.capture(
                "train_max_bad_steps", artifacts=artifacts,
                component="train",
                extra={"step": step_idx + 1, "bad_streak": bad_streak,
                       "nonfinite_steps": nonfinite_steps})
            raise RuntimeError(
                f"aborting: {bad_streak} consecutive non-finite loss/grad "
                f"steps (last at step {step_idx + 1}). Params were left "
                "unchanged by every bad step — inspect the data shard / "
                "learning rate and resume from the last checkpoint "
                "(docs/fault-tolerance.md)")

    def _fault_due(i: int, mode: str) -> bool:
        return (fault is not None and fault["mode"] == mode
                and (i == fault["step"]
                     or (fault["repeat"] and i >= fault["step"])))

    # Everything from here runs under the cleanup block: a failure in
    # restore, data-pipeline setup, or the loop itself must still restore
    # the signal handlers and wait/close the async checkpoint manager.
    try:
        if job.resume and ckpt.latest_intact_step() is not None:
            t_restore = time.perf_counter()
            with span("restore"):
                state, cursor, _ckpt_step = ckpt.restore_with_cursor(state)
            restore_time_s = time.perf_counter() - t_restore
            # Restart overhead, not steady-state time: excluded from the
            # goodput window, reported separately in goodput_detail.
            goodput.exclude(restore_time_s, "restore")
            start_step = int(state.step)
            last_saved = start_step
            # Legacy (pre-cursor) checkpoints: every step consumes exactly
            # one batch from a stream that starts at 0, so the step count
            # is the correct cursor for any run this trainer produced.
            consumed = int(cursor.get("batches_consumed", start_step))

        # Preemption handling: SIGTERM/SIGINT (and a pending GCE
        # maintenance event, when polling is on) set the stop reason; the
        # loop notices at the next step boundary and takes the
        # emergency-checkpoint path.
        if threading.current_thread() is threading.main_thread():
            def _on_signal(signum, frame):
                name = signal.Signals(signum).name
                if stop["reason"] is None:
                    stop["reason"] = ("sigint" if signum == signal.SIGINT
                                      else "sigterm")
                    print(f"trainer: caught {name}; emergency checkpoint "
                          "at the next step boundary", flush=True)
            for sig in (signal.SIGTERM, signal.SIGINT):
                restore_sigs.append((sig, signal.signal(sig, _on_signal)))
        if job.maintenance_poll_s > 0:
            poller_stop = threading.Event()
            poller_wait = poller_stop

            def _poll_maintenance():
                from runbooks_tpu.cloud import metadata

                while not poller_wait.wait(job.maintenance_poll_s):
                    try:
                        event = metadata.maintenance_event()
                    except Exception:  # noqa: BLE001 — flake != stop
                        continue
                    if event and stop["reason"] is None:
                        stop["reason"] = "maintenance"
                        print(f"trainer: GCE maintenance event {event!r}; "
                              "emergency checkpoint at the next step "
                              "boundary", flush=True)
                        return

            threading.Thread(target=_poll_maintenance,
                             name="rbt-maintenance", daemon=True).start()

        batches = _batches(job, model_cfg, skip=consumed)
        if job.prefetch_depth > 0:
            # Async input pipeline: tokenize/pack runs ahead on a
            # background thread and batches land on device (sharded
            # device_put) while the previous step computes — host work
            # overlaps device compute instead of serializing with it
            # inside the step loop.
            batches = prefetcher = data_mod.Prefetcher(
                batches, depth=job.prefetch_depth,
                place=data_mod.device_placer(mesh))
        t_start = time.perf_counter()
        with jax.set_mesh(mesh):
            for i in range(start_step, job.steps):
                if _fault_due(i, "kill"):
                    raise SimulatedFault(
                        f"RBT_FAULT_INJECT: simulated death at step {i}")
                if _fault_due(i, "sigterm"):
                    os.kill(os.getpid(), signal.SIGTERM)
                if stop["reason"]:
                    exit_reason = stop["reason"]
                    break
                if job.profile_stop > job.profile_start \
                        and i == job.profile_start and not profiling_at:
                    PROFILER.start(os.path.join(artifacts, "profile"))
                    profiling = True
                if profile_at is not None and i == profile_at[0] \
                        and not (profiling or profiling_at):
                    PROFILER.start(os.path.join(
                        artifacts, "profiles", f"step{profile_at[0]}"))
                    profiling_at = True
                t_data = time.perf_counter()
                with span("data_wait", step=i):
                    batch = next(batches)
                    consumed += 1
                    if prefetcher is None:
                        batch = {k: np.asarray(v) for k, v in batch.items()}
                data_wait_s = time.perf_counter() - t_data
                if _fault_due(i, "nonfinite"):
                    batch = dict(batch)
                    batch["loss_mask"] = batch["loss_mask"] * float("nan")
                t_step = time.perf_counter()
                # The first step folds this run's intended XLA compile:
                # with a colocated component already steady (a serve
                # engine sharing the process), it must not read as a
                # stall. Later steps run unwrapped — a compile THERE is
                # exactly what the sentinel exists to catch.
                expected_cm = (obs_device.SENTINEL.expected()
                               if i == start_step
                               else contextlib.nullcontext())
                with span("step", step=i), expected_cm:
                    if lora_mode:
                        state, metrics = step_fn(state, base_params, batch)
                    else:
                        state, metrics = step_fn(state, batch)
                step_s = time.perf_counter() - t_step
                _check_nonfinite(pending_nf)
                pending_nf = (i, metrics.get("nonfinite"))
                if i == start_step:
                    # The first step folds the XLA compile; pulling the
                    # loss waits for it, then the throughput window resets
                    # so tokens/sec and MFU report steady-state compute
                    # (compile time lands in its own field). The whole
                    # window is restart/startup overhead for goodput.
                    float(metrics["loss"])
                    compile_time_s = time.perf_counter() - t_start
                    goodput.exclude(compile_time_s, "compile")
                    # Compile phase over: from here a compile in the step
                    # loop is a stall the sentinel flags loudly.
                    obs_device.SENTINEL.mark_steady("train")
                    if os.environ.get("RBT_DEVICE_OBS", "1") != "0":
                        # Roofline attribution of the step program: FLOPs
                        # + HBM bytes from the lowering's cost_analysis
                        # (a re-trace, no second backend compile) — the
                        # analytic cross-check for the wall-clock MFU.
                        # The re-trace is startup overhead like the
                        # compile itself: excluded from goodput's window.
                        t_cost = time.perf_counter()
                        args = ((state, base_params, batch) if lora_mode
                                else (state, batch))
                        device_cost = obs_device.cost_analysis_of(
                            step_fn, *args)
                        if device_cost is not None:
                            device_cost.update(obs_device.classify_roofline(
                                device_cost["flops"],
                                device_cost["hbm_bytes"]))
                            obs_device.PROGRAMS.record_cost(
                                "train", "train_step",
                                f"b{job.batch_size}s{job.seq_len}",
                                device_cost)
                        goodput.exclude(
                            time.perf_counter() - t_cost, "compile")
                    t_start = time.perf_counter()
                else:
                    tokens_done += tokens_per_step
                if profiling and i + 1 == job.profile_stop:
                    jax.block_until_ready(metrics["loss"])
                    PROFILER.stop()
                    profiling = False
                if profiling_at \
                        and i + 1 == profile_at[0] + profile_at[1]:
                    jax.block_until_ready(metrics["loss"])
                    PROFILER.stop()
                    profiling_at = False
                is_log = (i + 1) % job.log_every == 0 or i + 1 == job.steps
                if is_log:
                    # Only log points sync on the device (float pulls the
                    # scalar); between them steps dispatch async with
                    # metrics buffered as device arrays. The sync wait is
                    # device compute finishing — step time, not overhead.
                    t_sync = time.perf_counter()
                    loss = float(metrics["loss"])
                    t_synced = time.perf_counter()
                    dt = t_synced - t_start
                    if i != start_step:
                        step_s += t_synced - t_sync
                ckpt_s = 0.0
                if (i + 1) % job.checkpoint_every == 0 or i + 1 == job.steps:
                    t_ckpt = time.perf_counter()
                    # expected(): checkpoint plumbing may compile small
                    # host programs; that is not a step-loop stall.
                    with span("checkpoint", step=i + 1), \
                            obs_device.SENTINEL.expected():
                        ckpt.save(i + 1, state,
                                  cursor={"batches_consumed": consumed})
                    ckpt_s = time.perf_counter() - t_ckpt
                    last_saved = i + 1
                if i != start_step:
                    # Per-step breakdown: registry histograms + the
                    # goodput accumulator. The compile step is excluded
                    # wholesale above — recording it here too would count
                    # the same seconds twice.
                    goodput.step(step_s, data_wait_s, ckpt_s)
                    REGISTRY.observe(
                        "train_step_seconds", step_s,
                        help_text="Per-step compute wall time (dispatch "
                                  "+ device sync share).")
                    REGISTRY.observe(
                        "train_data_wait_seconds", data_wait_s,
                        help_text="Per-step input-pipeline wait.")
                    if ckpt_s:
                        REGISTRY.observe(
                            "train_checkpoint_seconds", ckpt_s,
                            help_text="Blocking checkpoint save time.")
                    win["data"] += data_wait_s
                    win["step"] += step_s
                    win["ckpt"] += ckpt_s
                    win["steps"] += 1
                if is_log:
                    if tokens_done:
                        tps = tokens_done / max(dt, 1e-9)
                    else:  # single measured step: only the compile window
                        tps = tokens_per_step / max(compile_time_s, 1e-9)
                    achieved = tps * flops_per_token
                    entry = {"step": i + 1, "loss": round(loss, 4),
                             "tokens_per_sec": round(tps, 1),
                             "tflops_per_sec": round(achieved / 1e12, 2)}
                    if peak_flops:
                        entry["mfu"] = round(achieved / peak_flops, 4)
                    if not history and compile_time_s is not None:
                        entry["compile_time_s"] = round(compile_time_s, 2)
                    if win["steps"]:
                        # Step-time breakdown (window means) + running
                        # goodput: the is-it-input-bound answer, on every
                        # log line instead of behind a debugger.
                        entry["data_wait_s"] = round(
                            win["data"] / win["steps"], 4)
                        entry["step_s"] = round(
                            win["step"] / win["steps"], 4)
                        if win["ckpt"]:
                            entry["ckpt_s"] = round(
                                win["ckpt"] / win["steps"], 4)
                        entry["goodput"] = round(goodput.ratio(), 4)
                        REGISTRY.set_gauge(
                            "train_goodput_ratio", entry["goodput"],
                            help_text="Productive step time / wall clock "
                                      "(restart overhead excluded).")
                    # Progress gauges: what the controller's fleet
                    # scraper folds into Model .status.telemetry
                    # (step/loss/goodput on `rbt get`).
                    REGISTRY.set_gauge(
                        "train_step", i + 1,
                        help_text="Last completed training step.")
                    REGISTRY.set_gauge(
                        "train_loss", round(loss, 6),
                        help_text="Loss at the last logged step.")
                    # Per-step HBM watermark (device_memory_* gauges;
                    # absent on CPU where memory_stats() is None) and the
                    # analytic-MFU cross-check from the step program's
                    # cost_analysis.
                    hbm_now = max(
                        (m.get("bytes_in_use", 0)
                         for m in obs_device.set_memory_gauges()),
                        default=0)
                    if hbm_now:
                        entry["hbm_used_bytes"] = hbm_now
                        hbm_peak_bytes = max(hbm_peak_bytes, hbm_now)
                    if device_cost and win["steps"] and peak_flops:
                        entry["analytic_mfu"] = round(
                            device_cost["flops"]
                            / (win["step"] / win["steps"]) / peak_flops, 4)
                        REGISTRY.set_gauge(
                            "train_analytic_mfu", entry["analytic_mfu"],
                            help_text="cost_analysis FLOPs / measured "
                                      "step time / peak — the analytic "
                                      "cross-check of the wall-clock "
                                      "MFU.")
                    obs_device.PROGRAMS.set_gauges(component="train")
                    win = {"data": 0.0, "step": 0.0, "ckpt": 0.0,
                           "steps": 0}
                    history.append(entry)
                    print(json.dumps(entry), flush=True)
                    _write_metrics()
            if exit_reason is None:
                _check_nonfinite(pending_nf)
            else:
                # Emergency checkpoint: the work since the last periodic
                # save must survive the preemption. Carries the data
                # cursor like every save; force=True overwrites a same-step
                # periodic save if the stop landed right after one.
                step_now = int(state.step)
                if step_now != last_saved:
                    with span("emergency_save", step=step_now,
                              reason=exit_reason), \
                            obs_device.SENTINEL.expected():
                        ckpt.save(step_now, state,
                                  cursor={"batches_consumed": consumed},
                                  force=True)
                obs_trace.instant("preempted", reason=exit_reason,
                                  step=step_now)
                print(json.dumps({"preempted": exit_reason,
                                  "emergency_checkpoint_step": step_now}),
                      flush=True)
    finally:
        if prefetcher is not None:
            prefetcher.close()
        if poller_stop is not None:
            poller_stop.set()
        # This run's steady claim dies with it: a follow-up run (resume,
        # tests, a second job in-process) recompiles legitimately.
        obs_device.SENTINEL.clear_steady("train")
        # Async-checkpoint cleanup belongs HERE: an exception mid-run must
        # not leave the orbax save thread dangling with a half-written step
        # directory (wait() also stamps the integrity markers; close()
        # releases the manager even if wait itself blows up). Signal
        # handlers restore only AFTER the saves land — a SIGTERM during
        # the final wait must not kill the process mid-save (observed: a
        # kernel-default 143 death leaving an orbax tmp dir).
        try:
            try:
                ckpt.wait()
            finally:
                ckpt.close()
        finally:
            for sig, old in restore_sigs:
                signal.signal(sig, old)
            # Flush the run's trace file — live spans (RBT_TRACE=1) or
            # tail-sampled/incident promotions may have opened it (the
            # writer reopens in append mode if anything traces after
            # this).
            obs_trace.close()

    if profiling or profiling_at:  # profile window ran past the last step
        PROFILER.stop()
    summary = _summary_dict()
    _write_metrics(summary)
    if lora_mode:
        # Export merged params reference for serving (artifact contract).
        merged_note = {"note": "merged weights = base + lora; see checkpoints"}
        with open(os.path.join(artifacts, "lora.json"), "w") as f:
            json.dump(dataclasses.asdict(job.lora) | merged_note, f)
    return summary


def exit_code_for(summary: Dict[str, Any]) -> int:
    """Container exit code for a finished run: EXIT_PREEMPTED (42) when the
    run stopped for a preemption-shaped reason (SIGTERM/SIGINT/maintenance
    event, after its emergency checkpoint), 0 otherwise. The controller's
    train-Job podFailurePolicy restarts on 42 but fails the Job on any
    other non-zero code (docs/fault-tolerance.md)."""
    if summary.get("exit_reason") in ("sigterm", "sigint", "maintenance"):
        return EXIT_PREEMPTED
    return 0


def main() -> int:
    params = contract.load_params()
    job = TrainJobConfig.from_params(params)
    # Metrics exposition for the controller's fleet scraper: RBT_METRICS_PORT
    # (injected by the Model reconciler's Job template) serves the shared
    # registry — train_step/train_loss/goodput + the step histograms — on
    # GET /metrics. Env-gated so library callers of run_training never bind
    # a port.
    metrics_port = int(os.environ.get("RBT_METRICS_PORT", "0") or 0)
    if metrics_port:
        from runbooks_tpu.obs.metrics import serve_metrics

        serve_metrics(metrics_port)
    if job.maintenance_poll_s == 0 and "maintenance_poll_s" not in params:
        # Container entry point on GCE: watch for maintenance events /
        # preemptions by default (a quick single-attempt probe — an off-GCE
        # box must not stall startup on a dead metadata address).
        from runbooks_tpu.cloud import metadata

        if metadata.on_gce(timeout=0.5, attempts=1):
            job = dataclasses.replace(job, maintenance_poll_s=5.0)
    summary = run_training(job)
    print(json.dumps({"done": True, **{k: v for k, v in summary.items()
                                       if k != "history"}}))
    return exit_code_for(summary)


if __name__ == "__main__":
    raise SystemExit(main())
