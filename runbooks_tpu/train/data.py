"""Input pipeline: tokenize -> pack -> batch, feeding the sharded train step.

The reference's data story is "a Dataset job writes files to
/content/artifacts, the trainer container reads /content/data" (reference:
docs/container-contract.md; internal/controller/dataset_controller.go). This
module is the trainer-side half: it reads jsonl/text files (as mounted at
/content/data), tokenizes, and packs multiple documents per row with
segment_ids/positions so the model's packed-sequence masking keeps documents
isolated (no cross-contamination, no padding waste — the TPU-efficient way to
fine-tune on variable-length data).

Host-side is pure numpy (prefetch-friendly); device placement happens in the
trainer with the mesh's batch shardings.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
)

import numpy as np

Batch = Dict[str, np.ndarray]


class ByteTokenizer:
    """Dependency-free byte-level tokenizer (hermetic default: works with no
    downloaded vocab). ids 0..255 = bytes, 256 = BOS, 257 = EOS."""

    bos_id = 256
    eos_id = 257
    vocab_size = 258

    def encode(self, text: str, add_bos: bool = True,
               add_eos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


def load_tokenizer(name_or_path: Optional[str] = None,
                   allow_byte_fallback: bool = False):
    """Tokenizer for training/serving. No path -> the hermetic byte
    tokenizer (the zero-download default). A PATH that fails to load
    RAISES: silently swapping a requested HF vocab for the 258-symbol byte
    fallback changes the token space under the model — a trainer would
    quietly produce garbage and a server would decode gibberish behind a
    healthy readiness probe. Pass allow_byte_fallback=True to opt back
    into the old degrade-silently behavior (smoke setups only)."""
    if not name_or_path:
        return ByteTokenizer()
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(
            name_or_path, local_files_only=True)
    except Exception as exc:
        if allow_byte_fallback:
            print(f"data: tokenizer {name_or_path!r} failed to load "
                  f"({exc!r}); falling back to the byte tokenizer",
                  flush=True)
            return ByteTokenizer()
        raise RuntimeError(
            f"tokenizer {name_or_path!r} could not be loaded (is the path "
            "mounted and complete? local_files_only=True — no hub "
            "downloads). Pass allow_byte_fallback=True to serve the byte "
            f"tokenizer instead: {exc}") from exc


def read_documents(path: str, text_key: str = "text",
                   prompt_template: Optional[str] = None) -> Iterator[str]:
    """Yield documents from a file or directory: .jsonl ({text_key: ...} per
    line), .txt (one doc per file), or a directory of either.

    prompt_template renders each jsonl record through str.format (e.g.
    "## Instruction\\n{prompt}\\n## Response:\\n{completion}") — the analog
    of the reference trainer images' prompt_template param
    (reference: examples/falcon-7b-instruct/finetuned-model-custom-prompt
    .yaml); records missing a referenced field are skipped."""
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            yield from read_documents(os.path.join(path, name), text_key,
                                      prompt_template)
        return
    if path.endswith((".jsonl", ".json")):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                obj = json.loads(line)
                if prompt_template is not None and isinstance(obj, dict):
                    try:
                        text = prompt_template.format(**obj)
                    except (KeyError, IndexError):
                        continue
                else:
                    text = obj.get(text_key)
                if text:
                    yield text
    elif path.endswith(".txt"):
        with open(path) as f:
            yield f.read()


def pack_documents(
    token_docs: Iterable[Sequence[int]],
    seq_len: int,
    drop_remainder: bool = False,
) -> Iterator[Dict[str, np.ndarray]]:
    """Greedy-pack token documents into rows of seq_len+1 tokens.

    Each yielded row dict has (all [seq_len]):
      tokens, targets (next-token), segment_ids (1-based per doc, 0 = pad),
      positions (restart per doc), loss_mask (0 on pad).
    Documents longer than the row are split across rows (their continuation
    keeps advancing positions so long docs still train full-context).
    """
    row_toks: List[int] = []
    row_segs: List[int] = []
    row_pos: List[int] = []
    seg = 0

    def flush():
        nonlocal row_toks, row_segs, row_pos, seg
        n = seq_len + 1
        toks = row_toks[:n]
        segs = row_segs[:n]
        pos = row_pos[:n]
        pad = n - len(toks)
        if pad:
            toks += [0] * pad
            segs += [0] * pad
            pos += [0] * pad
        row = {
            "tokens": np.asarray(toks[:-1], np.int32),
            "targets": np.asarray(toks[1:], np.int32),
            "segment_ids": np.asarray(segs[:-1], np.int32),
            "positions": np.asarray(pos[:-1], np.int32),
            # A target is trainable iff it belongs to the same (non-pad)
            # segment as its input token (no loss across doc boundaries).
            "loss_mask": np.asarray(
                [1.0 if segs[i] != 0 and segs[i] == segs[i + 1] else 0.0
                 for i in range(seq_len)], np.float32),
        }
        row_toks, row_segs, row_pos = row_toks[n:], row_segs[n:], row_pos[n:]
        if row_toks:
            # continuation of a split document: positions keep counting
            seg += 1
            row_segs = [seg] * len(row_toks)
        return row

    for doc in token_docs:
        doc = list(doc)
        if not doc:
            continue
        seg += 1
        row_toks += doc
        row_segs += [seg] * len(doc)
        row_pos += list(range(len(doc)))
        while len(row_toks) >= seq_len + 1:
            yield flush()
    if row_toks and not drop_remainder:
        yield flush()


def batch_rows(rows: Iterator[Dict[str, np.ndarray]],
               batch_size: int,
               drop_remainder: bool = True) -> Iterator[Batch]:
    buf: List[Dict[str, np.ndarray]] = []
    for row in rows:
        buf.append(row)
        if len(buf) == batch_size:
            yield {k: np.stack([r[k] for r in buf]) for k in buf[0]}
            buf = []
    if buf and not drop_remainder:
        while len(buf) < batch_size:  # pad with empty rows
            buf.append({k: np.zeros_like(v) for k, v in buf[0].items()})
        yield {k: np.stack([r[k] for r in buf]) for k in buf[0]}


def dataset(
    path: str,
    seq_len: int,
    batch_size: int,
    tokenizer=None,
    epochs: Optional[int] = 1,
    text_key: str = "text",
    prompt_template: Optional[str] = None,
) -> Iterator[Batch]:
    """End-to-end: files -> packed, batched numpy batches. epochs=None loops
    forever."""
    tokenizer = tokenizer or ByteTokenizer()
    epoch = 0
    while epochs is None or epoch < epochs:
        docs = (tokenizer.encode(t)
                for t in read_documents(path, text_key, prompt_template))
        yield from batch_rows(pack_documents(docs, seq_len), batch_size)
        epoch += 1


def skip_batches(it: Iterator[Batch], n: int) -> Iterator[Batch]:
    """Advance ``it`` past its first ``n`` batches — the resume half of the
    checkpoint data cursor (docs/fault-tolerance.md): a run restored at a
    checkpoint that had consumed n batches must see batch n first, exactly
    as the uninterrupted run would, instead of replaying the dataset from
    document 0. Draining re-runs tokenize/pack on the host (deterministic,
    no device work); batches a prefetcher had in flight beyond the cursor
    at preemption time are simply regenerated."""
    it = iter(it)
    for _ in range(n):
        try:
            next(it)
        except StopIteration:  # finite dataset shorter than the cursor
            break
    return it


class Prefetcher:
    """Bounded background-thread prefetcher: overlap host-side batch
    production (tokenize/pack — everything upstream in the iterator) and,
    via ``place``, the host-to-device transfer with device compute.

    The producer thread pulls from ``it``, applies ``place`` (typically
    ``jax.device_put`` with the mesh batch shardings — JAX transfers are
    thread-safe and async), and parks results in a queue of ``depth``
    slots. depth=2 double-buffers: while the device crunches step i, batch
    i+1 is already on device and batch i+2 is being packed. The training
    loop then never blocks on ``next(batches)`` host work — the
    host/device serialization the TPU-scaling literature flags as a
    first-order loss once the matmuls are sharded.

    Semantics:
      - ordering: batches come out in iterator order (FIFO queue);
      - termination: exhaustion of ``it`` ends iteration (StopIteration);
        ``close()`` stops the producer and joins the thread (also called
        by ``__exit__`` and safe to call twice);
      - errors: an exception in the iterator or in ``place`` is re-raised
        in the consumer at the position it occurred, after all batches
        produced before it.
    """

    _DONE = object()

    def __init__(self, it: Iterator[Batch], depth: int = 2,
                 place: Optional[Callable[[Batch], Any]] = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._queue: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, args=(iter(it), place),
            name="rbt-prefetch", daemon=True)
        self._thread.start()

    def _produce(self, it, place):
        try:
            while not self._stop.is_set():
                try:
                    item = next(it)
                except StopIteration:
                    break
                if place is not None:
                    item = place(item)
                if not self._put(item):
                    return
            self._put(self._DONE)
        except BaseException as exc:  # re-raised on the consumer side
            self._put(exc)

    def _put(self, item) -> bool:
        """Blocking put that gives up when close() is requested."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._stop.is_set():
                raise StopIteration
            try:
                item = self._queue.get(timeout=0.1)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # Producer died without a sentinel (shouldn't happen,
                    # but never hang the train loop on it).
                    raise StopIteration
        if item is self._DONE:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def close(self) -> None:
        self._stop.set()
        # Drain so a producer blocked on a full queue observes the stop.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def device_placer(mesh, rules=None):
    """Batch -> sharded device batch for Prefetcher(place=...): lazily
    builds the mesh batch shardings from the first batch's shapes, then
    ``jax.device_put``s every batch (async H2D; double-buffered by the
    prefetch queue)."""
    import jax

    from runbooks_tpu.train.step import batch_shardings

    holder: Dict[str, Any] = {}

    def place(batch: Batch):
        if "shardings" not in holder:
            shapes = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), batch)
            holder["shardings"] = batch_shardings(mesh, shapes, rules)
        return jax.device_put(batch, holder["shardings"])

    return place


def synthetic_batches(vocab_size: int, seq_len: int, batch_size: int,
                      seed: int = 0) -> Iterator[Batch]:
    """Random-token batches for benchmarks and smoke tests."""
    rng = np.random.default_rng(seed)
    while True:
        toks = rng.integers(1, vocab_size, (batch_size, seq_len + 1),
                            dtype=np.int32)
        yield {
            "tokens": toks[:, :-1],
            "targets": toks[:, 1:],
            "loss_mask": np.ones((batch_size, seq_len), np.float32),
        }
