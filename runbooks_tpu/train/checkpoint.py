"""Checkpoint/resume on the container contract's artifact layout.

The reference delegates checkpointing entirely to user containers, providing
only a durable bucket mounted RW at /content/artifacts (reference:
internal/controller/model_controller.go:348-357, docs/design.md "bucket as
source of truth"; SURVEY.md §5.4). Here it is first-class: orbax checkpoints
under ``{artifacts}/checkpoints/{step}``, async by default (training continues
while the previous step uploads), resume = restore latest.

Sharding-aware: restore takes the target TrainState shardings, so a
checkpoint written on one mesh layout restores onto another (orbax reshards).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin orbax wrapper bound to an artifact directory."""

    def __init__(self, artifacts_dir: str, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = os.path.join(os.path.abspath(artifacts_dir),
                                      "checkpoints")
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        return self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of ``state_like`` (a TrainState
        of jax.ShapeDtypeStruct with .sharding set, or a concrete state)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        def as_abstract(x):
            if isinstance(x, jax.Array):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            return x
        abstract = jax.tree.map(as_abstract, state_like)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def wait(self) -> None:
        """Block until in-flight async saves land (call before exit)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
