"""Checkpoint/resume on the container contract's artifact layout.

The reference delegates checkpointing entirely to user containers, providing
only a durable bucket mounted RW at /content/artifacts (reference:
internal/controller/model_controller.go:348-357, docs/design.md "bucket as
source of truth"; SURVEY.md §5.4). Here it is first-class: orbax checkpoints
under ``{artifacts}/checkpoints/{step}``, async by default (training continues
while the previous step uploads), resume = restore latest.

Sharding-aware: restore takes the target TrainState shardings, so a
checkpoint written on one mesh layout restores onto another (orbax reshards).

Fault-tolerant (docs/fault-tolerance.md): every completed save is stamped
with an integrity marker (``rbt-intact.json``) that also carries the data-
pipeline cursor, so a preemption mid-async-save leaves a step directory
restore can *recognize* as partial and skip — ``restore`` walks backward to
the newest intact checkpoint instead of dying on the corrupt latest. The
cursor payload is plain JSON next to the arrays: it survives restoring onto
a different mesh untouched (orbax only reshards the arrays).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin orbax wrapper bound to an artifact directory."""

    # Written inside a step directory once its (possibly async) save has
    # fully landed; absence marks the directory as partial (preemption or
    # crash mid-save). Lives inside the step dir so orbax's max_to_keep
    # garbage collection removes it together with the arrays.
    MARKER = "rbt-intact.json"

    def __init__(self, artifacts_dir: str, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = os.path.join(os.path.abspath(artifacts_dir),
                                      "checkpoints")
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )
        # step -> cursor dict for saves whose marker is not yet written
        # (async saves finalize on the next save()/wait()).
        self._pending: Dict[int, dict] = {}

    # -- integrity markers + cursor ------------------------------------

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(int(step)))

    def _marker_path(self, step: int) -> str:
        return os.path.join(self._step_dir(step), self.MARKER)

    def _finalize_pending(self) -> None:
        """Stamp the marker for every landed save (call only after
        wait_until_finished — a marker on a still-writing dir would defeat
        its purpose)."""
        for step, cursor in list(self._pending.items()):
            step_dir = self._step_dir(step)
            if os.path.isdir(step_dir):
                tmp = self._marker_path(step) + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"step": step, "cursor": cursor}, f)
                os.replace(tmp, self._marker_path(step))
            self._pending.pop(step, None)

    def intact_steps(self) -> list:
        """Ascending steps whose save completed (marker present)."""
        steps = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return steps
        for name in names:
            if name.isdigit() and os.path.exists(self._marker_path(int(name))):
                steps.append(int(name))
        return sorted(steps)

    def read_cursor(self, step: int) -> dict:
        """Data-pipeline cursor saved alongside ``step`` ({} when absent or
        unreadable — legacy checkpoints predate the marker)."""
        try:
            with open(self._marker_path(step)) as f:
                return dict(json.load(f).get("cursor") or {})
        except (OSError, ValueError):
            return {}

    # -- save/restore ---------------------------------------------------

    def save(self, step: int, state: Any, force: bool = False,
             cursor: Optional[dict] = None) -> bool:
        """Save ``state`` at ``step``; ``cursor`` (a small JSON-able dict,
        e.g. {"batches_consumed": n}) is stamped into the integrity marker
        once the save lands, so resume can continue the data stream
        step-exactly instead of replaying it from the start."""
        # Let any in-flight async save land and stamp its marker before
        # starting the next one (orbax serializes the saves regardless).
        self._mgr.wait_until_finished()
        self._finalize_pending()
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force)
        if saved:
            self._pending[int(step)] = dict(cursor or {})
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def latest_intact_step(self) -> Optional[int]:
        """Newest step whose save completed; falls back to orbax's latest
        for pre-marker (legacy) checkpoint directories."""
        steps = self.intact_steps()
        if steps:
            return steps[-1]
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of ``state_like`` (a TrainState
        of jax.ShapeDtypeStruct with .sharding set, or a concrete state).

        With step=None, restores the newest *intact* checkpoint and falls
        back to older ones when the latest is partial or corrupt (e.g. a
        preemption mid-async-save truncated it)."""
        return self.restore_with_cursor(state_like, step)[0]

    def restore_with_cursor(self, state_like: Any,
                            step: Optional[int] = None,
                            ) -> Tuple[Any, dict, int]:
        """Like ``restore`` but returns (state, cursor, restored_step) so
        the trainer can resume its data pipeline at the exact batch the
        checkpointed step had consumed."""
        def as_abstract(x):
            if isinstance(x, jax.Array):
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=x.sharding)
            return x
        abstract = jax.tree.map(as_abstract, state_like)
        if step is not None:
            state = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
            return state, self.read_cursor(step), int(step)

        all_steps = sorted(int(s) for s in self._mgr.all_steps())
        if not all_steps:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        marked = set(self.intact_steps())
        # Prefer intact checkpoints, newest first; when nothing is marked
        # (legacy layout) try everything newest-first anyway.
        candidates = sorted((s for s in all_steps if s in marked),
                            reverse=True) or sorted(all_steps, reverse=True)
        skipped = [s for s in all_steps if s > candidates[0]]
        if skipped:
            print(f"checkpoint: ignoring partial step dir(s) {skipped} "
                  "(no integrity marker — interrupted save); restoring "
                  f"step {candidates[0]}", flush=True)
        last_exc: Optional[Exception] = None
        for s in candidates:
            try:
                state = self._mgr.restore(
                    s, args=ocp.args.StandardRestore(abstract))
            except Exception as exc:  # noqa: BLE001 — corrupt/partial step
                print(f"checkpoint: step {s} failed to restore ({exc!r}); "
                      "falling back to the previous checkpoint", flush=True)
                last_exc = exc
                continue
            return state, self.read_cursor(s), s
        raise RuntimeError(
            f"no checkpoint under {self.directory} could be restored "
            f"(tried {candidates})") from last_exc

    def wait(self) -> None:
        """Block until in-flight async saves land (call before exit), then
        stamp their integrity markers."""
        self._mgr.wait_until_finished()
        self._finalize_pending()

    def close(self) -> None:
        self._mgr.close()
        self._finalize_pending()
