"""Optimizer construction (optax) with the standard LLM fine-tune recipe.

AdamW + linear warmup + cosine decay + global-norm clipping. Kept as plain
optax so the optimizer state is a pytree that shards with the same FSDP rules
as the params (runbooks_tpu.parallel.sharding).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import optax


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    learning_rate: float = 2e-5
    warmup_steps: int = 100
    total_steps: int = 1000
    schedule: str = "cosine"          # "cosine" | "linear" | "constant"
    min_lr_ratio: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.0
    grad_clip_norm: Optional[float] = 1.0
    # Dtype for the adam first moment (mu). None keeps the param dtype
    # (f32 masters -> f32 mu). "bfloat16" halves mu bytes — measured on the
    # bench-410m shapes the f32 masters+moments are the 5 GB that force
    # full remat (BENCH_NOTES r3); bf16 mu is the first of the three
    # state-memory levers (mu dtype, param dtype, state sharding).
    mu_dtype: Optional[str] = None


def make_schedule(cfg: OptimizerConfig) -> optax.Schedule:
    warmup = optax.linear_schedule(0.0, cfg.learning_rate,
                                   max(cfg.warmup_steps, 1))
    decay_steps = max(cfg.total_steps - cfg.warmup_steps, 1)
    if cfg.schedule == "cosine":
        decay = optax.cosine_decay_schedule(
            cfg.learning_rate, decay_steps, alpha=cfg.min_lr_ratio)
    elif cfg.schedule == "linear":
        decay = optax.linear_schedule(
            cfg.learning_rate, cfg.learning_rate * cfg.min_lr_ratio, decay_steps)
    else:
        decay = optax.constant_schedule(cfg.learning_rate)
    return optax.join_schedules([warmup, decay], [cfg.warmup_steps])


def make_optimizer(cfg: OptimizerConfig) -> optax.GradientTransformation:
    chain = []
    if cfg.grad_clip_norm is not None:
        chain.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    chain.append(
        optax.adamw(
            learning_rate=make_schedule(cfg),
            b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
            weight_decay=cfg.weight_decay,
            mu_dtype=cfg.mu_dtype,
        )
    )
    return optax.chain(*chain)
