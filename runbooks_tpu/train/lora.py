"""LoRA fine-tuning (the reference's headline example is a Llama-2-7B LoRA-style
HF fine-tune — reference: examples/llama2-7b/finetuned-model.yaml; here LoRA is
a first-class, TPU-sharded implementation).

Formulation: for each target matrix W [*, in, out], learn A [*, in, r] and
B [*, r, out]; the effective weight is W + (alpha/r) * A @ B. Training merges
on the fly inside the loss (XLA fuses the small matmuls; grads flow only to
A/B), so the base params stay frozen and can even live in bf16. ``merge``
folds the deltas into the base weights for serving/export.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Any

# Matrices eligible for LoRA, by their path inside params["layers"].
DEFAULT_TARGETS = ("attn.wq", "attn.wk", "attn.wv", "attn.wo")
ALL_TARGETS = DEFAULT_TARGETS + ("mlp.wi_gate", "mlp.wi_up", "mlp.wi", "mlp.wo")


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Sequence[str] = DEFAULT_TARGETS

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _get(tree: Params, dotted: str):
    node = tree
    for part in dotted.split("."):
        if part not in node:
            return None
        node = node[part]
    return node


def init_lora(params: Params, cfg: LoraConfig, rng: jax.Array) -> Params:
    """LoRA params matching the model's stacked-layer layout:
    {target: {"a": [L, in, r], "b": [L, r, out]}}. A ~ N(0, 1/in), B = 0
    (standard init: delta starts at zero)."""
    lora: Dict[str, Dict[str, jax.Array]] = {}
    keys = jax.random.split(rng, len(cfg.targets))
    for key, target in zip(keys, cfg.targets):
        w = _get(params["layers"], target)
        if w is None:
            continue
        L, d_in, d_out = w.shape
        lora[target] = {
            "a": (jax.random.normal(key, (L, d_in, cfg.rank)) * d_in ** -0.5
                  ).astype(w.dtype),
            "b": jnp.zeros((L, cfg.rank, d_out), w.dtype),
        }
    if not lora:
        raise ValueError(f"no LoRA targets matched: {cfg.targets}")
    return lora


def lora_logical_axes(cfg: LoraConfig, params: Params) -> Params:
    """Logical axes for LoRA params: rank axis replicated, in/out axes follow
    the base matrix convention (embed/heads/mlp)."""
    base_axes = {
        "attn.wq": ("embed", "heads"), "attn.wk": ("embed", "kv_heads"),
        "attn.wv": ("embed", "kv_heads"), "attn.wo": ("heads", "embed"),
        "mlp.wi_gate": ("embed", "mlp"), "mlp.wi_up": ("embed", "mlp"),
        "mlp.wi": ("embed", "mlp"), "mlp.wo": ("mlp", "embed"),
    }
    axes: Dict[str, Dict[str, tuple]] = {}
    for target in params:
        in_ax, out_ax = base_axes.get(target, (None, None))
        axes[target] = {"a": (None, in_ax, None), "b": (None, None, out_ax)}
    return axes


def apply_lora(params: Params, lora: Params, cfg: LoraConfig) -> Params:
    """Base params with LoRA deltas folded in (lazily, inside jit)."""
    layers = dict(params["layers"])

    def fold(node: Params, path: Tuple[str, ...]):
        out = {}
        for k, v in node.items():
            sub_path = path + (k,)
            dotted = ".".join(sub_path)
            if isinstance(v, dict):
                out[k] = fold(v, sub_path)
            elif dotted in lora:
                ab = jnp.einsum(
                    "lir,lro->lio", lora[dotted]["a"], lora[dotted]["b"],
                    preferred_element_type=jnp.float32,
                )
                out[k] = (v.astype(jnp.float32)
                          + cfg.scale * ab).astype(v.dtype)
            else:
                out[k] = v
        return out

    new_params = dict(params)
    new_params["layers"] = fold(layers, ())
    return new_params


merge = apply_lora  # serving/export alias: returns fully-merged params


def trainable_param_count(lora: Params) -> int:
    import numpy as np

    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(lora))


# ---------------------------------------------------------------------------
# Sharded LoRA training (base frozen, only A/B in the optimizer)
# ---------------------------------------------------------------------------

def create_lora_train_state(model_cfg, lora_cfg: LoraConfig, base_params,
                            optimizer, mesh, rng, rules=None):
    """Sharded TrainState whose params are the LoRA tree only. Returns
    (state, state_shardings)."""
    import jax.numpy as jnp
    from runbooks_tpu.train.step import (
        TrainState,
        infer_state_shardings,
        layout_invariant_init,
    )

    def init_fn(rng):
        lora = init_lora(base_params, lora_cfg, rng)
        return TrainState(step=jnp.zeros((), jnp.int32), params=lora,
                          opt_state=optimizer.init(lora))

    state_shapes = jax.eval_shape(init_fn, rng)
    axes = lora_logical_axes(lora_cfg, state_shapes.params)
    shardings = infer_state_shardings(axes, state_shapes, mesh, rules)
    with jax.set_mesh(mesh), layout_invariant_init():
        state = jax.jit(init_fn, out_shardings=shardings)(rng)
    return state, shardings


def make_lora_train_step(model_cfg, lora_cfg: LoraConfig, optimizer, mesh,
                         state_shardings, base_shardings, remat: bool = True,
                         accumulate_steps: int = 1, loss_chunk: int = 0):
    """jit'ed (state, base_params, batch) -> (state, metrics); grads flow only
    to the LoRA tree, base stays frozen (and may be bf16).

    accumulate_steps/loss_chunk mirror make_train_step: k-microbatch
    gradient accumulation with an f32 accumulator, and the chunked fused
    cross-entropy that never materializes [b, s, vocab] logits (the merge
    happens per microbatch inside the differentiated graph either way)."""
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from runbooks_tpu.train.step import (
        TrainState,
        accumulated_value_and_grad,
        make_ce_terms,
    )

    k = int(accumulate_steps)
    if k < 1:
        raise ValueError(f"accumulate_steps must be >= 1, got {k}")
    ce_terms = make_ce_terms(model_cfg, remat, int(loss_chunk))

    def step_fn(state: "TrainState", base_params, batch):
        # Closures capture base_params per trace (construction is free at
        # trace time — no mutable state shared across traces).
        def lora_ce_terms(lora, mb):
            merged = apply_lora(base_params, lora, lora_cfg)
            loss, total, aux = ce_terms(merged, mb)
            if model_cfg.moe_num_experts and k == 1:
                # Same objective as full fine-tuning: keep routing balanced
                # while adapting (train/step.py does the same). The k>1
                # path adds the aux term inside accumulated_value_and_grad.
                loss = loss + model_cfg.moe_aux_coef * aux
            return loss, total, aux

        if k > 1:
            (loss, total), grads = accumulated_value_and_grad(
                model_cfg, lora_ce_terms, k)(state.params, batch)
        else:
            def loss_fn(lora):
                loss, total, _ = lora_ce_terms(lora, batch)
                return loss, total

            (loss, total), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_lora = optax.apply_updates(state.params, updates)
        grad_norm = optax.global_norm(grads)
        # Non-finite guard, same contract as make_train_step: a bad batch
        # skips the update (LoRA params + opt state bitwise unchanged) and
        # flags the step for the trainer's consecutive-bad-step abort.
        ok = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
        new_lora, new_opt = jax.tree.map(
            lambda new, old: jnp.where(ok, new, old),
            (new_lora, new_opt), (state.params, state.opt_state))
        metrics = {"loss": loss, "grad_norm": grad_norm,
                   "weight_tokens": total,
                   "nonfinite": (~ok).astype(jnp.int32)}
        return TrainState(step=state.step + 1, params=new_lora,
                          opt_state=new_opt), metrics

    replicated = NamedSharding(mesh, P())
    return jax.jit(
        step_fn,
        in_shardings=(state_shardings, base_shardings, None),
        out_shardings=(state_shardings, replicated),
        donate_argnums=(0,),
    )
