"""Dataset-loader workload: fetch raw data into the artifact store.

The TPU-native replacement for the reference's external dataset images
(reference: examples/datasets/k8s-instructions.yaml pulls
substratusai/images//dataset-loader-http, squad.yaml a prebuilt
dataset-squad image). Runs under the container contract as the Dataset
reconciler's ``{name}-data-loader`` Job:

  params.json: {"urls": "https://... , https://...",   # comma or list
                "paths": ["/some/local.jsonl"],        # pre-mounted files
                "text_key": "text"}                    # jsonl field to keep

Each source is copied to /content/artifacts (the Dataset's bucket prefix,
mounted RW). Downstream Model jobs mount that prefix RO at /content/data and
feed it to train.data's tokenize->pack pipeline. A dataset.json manifest
records what was loaded (row/byte counts) — the analog of the reference
images' load logs, but machine-readable.
"""

from __future__ import annotations

import json
import os
import shutil
import urllib.parse
import urllib.request

from runbooks_tpu.utils import contract


def _sources(params_cfg: dict) -> list:
    urls = params_cfg.get("urls", [])
    if isinstance(urls, str):
        urls = [u.strip() for u in urls.split(",") if u.strip()]
    return list(urls) + list(params_cfg.get("paths", []))


def _fetch(src: str, dest_dir: str) -> str:
    """Download/copy one source into dest_dir; returns the local filename."""
    name = os.path.basename(urllib.parse.urlparse(src).path) or "data"
    dest = os.path.join(dest_dir, name)
    if src.startswith(("http://", "https://", "file://")):
        with urllib.request.urlopen(src, timeout=120) as resp, \
                open(dest, "wb") as out:
            shutil.copyfileobj(resp, out)
    else:
        shutil.copy(src, dest)
    return dest


def _count_rows(path: str, text_key: str) -> int:
    if not path.endswith((".jsonl", ".json", ".txt")):
        return 0
    rows = 0
    with open(path, "rb") as f:
        for line in f:
            if not path.endswith(".jsonl"):
                rows += 1
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(doc, dict) and text_key in doc:
                rows += 1
    return rows


def main() -> int:
    params_cfg = contract.load_params()
    artifacts = params_cfg.get("artifacts_dir") or contract.artifacts_dir()
    os.makedirs(artifacts, exist_ok=True)
    text_key = params_cfg.get("text_key", "text")

    sources = _sources(params_cfg)
    if not sources:
        raise SystemExit("dataset_loader: no 'urls' or 'paths' in params")

    files = []
    for src in sources:
        dest = _fetch(src, artifacts)
        files.append({
            "source": src,
            "file": os.path.basename(dest),
            "bytes": os.path.getsize(dest),
            "rows": _count_rows(dest, text_key),
        })

    manifest = {"files": files, "text_key": text_key,
                "total_bytes": sum(f["bytes"] for f in files),
                "total_rows": sum(f["rows"] for f in files)}
    with open(os.path.join(artifacts, "dataset.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    print(json.dumps({"done": True, **manifest}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
