"""Sharded train state + train step.

The whole step (fwd, bwd, optimizer) is one jit'ed function over the mesh;
XLA inserts all collectives (FSDP all-gathers, TP all-reduces, gradient
reduce-scatters) from the sharding annotations — there is no hand-written
communication here (SURVEY.md §2a: the reference has no distributed backend;
this is the TPU-native equivalent, XLA collectives over ICI/DCN).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from runbooks_tpu.models.config import ModelConfig
from runbooks_tpu.models.transformer import forward, init_params, param_logical_axes
from runbooks_tpu.parallel.sharding import spec_for_array

Params = Any
Batch = Dict[str, jax.Array]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: Params
    opt_state: Any


def cross_entropy_loss(
    logits: jax.Array,        # [b, s, v] float32
    targets: jax.Array,       # [b, s] int32
    weights: Optional[jax.Array] = None,  # [b, s] float {0,1} loss mask
) -> Tuple[jax.Array, jax.Array]:
    """Returns (mean loss over weighted tokens, total weight).

    This is the reference (parity-oracle) loss: it consumes fully
    materialized [b, s, v] f32 logits. The training fast path uses
    ``chunked_cross_entropy`` below, which never builds that tensor; this
    function is what the chunked path is tested against (the same role
    gpipe plays for 1f1b).
    """
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if weights is None:
        weights = jnp.ones_like(nll)
    weights = weights.astype(jnp.float32)
    total = jnp.maximum(jnp.sum(weights), 1.0)
    return jnp.sum(nll * weights) / total, total


def chunked_cross_entropy(
    acts: jax.Array,          # [b, s, d] post-final-norm activations
    head: jax.Array,          # [d, v] head weights (embed.T when tied)
    targets: jax.Array,       # [b, s] int32
    weights: Optional[jax.Array] = None,  # [b, s] float {0,1} loss mask
    chunk_size: int = 256,
    compute_dtype: Any = jnp.bfloat16,
) -> Tuple[jax.Array, jax.Array]:
    """Fused chunked softmax cross-entropy: (mean loss, total weight).

    Numerically equivalent to ``cross_entropy_loss(acts @ head, ...)`` but
    the [b, s, v] f32 logits tensor is never materialized: the sequence is
    processed in chunks of ``chunk_size`` tokens by a ``lax.scan`` whose
    body computes [b, c, v] chunk logits (bf16 operands, f32 accumulation
    — same dtype contract as the head einsum in models/transformer.py),
    reduces them to a stabilized log-sum-exp plus the target logit, and
    accumulates the weighted NLL sum. The body is ``jax.checkpoint``-ed so
    the backward re-forms each chunk's logits instead of the scan stacking
    [n_chunks, b, c, v] residuals — peak logits memory is O(b * c * v) in
    both passes. At llama vocab (32k) and s=2048 this is the difference
    between a 256 MB-per-sample tensor held twice and a ~32x smaller
    rolling buffer, which is what lets the accumulation path below raise
    the global batch.
    """
    b, s, _ = acts.shape
    if weights is None:
        weights = jnp.ones((b, s), jnp.float32)
    weights = weights.astype(jnp.float32)
    total = jnp.maximum(jnp.sum(weights), 1.0)

    c = max(1, min(int(chunk_size), s))
    n = -(-s // c)
    pad = n * c - s
    if pad:
        # Zero-weight padding tokens: they contribute exactly 0 to the sum.
        acts = jnp.pad(acts, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        weights = jnp.pad(weights, ((0, 0), (0, pad)))

    # [b, n*c, ...] -> [n, b, c, ...] so scan walks sequence chunks.
    a_ch = acts.reshape(b, n, c, acts.shape[-1]).transpose(1, 0, 2, 3)
    t_ch = targets.reshape(b, n, c).transpose(1, 0, 2)
    w_ch = weights.reshape(b, n, c).transpose(1, 0, 2)

    def body(nll_sum, xs):
        a_c, t_c, w_c = xs
        logits = jnp.einsum(
            "bch,hv->bcv", a_c.astype(compute_dtype),
            head.astype(compute_dtype),
            preferred_element_type=jnp.float32)
        # Online (per-chunk) max/log-sum-exp; the max shift is pure
        # stabilization, so no gradient flows through it.
        m = jax.lax.stop_gradient(
            jnp.max(logits, axis=-1, keepdims=True))
        lse = jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1)) + m[..., 0]
        tgt = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
        return nll_sum + jnp.sum((lse - tgt) * w_c), None

    nll_sum, _ = jax.lax.scan(
        jax.checkpoint(body), jnp.zeros((), jnp.float32),
        (a_ch, t_ch, w_ch))
    return nll_sum / total, total


def infer_state_shardings(axes: Any, state_shapes: TrainState,
                          mesh: Mesh, rules=None) -> TrainState:
    """Shardings for a full TrainState given the params' logical-axes tree.

    Optimizer moments (adam mu/nu) have the same tree *suffix* paths as the
    params they track, so each state leaf is matched to a param's logical axes
    by its longest dict-key suffix; unmatched leaves (counts, scalars)
    replicate.
    """
    flat_axes: Dict[Tuple[str, ...], tuple] = {}
    def record(path, leaf):
        keys = tuple(k.key for k in path
                     if isinstance(k, jax.tree_util.DictKey))
        flat_axes[keys] = leaf
        return leaf
    jax.tree_util.tree_map_with_path(
        record, axes,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x))

    def assign(path, leaf):
        keys = tuple(k.key for k in path
                     if isinstance(k, jax.tree_util.DictKey))
        for i in range(len(keys) + 1):
            logical = flat_axes.get(keys[i:])
            if logical is not None and len(logical) <= len(leaf.shape):
                return NamedSharding(
                    mesh, spec_for_array(leaf.shape, logical, mesh, rules))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(assign, state_shapes)


def batch_shardings(mesh: Mesh, batch_shapes, rules=None) -> Any:
    def one(s):
        logical = ("batch", "seq") if len(s.shape) == 2 else ("batch",)
        return NamedSharding(mesh, spec_for_array(s.shape, logical, mesh, rules))
    return jax.tree.map(one, batch_shapes)


@contextlib.contextmanager
def layout_invariant_init():
    """Make sharded jitted init independent of the device layout.

    The non-partitionable threefry lowering (this jax version's default)
    generates different random bits when ``jax.random.normal`` runs under
    ``jit(..., out_shardings=...)`` on different mesh layouts — the
    carried ROADMAP bug where d2f2t2/d4t2 initial params diverged from
    dp8/fsdp8 by enough for a 0.75% step-1 loss delta
    (tests/test_train_step.py::test_mesh_layouts_agree_numerically).
    The partitionable threefry lowering computes each element's bits from
    its *global* index, so every layout materializes the same values
    while still initializing shard-local (no single-host OOM on large
    models). Scoped to the init call: the flag is part of jit's trace
    key, so the train step itself is untouched.

    The scope also marks its compiles as *expected* for the compile
    sentinel (obs/device.py): a sharded init is by definition an
    intentional startup compile, and must not page an operator when it
    runs in a process where another component (a colocated serve
    engine) already declared itself steady.

    The flag flip is THREAD-LOCAL (jax config State context manager)
    whenever this jax exposes it: a colocated engine decoding on its
    worker thread must not see its jit cache key change mid-request (a
    recompile = serve-time stall). The process-global update is only
    the fallback for jax builds without the context-manager API.
    """
    from runbooks_tpu.obs import device as obs_device

    try:
        from jax._src.config import threefry_partitionable as _tp_state

        ctx = _tp_state(True)
    except (ImportError, AttributeError, TypeError):
        ctx = None
    with obs_device.SENTINEL.expected():
        if ctx is not None:
            with ctx:
                yield
        else:
            prev = jax.config.jax_threefry_partitionable
            jax.config.update("jax_threefry_partitionable", True)
            try:
                yield
            finally:
                jax.config.update("jax_threefry_partitionable", prev)


def create_train_state(
    cfg: ModelConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    rng: jax.Array,
    rules=None,
) -> Tuple[TrainState, TrainState]:
    """Initialize a sharded TrainState directly on the mesh.

    Returns (state, state_shardings). Init happens inside jit with
    out_shardings so large models materialize already sharded (no single-host
    OOM); the partitionable-threefry scope makes the values identical on
    every mesh layout (see layout_invariant_init).
    """

    def init_fn(rng):
        params = init_params(cfg, rng)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
        )

    state_shapes = jax.eval_shape(init_fn, rng)
    shardings = infer_state_shardings(param_logical_axes(cfg), state_shapes,
                                      mesh, rules)
    with jax.set_mesh(mesh), layout_invariant_init():
        state = jax.jit(init_fn, out_shardings=shardings)(rng)
    return state, shardings


def make_ce_terms(cfg: ModelConfig, remat: bool, loss_chunk: int):
    """(params, batch) -> (mean CE loss, total weight, MoE aux).

    loss_chunk > 0 selects the fused chunked path: the forward returns
    [b, s, d] activations (return_activations=True) and
    ``chunked_cross_entropy`` consumes them with the head weights, so the
    [b, s, vocab] f32 logits tensor never exists. loss_chunk == 0 is the
    reference path (full logits + ``cross_entropy_loss``), kept as the
    parity oracle. Shared by the full and LoRA train steps.
    """

    def ce_terms(params, batch: Batch):
        if loss_chunk:
            acts, _, aux = forward(
                cfg, params, batch["tokens"],
                positions=batch.get("positions"),
                segment_ids=batch.get("segment_ids"),
                remat=remat, with_aux=True, return_activations=True)
            head = (params["embed"].T if cfg.tie_embeddings
                    else params["head"])
            loss, total = chunked_cross_entropy(
                acts, head, batch["targets"], batch.get("loss_mask"),
                chunk_size=loss_chunk, compute_dtype=cfg.activation_dtype)
        else:
            logits, _, aux = forward(
                cfg, params, batch["tokens"],
                positions=batch.get("positions"),
                segment_ids=batch.get("segment_ids"),
                remat=remat, with_aux=True)
            loss, total = cross_entropy_loss(
                logits, batch["targets"], batch.get("loss_mask"))
        return loss, total, aux

    return ce_terms


def accumulated_value_and_grad(cfg: ModelConfig, ce_terms, k: int):
    """(params, batch) -> ((loss, total_weight), grads) over k microbatches.

    The [b, s] batch is viewed as [k, b/k, s]; a ``lax.scan`` runs
    fwd+bwd per microbatch and accumulates gradients into an f32
    accumulator (cast back to the param dtype at the end — bf16 params
    still accumulate exactly). Peak activation memory is that of ONE
    microbatch, which is what lets a fixed memory budget run a k-times
    larger global batch.

    Exactness: the full-batch loss is sum(nll*w)/total_w over the whole
    batch, so each microbatch contributes its *unnormalized* NLL sum
    scaled by the global 1/total_w (total_w is a function of the batch
    only, computed outside the grad). The k partial losses and gradients
    then sum to exactly the single-large-batch values; the MoE aux term
    (a nonlinear per-batch statistic) is averaged over microbatches.
    """

    def value_and_grad(params, batch: Batch):
        b = batch["tokens"].shape[0]
        if b % k:
            raise ValueError(
                f"accumulate_steps={k} must divide batch size {b}")
        micro = jax.tree.map(
            lambda a: a.reshape((k, b // k) + a.shape[1:]), batch)
        lm = batch.get("loss_mask")
        full_w = (jnp.sum(lm.astype(jnp.float32)) if lm is not None
                  else jnp.asarray(
                      float(b * batch["tokens"].shape[1]), jnp.float32))
        total_weight = jnp.maximum(full_w, 1.0)

        def micro_loss(p, mb):
            loss, total, aux = ce_terms(p, mb)
            # mean -> sum/global-total: partial losses sum to the
            # full-batch loss (see docstring).
            out = loss * total / total_weight
            if cfg.moe_num_experts:
                out = out + cfg.moe_aux_coef * aux / k
            return out

        grad_fn = jax.value_and_grad(micro_loss)

        def acc_body(carry, mb):
            loss_acc, grads_acc = carry
            loss_i, g_i = grad_fn(params, mb)
            grads_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), grads_acc, g_i)
            return (loss_acc + loss_i, grads_acc), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (loss, grads_f32), _ = jax.lax.scan(
            acc_body, (jnp.zeros((), jnp.float32), zeros), micro)
        grads = jax.tree.map(lambda p, g: g.astype(p.dtype),
                             params, grads_f32)
        return (loss, total_weight), grads

    return value_and_grad


def make_train_step(
    cfg: ModelConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    state_shardings: TrainState,
    rules=None,
    remat: bool = True,
    accumulate_steps: int = 1,
    loss_chunk: int = 0,
):
    """Build the jit'ed train step: (state, batch) -> (state, metrics).

    Batch keys: tokens [b,s], targets [b,s], and optional loss_mask [b,s],
    segment_ids [b,s], positions [b,s].

    accumulate_steps=k splits the batch into k microbatches scanned with a
    donated f32 gradient accumulator (one optimizer step per call; peak
    activation memory of one microbatch). loss_chunk=c computes the loss
    via the chunked fused cross-entropy (never materializing [b, s, vocab]
    logits; see chunked_cross_entropy). Both are ignored on the 1f1b
    pipeline path, which already microbatches and never builds full-batch
    logits — accumulate_steps>1 there raises (use
    cfg.pipeline_microbatches instead).
    """

    n_stages = int(mesh.shape.get("stage", 1))
    use_1f1b = n_stages > 1 and cfg.pipeline_schedule == "1f1b"
    if cfg.pipeline_schedule not in ("1f1b", "gpipe"):
        raise ValueError(
            f"unknown pipeline_schedule {cfg.pipeline_schedule!r}; "
            "expected 1f1b|gpipe")
    k = int(accumulate_steps)
    if k < 1:
        raise ValueError(f"accumulate_steps must be >= 1, got {k}")
    if use_1f1b and k > 1:
        raise ValueError(
            "accumulate_steps > 1 is redundant under the 1f1b pipeline "
            "schedule (it already runs per-microbatch fwd/bwd); set "
            "cfg.pipeline_microbatches instead")

    ce_terms = make_ce_terms(cfg, remat, int(loss_chunk))
    acc_grad_fn = accumulated_value_and_grad(cfg, ce_terms, k) if k > 1 \
        else None

    def step_fn(state: TrainState, batch: Batch):
        if use_1f1b:
            # Explicit-backward pipeline: in-flight activations bounded by
            # O(stages), no full-batch logits (models/transformer.py:
            # loss_and_grads_1f1b). The gpipe schedule below is the
            # autodiff oracle it is tested against.
            from runbooks_tpu.models.transformer import loss_and_grads_1f1b

            loss, grads, total_weight = loss_and_grads_1f1b(
                cfg, state.params, batch["tokens"], batch["targets"],
                batch.get("loss_mask"),
                positions=batch.get("positions"),
                segment_ids=batch.get("segment_ids"))
        elif acc_grad_fn is not None:
            (loss, total_weight), grads = acc_grad_fn(state.params, batch)
        else:
            def loss_fn(params):
                loss, total, aux = ce_terms(params, batch)
                if cfg.moe_num_experts:
                    loss = loss + cfg.moe_aux_coef * aux
                return loss, total

            (loss, total_weight), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        grad_norm = optax.global_norm(grads)
        # Non-finite guard (docs/fault-tolerance.md): a poisoned batch or a
        # numeric blow-up must not write NaN into the params — the update is
        # skipped wholesale (params AND optimizer state bitwise unchanged,
        # step counter still advances) and the step is flagged in metrics so
        # the trainer can count consecutive bad steps and abort.
        ok = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
        new_params, new_opt_state = jax.tree.map(
            lambda new, old: jnp.where(ok, new, old),
            (new_params, new_opt_state), (state.params, state.opt_state))
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "weight_tokens": total_weight,
            "nonfinite": (~ok).astype(jnp.int32),
        }
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt_state), metrics

    replicated = NamedSharding(mesh, P())
    return jax.jit(
        step_fn,
        in_shardings=(state_shardings, None),
        out_shardings=(state_shardings, replicated),
        donate_argnums=(0,),
    )
