"""Sharded train state + train step.

The whole step (fwd, bwd, optimizer) is one jit'ed function over the mesh;
XLA inserts all collectives (FSDP all-gathers, TP all-reduces, gradient
reduce-scatters) from the sharding annotations — there is no hand-written
communication here (SURVEY.md §2a: the reference has no distributed backend;
this is the TPU-native equivalent, XLA collectives over ICI/DCN).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from runbooks_tpu.models.config import ModelConfig
from runbooks_tpu.models.transformer import forward, init_params, param_logical_axes
from runbooks_tpu.parallel.sharding import spec_for_array

Params = Any
Batch = Dict[str, jax.Array]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: Params
    opt_state: Any


def cross_entropy_loss(
    logits: jax.Array,        # [b, s, v] float32
    targets: jax.Array,       # [b, s] int32
    weights: Optional[jax.Array] = None,  # [b, s] float {0,1} loss mask
) -> Tuple[jax.Array, jax.Array]:
    """Returns (mean loss over weighted tokens, total weight)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if weights is None:
        weights = jnp.ones_like(nll)
    weights = weights.astype(jnp.float32)
    total = jnp.maximum(jnp.sum(weights), 1.0)
    return jnp.sum(nll * weights) / total, total


def infer_state_shardings(axes: Any, state_shapes: TrainState,
                          mesh: Mesh, rules=None) -> TrainState:
    """Shardings for a full TrainState given the params' logical-axes tree.

    Optimizer moments (adam mu/nu) have the same tree *suffix* paths as the
    params they track, so each state leaf is matched to a param's logical axes
    by its longest dict-key suffix; unmatched leaves (counts, scalars)
    replicate.
    """
    flat_axes: Dict[Tuple[str, ...], tuple] = {}
    def record(path, leaf):
        keys = tuple(k.key for k in path
                     if isinstance(k, jax.tree_util.DictKey))
        flat_axes[keys] = leaf
        return leaf
    jax.tree_util.tree_map_with_path(
        record, axes,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x))

    def assign(path, leaf):
        keys = tuple(k.key for k in path
                     if isinstance(k, jax.tree_util.DictKey))
        for i in range(len(keys) + 1):
            logical = flat_axes.get(keys[i:])
            if logical is not None and len(logical) <= len(leaf.shape):
                return NamedSharding(
                    mesh, spec_for_array(leaf.shape, logical, mesh, rules))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(assign, state_shapes)


def batch_shardings(mesh: Mesh, batch_shapes, rules=None) -> Any:
    def one(s):
        logical = ("batch", "seq") if len(s.shape) == 2 else ("batch",)
        return NamedSharding(mesh, spec_for_array(s.shape, logical, mesh, rules))
    return jax.tree.map(one, batch_shapes)


def create_train_state(
    cfg: ModelConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    rng: jax.Array,
    rules=None,
) -> Tuple[TrainState, TrainState]:
    """Initialize a sharded TrainState directly on the mesh.

    Returns (state, state_shardings). Init happens inside jit with
    out_shardings so large models materialize already sharded (no single-host
    OOM).
    """

    def init_fn(rng):
        params = init_params(cfg, rng)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
        )

    state_shapes = jax.eval_shape(init_fn, rng)
    shardings = infer_state_shardings(param_logical_axes(cfg), state_shapes,
                                      mesh, rules)
    with jax.set_mesh(mesh):
        state = jax.jit(init_fn, out_shardings=shardings)(rng)
    return state, shardings


def make_train_step(
    cfg: ModelConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    state_shardings: TrainState,
    rules=None,
    remat: bool = True,
):
    """Build the jit'ed train step: (state, batch) -> (state, metrics).

    Batch keys: tokens [b,s], targets [b,s], and optional loss_mask [b,s],
    segment_ids [b,s], positions [b,s].
    """

    n_stages = int(mesh.shape.get("stage", 1))
    use_1f1b = n_stages > 1 and cfg.pipeline_schedule == "1f1b"
    if cfg.pipeline_schedule not in ("1f1b", "gpipe"):
        raise ValueError(
            f"unknown pipeline_schedule {cfg.pipeline_schedule!r}; "
            "expected 1f1b|gpipe")

    def step_fn(state: TrainState, batch: Batch):
        if use_1f1b:
            # Explicit-backward pipeline: in-flight activations bounded by
            # O(stages), no full-batch logits (models/transformer.py:
            # loss_and_grads_1f1b). The gpipe schedule below is the
            # autodiff oracle it is tested against.
            from runbooks_tpu.models.transformer import loss_and_grads_1f1b

            loss, grads, total_weight = loss_and_grads_1f1b(
                cfg, state.params, batch["tokens"], batch["targets"],
                batch.get("loss_mask"),
                positions=batch.get("positions"),
                segment_ids=batch.get("segment_ids"))
        else:
            def loss_fn(params):
                logits, _, aux = forward(
                    cfg, params, batch["tokens"],
                    positions=batch.get("positions"),
                    segment_ids=batch.get("segment_ids"),
                    remat=remat,
                    with_aux=True,
                )
                loss, total = cross_entropy_loss(
                    logits, batch["targets"], batch.get("loss_mask"))
                if cfg.moe_num_experts:
                    loss = loss + cfg.moe_aux_coef * aux
                return loss, total

            (loss, total_weight), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "weight_tokens": total_weight,
        }
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt_state), metrics

    replicated = NamedSharding(mesh, P())
    return jax.jit(
        step_fn,
        in_shardings=(state_shardings, None),
        out_shardings=(state_shardings, replicated),
        donate_argnums=(0,),
    )
