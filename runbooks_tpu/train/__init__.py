from runbooks_tpu.train.checkpoint import CheckpointManager
from runbooks_tpu.train.lora import LoraConfig, apply_lora, init_lora
from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
from runbooks_tpu.train.step import (
    TrainState,
    create_train_state,
    cross_entropy_loss,
    make_train_step,
)
from runbooks_tpu.train.trainer import TrainJobConfig, run_training

__all__ = ["CheckpointManager", "LoraConfig", "apply_lora", "init_lora",
           "OptimizerConfig", "make_optimizer", "TrainState",
           "create_train_state", "cross_entropy_loss", "make_train_step",
           "TrainJobConfig", "run_training"]
