"""Repo-invariant lint: AST checks for the defect classes that keep
recurring in review (docs/static-analysis.md has the rule catalog).

Every rule here encodes a bug class a past PR actually shipped or caught
in review:

- ``lock-discipline``: attributes annotated ``# guarded-by: <lock>`` at
  their ``__init__`` assignment must only be touched inside a
  ``with self.<lock>:`` block (the EngineWorker/fleet-scraper bug class
  review fixed twice in PR 6/7). A ``# guarded-by: <lock>`` comment on a
  ``def`` line instead marks a *lock-held helper* — a private method the
  class only calls with the lock already held — and the whole body is
  treated as guarded.
- ``async-blocking``: blocking calls (``time.sleep``, subprocess, sync
  urllib/socket, ``Future.result()``) inside ``async def`` freeze the
  whole event loop — every SSE stream and readiness probe with it.
- ``device-sync``: host↔device syncs (``np.asarray`` on device values,
  ``.item()``, ``block_until_ready``, ``jax.device_get``) on the serve/
  train hot paths (``serve/engine.py``, ``train/step.py``). Intentional
  dispatch boundaries carry an inline ignore naming why.
- ``rng-layout``: ``jax.jit(..., out_shardings=...)`` over RNG init
  (``jax.random.*`` / ``init_params`` / ``init_lora``) outside a
  ``layout_invariant_init()`` scope — the exact carried-bug class of
  the non-partitionable threefry lowering (train/step.py).
- ``bare-except``: ``except:`` catches SystemExit/KeyboardInterrupt and
  hides typos; name a type.
- ``swallowed-error``: a broad ``except Exception``/``BaseException``
  whose body is only ``pass``/``continue`` with no comment explaining
  why silence is correct.

Suppression is inline — ``# rbt-check: ignore[<rule>] <reason>`` on the
flagged line (or alone on the line above) — or via
``config/check_baseline.json`` (findings.py). Inline ignores without a
reason are themselves flagged (``ignore-reason``): an unexplained
suppression is how baselines rot.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from runbooks_tpu.analysis.findings import Finding

# Files the device-sync rule audits: the serve decode loop and the train
# step — the two places where an accidental host sync is a per-token /
# per-step stall on TPU.
DEVICE_SYNC_PATHS = ("serve/engine.py", "train/step.py")

# (module, attr) call patterns that block the event loop.
_BLOCKING_MODULE_CALLS = {
    ("time", "sleep"),
    ("os", "system"),
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("socket", "create_connection"),
    ("requests", "get"), ("requests", "post"), ("requests", "put"),
    ("requests", "delete"), ("requests", "request"),
}

_IGNORE_RE = re.compile(
    r"#\s*rbt-check:\s*ignore\[([A-Za-z0-9_,\- ]+)\]\s*(.*)")
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*(?:self\.)?([A-Za-z0-9_]+)")


class _Ignores:
    """Per-file inline suppressions: line -> set of rule ids ('*' = all).
    A comment alone on a line applies to the next line too (for lines too
    long to carry the comment inline)."""

    def __init__(self, source: str):
        self.by_line: Dict[int, Set[str]] = {}
        self.missing_reason: List[Tuple[int, str]] = []
        for i, line in enumerate(source.splitlines(), start=1):
            m = _IGNORE_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            if not m.group(2).strip():
                self.missing_reason.append((i, ",".join(sorted(rules))))
            self.by_line.setdefault(i, set()).update(rules)
            if line.lstrip().startswith("#"):
                # Comment-only line: the suppression targets the next line.
                self.by_line.setdefault(i + 1, set()).update(rules)

    def active(self, line: int, rule: str) -> bool:
        rules = self.by_line.get(line, ())
        return rule in rules or "*" in rules


def _attr_chain(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (attr is None or node.attr == attr))


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

def _guarded_attrs(cls: ast.ClassDef, lines: List[str]) -> Dict[str, str]:
    """attr -> lock name, from `self.X = ...  # guarded-by: <lock>` lines
    anywhere in the class body (conventionally __init__)."""
    guards: Dict[str, str] = {}
    for node in ast.walk(cls):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if not _is_self_attr(t):
                continue
            m = _GUARDED_BY_RE.search(lines[node.lineno - 1])
            if m:
                guards[t.attr] = m.group(1)
    return guards


class _LockVisitor(ast.NodeVisitor):
    """Flags guarded self-attribute accesses outside `with self.<lock>:`."""

    def __init__(self, guards: Dict[str, str], rel: str, ignores: _Ignores,
                 findings: List[Finding]):
        self.guards = guards
        self.rel = rel
        self.ignores = ignores
        self.findings = findings
        self.held: List[str] = []

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func
            chain = _attr_chain(expr)
            if chain and chain.startswith("self."):
                acquired.append(chain[len("self."):])
        self.held.extend(acquired)
        self.generic_visit(node)
        del self.held[len(self.held) - len(acquired):]

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if _is_self_attr(node) and node.attr in self.guards:
            lock = self.guards[node.attr]
            if lock not in self.held \
                    and not self.ignores.active(node.lineno,
                                               "lock-discipline"):
                self.findings.append(Finding(
                    rule="lock-discipline", path=self.rel,
                    line=node.lineno,
                    message=f"self.{node.attr} is `# guarded-by: {lock}` "
                            f"but accessed outside `with self.{lock}:`"))
        self.generic_visit(node)


def _check_locks(tree: ast.Module, rel: str, lines: List[str],
                 ignores: _Ignores, findings: List[Finding]) -> None:
    for cls in (n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)):
        guards = _guarded_attrs(cls, lines)
        if not guards:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue  # construction happens-before any other thread
            v = _LockVisitor(guards, rel, ignores, findings)
            m = _GUARDED_BY_RE.search(lines[fn.lineno - 1])
            if m:
                # Lock-held helper: the def line's annotation asserts the
                # class only calls this with <lock> already held.
                v.held.append(m.group(1))
            v.visit(fn)


# ---------------------------------------------------------------------------
# async-blocking
# ---------------------------------------------------------------------------

class _AsyncVisitor(ast.NodeVisitor):
    def __init__(self, rel: str, ignores: _Ignores,
                 findings: List[Finding]):
        self.rel = rel
        self.ignores = ignores
        self.findings = findings

    # Nested sync defs/lambdas inside an async def typically run in an
    # executor or a worker thread — only the coroutine body itself is
    # audited.
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return

    # A nested async def gets its OWN visitor from _check_async's walk;
    # descending here too would report its findings twice.
    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return

    def _flag(self, node: ast.AST, what: str) -> None:
        if not self.ignores.active(node.lineno, "async-blocking"):
            self.findings.append(Finding(
                rule="async-blocking", path=self.rel, line=node.lineno,
                message=f"{what} inside `async def` blocks the event loop "
                        "(every stream and probe with it); await an async "
                        "equivalent or run_in_executor"))

    def visit_Call(self, node: ast.Call) -> None:
        chain = _attr_chain(node.func)
        if chain:
            parts = tuple(chain.split("."))
            tail2 = parts[-2:] if len(parts) >= 2 else ()
            if tail2 in _BLOCKING_MODULE_CALLS \
                    or parts[:2] == ("urllib", "request"):
                self._flag(node, f"blocking call {chain}()")
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "result" and not node.args:
            # Future.result() blocks; asyncio code awaits wrap_future.
            self._flag(node, ".result()")
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join" and not node.args:
            # A no-positional-arg .join() is a thread join (str.join
            # always takes the iterable); it parks the event loop for
            # the full timeout. Join in an executor.
            self._flag(node, ".join()")
        self.generic_visit(node)


def _check_async(tree: ast.Module, rel: str, ignores: _Ignores,
                 findings: List[Finding]) -> None:
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            v = _AsyncVisitor(rel, ignores, findings)
            for stmt in node.body:
                v.visit(stmt)


# ---------------------------------------------------------------------------
# device-sync
# ---------------------------------------------------------------------------

def _check_device_sync(tree: ast.Module, rel: str, ignores: _Ignores,
                       findings: List[Finding]) -> None:
    if not rel.replace(os.sep, "/").endswith(DEVICE_SYNC_PATHS):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func) or ""
        what = None
        if chain in ("np.asarray", "numpy.asarray", "jax.device_get"):
            what = f"{chain}()"
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("item", "block_until_ready") \
                and not node.args:
            what = f".{node.func.attr}()"
        elif chain == "jax.block_until_ready":
            what = "jax.block_until_ready()"
        if what and not ignores.active(node.lineno, "device-sync"):
            findings.append(Finding(
                rule="device-sync", path=rel, line=node.lineno,
                message=f"{what} on the hot path forces a host↔device "
                        "sync per call; keep syncs at the allowlisted "
                        "dispatch boundaries (inline-ignore with a reason "
                        "if this IS one)"))


# ---------------------------------------------------------------------------
# rng-layout
# ---------------------------------------------------------------------------

_RNG_CALLEES = {"init_params", "init_lora"}


def _calls_rng(fn_node: ast.AST) -> bool:
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func) or ""
        if ".random." in f".{chain}." and chain.startswith(("jax.",
                                                           "random.")):
            return True
        tail = chain.rsplit(".", 1)[-1]
        if tail in _RNG_CALLEES:
            return True
    return False


class _RngVisitor(ast.NodeVisitor):
    """Flags jax.jit(..., out_shardings=...) over RNG-initializing bodies
    outside a `with layout_invariant_init():` scope."""

    def __init__(self, rel: str, ignores: _Ignores,
                 findings: List[Finding]):
        self.rel = rel
        self.ignores = ignores
        self.findings = findings
        self.scoped_depth = 0
        self.local_defs: List[Dict[str, ast.AST]] = [{}]

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.local_defs[-1][node.name] = node
        self.local_defs.append({})
        self.generic_visit(node)
        self.local_defs.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_With(self, node: ast.With) -> None:
        scoped = any(
            (_attr_chain(i.context_expr.func
                         if isinstance(i.context_expr, ast.Call)
                         else i.context_expr) or ""
             ).endswith("layout_invariant_init")
            for i in node.items)
        self.scoped_depth += int(scoped)
        self.generic_visit(node)
        self.scoped_depth -= int(scoped)

    def _target_ast(self, arg: ast.AST) -> Optional[ast.AST]:
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            for scope in reversed(self.local_defs):
                if arg.id in scope:
                    return scope[arg.id]
        return None

    def visit_Call(self, node: ast.Call) -> None:
        chain = _attr_chain(node.func) or ""
        is_jit = chain.endswith(".jit") or chain == "jit"
        has_out = any(k.arg == "out_shardings" for k in node.keywords)
        if is_jit and has_out and node.args and not self.scoped_depth:
            target = self._target_ast(node.args[0])
            if target is not None and _calls_rng(target) \
                    and not self.ignores.active(node.lineno, "rng-layout"):
                self.findings.append(Finding(
                    rule="rng-layout", path=self.rel, line=node.lineno,
                    message="jitted RNG init with out_shardings outside "
                            "layout_invariant_init(): the non-partitionable "
                            "threefry lowering makes the values depend on "
                            "the mesh layout (train/step.py)"))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# bare-except / swallowed-error
# ---------------------------------------------------------------------------

def _broad_except(node: ast.ExceptHandler) -> bool:
    names = []
    t = node.type
    for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
        n = _attr_chain(el) if el is not None else None
        if n:
            names.append(n.rsplit(".", 1)[-1])
    return any(n in ("Exception", "BaseException") for n in names)


def _check_excepts(tree: ast.Module, rel: str, lines: List[str],
                   ignores: _Ignores, findings: List[Finding]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            if not ignores.active(node.lineno, "bare-except"):
                findings.append(Finding(
                    rule="bare-except", path=rel, line=node.lineno,
                    message="bare `except:` also catches SystemExit/"
                            "KeyboardInterrupt and hides typos; name an "
                            "exception type"))
            continue
        if not _broad_except(node):
            continue
        body_is_silent = (
            len(node.body) == 1
            and isinstance(node.body[0], (ast.Pass, ast.Continue)))
        # A justification comment anywhere in the handler (the except
        # line or the body) counts — `pass  # knob absent on older jax`
        # is as deliberate as a comment up on the except line.
        end = max(node.lineno, getattr(node, "end_lineno", node.lineno)
                  or node.lineno)
        has_comment = any("#" in lines[i - 1]
                          for i in range(node.lineno, end + 1)
                          if i - 1 < len(lines))
        if body_is_silent and not has_comment \
                and not ignores.active(node.lineno, "swallowed-error"):
            findings.append(Finding(
                rule="swallowed-error", path=rel, line=node.lineno,
                message="broad except swallows the error with no comment "
                        "saying why silence is correct; narrow the type, "
                        "log it, or justify it on the except line"))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def lint_source(source: str, rel: str) -> List[Finding]:
    """Lint one file's source. `rel` is the repo-relative path (rules like
    device-sync scope on it)."""
    findings: List[Finding] = []
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Finding(rule="syntax", path=rel, line=exc.lineno or 0,
                        message=f"unparseable: {exc.msg}")]
    lines = source.splitlines() or [""]
    ignores = _Ignores(source)
    for line, rules in ignores.missing_reason:
        findings.append(Finding(
            rule="ignore-reason", path=rel, line=line,
            message=f"inline ignore[{rules}] has no reason; say why "
                    "(unexplained suppressions rot into blanket "
                    "allowlists)"))
    _check_locks(tree, rel, lines, ignores, findings)
    _check_async(tree, rel, ignores, findings)
    _check_device_sync(tree, rel, ignores, findings)
    _RngVisitor(rel, ignores, findings).visit(tree)
    _check_excepts(tree, rel, lines, ignores, findings)
    return findings


def lint_paths(root: str, package: str = "runbooks_tpu") -> List[Finding]:
    """Lint every .py file under root/<package>, repo-relative paths."""
    findings: List[Finding] = []
    base = os.path.join(root, package)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            with open(full, encoding="utf-8") as f:
                findings.extend(lint_source(f.read(), rel))
    return findings
