"""Shared findings model for `rbt check` (docs/static-analysis.md).

A finding is one rule violation at one location. Program-contract
findings use a ``program:<component>/<name>`` pseudo-path (there is no
file:line for a jaxpr); lint findings carry repo-relative paths and
1-based lines.

Suppression is two-tier, both requiring a reason:

- inline: a ``# rbt-check: ignore[<rule>] <reason>`` comment on the
  flagged line (handled inside lint.py, where the source is at hand);
- baseline: an entry in ``config/check_baseline.json`` —
  ``{"rule": ..., "path": ..., "contains": ..., "reason": ...}`` —
  matched here. ``contains`` (optional) must be a substring of the
  finding message, so one entry cannot blanket a whole rule.

`rbt check --strict` additionally fails on STALE baseline entries
(suppressions that matched nothing): a fixed violation must take its
suppression with it, or the baseline rots into a blanket allowlist.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str       # e.g. "lock-discipline", "program-callback"
    path: str       # repo-relative file, or "program:<component>/<name>"
    line: int       # 1-based; 0 for program-level findings
    message: str

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Suppression:
    rule: str
    path: str
    reason: str
    contains: Optional[str] = None

    def matches(self, f: Finding) -> bool:
        return (self.rule == f.rule and self.path == f.path
                and (self.contains is None or self.contains in f.message))


def load_baseline(path: str) -> List[Suppression]:
    """Parse config/check_baseline.json. A malformed baseline raises:
    an unreadable suppression list silently suppressing nothing (or
    everything) is worse than a loud failure in CI."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    out: List[Suppression] = []
    for i, entry in enumerate(data.get("suppressions", [])):
        missing = {"rule", "path", "reason"} - set(entry)
        if missing:
            raise ValueError(
                f"{path}: suppression #{i} missing {sorted(missing)} "
                "(every entry needs rule, path, and a reason)")
        out.append(Suppression(rule=entry["rule"], path=entry["path"],
                               reason=entry["reason"],
                               contains=entry.get("contains")))
    return out


def apply_baseline(
    findings: Sequence[Finding], baseline: Sequence[Suppression],
) -> Tuple[List[Finding], List[Finding], List[Suppression]]:
    """(active, suppressed, stale_suppressions)."""
    used: Dict[int, bool] = {i: False for i in range(len(baseline))}
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        hit = None
        for i, s in enumerate(baseline):
            if s.matches(f):
                hit = i
                break
        if hit is None:
            active.append(f)
        else:
            used[hit] = True
            suppressed.append(f)
    stale = [baseline[i] for i, u in used.items() if not u]
    return active, suppressed, stale
