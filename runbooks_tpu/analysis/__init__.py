"""Static program & concurrency auditor behind `rbt check` / `make check`.

Two sides, one findings model (docs/static-analysis.md):

- :mod:`runbooks_tpu.analysis.program` — **program contracts**: the
  registered steady-state programs (engine prefill/decode per
  bucket/view, train step, LoRA step) are traced ABSTRACTLY
  (``jax.make_jaxpr`` over ``ShapeDtypeStruct`` trees — zero device
  arrays, zero XLA backend compiles) and audited for host callbacks,
  silent low-precision→f32 promotions, closure-captured constants, and
  compiled-program-census drift against ``config/program_baseline.json``.
- :mod:`runbooks_tpu.analysis.lint` — **repo-invariant lint**: AST-based
  checks for lock discipline (``# guarded-by:`` annotations), blocking
  calls in ``async def``, device syncs on the serve/train hot paths,
  jitted RNG init without the layout-invariant threefry scope, and
  bare/swallowed exception handlers.

Both report through :mod:`runbooks_tpu.analysis.findings`, with
per-finding suppression via ``config/check_baseline.json`` and inline
``# rbt-check: ignore[rule]`` comments, so the repo ships clean and new
violations fail CI (`make check`).
"""

from runbooks_tpu.analysis.findings import (  # noqa: F401
    Finding,
    Suppression,
    apply_baseline,
    load_baseline,
)


def run_check(*args, **kwargs):  # noqa: D103 — thin lazy re-export
    from runbooks_tpu.analysis.check import run_check as _run

    return _run(*args, **kwargs)
