"""`rbt check` runner: lint + program contracts + baselines, one report.

The contract (docs/static-analysis.md, Makefile `make check`):

- the repo at HEAD is CLEAN — `rbt check --strict` exits 0 with the
  committed baselines;
- every new violation fails CI (active findings -> nonzero);
- --strict additionally fails on STALE baseline suppressions (a fixed
  violation must take its suppression with it) and on any XLA backend
  compile during the program audit (the audit is abstract tracing only;
  a compile means someone snuck real execution into it — verified with
  the PR-7 compile sentinel).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import List, Optional

from runbooks_tpu.analysis.findings import (
    Finding,
    Suppression,
    apply_baseline,
    load_baseline,
)

CHECK_BASELINE = os.path.join("config", "check_baseline.json")
PROGRAM_BASELINE = os.path.join("config", "program_baseline.json")


def find_repo_root(start: Optional[str] = None) -> str:
    """Nearest ancestor holding pyproject.toml (the repo root), so
    `rbt check` works from any cwd inside the checkout."""
    cur = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start or os.getcwd())
        cur = parent


@dataclasses.dataclass
class CheckReport:
    active: List[Finding]
    suppressed: List[Finding]
    stale: List[Suppression]
    census: Optional[dict]
    compiles: int
    seconds: float
    # False when jax.monitoring is unavailable: `compiles == 0` is then
    # VACUOUS, not verified (the PR-7 bench gate learned this the hard
    # way). CI (tools/check_gate.py) fails on it; interactive runs warn.
    monitoring: bool = True

    def exit_code(self, strict: bool = False) -> int:
        if self.active:
            return 1
        if strict and self.stale:
            return 2
        if strict and self.compiles:
            return 4
        return 0


def run_check(root: Optional[str] = None, *, programs: bool = True,
              lint: bool = True,
              write_baseline: bool = False) -> CheckReport:
    """Run both audit sides against the repo at `root`.

    write_baseline=True regenerates config/program_baseline.json from
    the current census instead of diffing against it (use after an
    intentional program-set change, then commit the file)."""
    root = root or find_repo_root()
    t0 = time.perf_counter()
    findings: List[Finding] = []
    census: Optional[dict] = None
    compiles = 0
    monitoring = True
    if lint:
        from runbooks_tpu.analysis.lint import lint_paths

        findings.extend(lint_paths(root))
    if programs:
        # The audit must never execute device code: the sentinel counts
        # backend compiles across it, and --strict fails on any.
        from runbooks_tpu.obs import device as obs_device

        from runbooks_tpu.analysis.program import (
            audit_programs,
            diff_census,
            load_program_baseline,
            write_program_baseline,
        )

        monitoring = obs_device.SENTINEL.install()
        before = obs_device.SENTINEL.total
        census, prog_findings = audit_programs()
        findings.extend(prog_findings)
        compiles = obs_device.SENTINEL.total - before
        baseline_path = os.path.join(root, PROGRAM_BASELINE)
        if write_baseline:
            write_program_baseline(baseline_path, census)
        else:
            findings.extend(diff_census(
                census, load_program_baseline(baseline_path),
                os.path.relpath(baseline_path, root)))
    baseline = load_baseline(os.path.join(root, CHECK_BASELINE))
    active, suppressed, stale = apply_baseline(findings, baseline)
    return CheckReport(active=active, suppressed=suppressed, stale=stale,
                       census=census, compiles=compiles,
                       seconds=time.perf_counter() - t0,
                       monitoring=monitoring)
