"""Program contracts: abstract audit of the registered hot programs.

The steady-state program set — engine prefill/decode/prefix-build per
bucket/view (serve/engine.py's module-level ``make_*_fn`` factories),
the train step, the LoRA step — is traced ABSTRACTLY here:
``jax.eval_shape`` builds ShapeDtypeStruct trees and ``jax.make_jaxpr``
stages each program out. Zero device arrays, zero XLA backend compiles
(`rbt check` asserts this via the PR-7 compile sentinel), so the audit
runs in CI in seconds while covering exactly the bodies the engine jits
(the factories are shared — the engine cannot ship a program this audit
never saw).

Per-program checks on the jaxpr (recursing through pjit/scan/cond/remat
sub-jaxprs):

- **program-callback**: host callbacks (``pure_callback``,
  ``io_callback``, ``jax.debug.print``/``debug_callback``) have no place
  in a steady-state program — each invocation is a device→host round
  trip per dispatch.
- **program-dtype**: a silent low-precision→f32 upcast
  (``convert_element_type``) materializing a tensor above
  ``f32_upcast_bytes`` — the "stray f32 promotion in a bf16 program"
  class. Intentional f32 accumulators (dot_general with
  ``preferred_element_type``, scalar loss/LSE accumulators, norms over
  small activations) stay under the threshold by construction.
- **program-const**: closure-captured constants above ``const_bytes``
  embedded in the jaxpr — they bloat every compile and pin HBM per
  compiled variant (weights must be *arguments*).
- **program-census-drift**: the signature cardinality per program
  (buckets × row counts, decode views, auto-prefix splice set) and the
  per-program flags must match ``config/program_baseline.json`` —
  the compiled-program census is a budget, and silent growth is a
  compile-time regression nobody notices until readiness stalls
  (arXiv:2011.03641's compilation-discipline lesson). Regenerate with
  ``rbt check --write-baseline`` when growth is intentional.

Static-shape discipline is asserted structurally: every traced aval must
have a concrete integer shape (no dynamic dims).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import math
import os
from typing import Any, Dict, List, Optional, Tuple

from runbooks_tpu.analysis.findings import Finding

# Dtypes whose silent widening to f32 we audit.
LOW_PRECISION = {"bfloat16", "float16", "int8", "uint8", "int4", "uint4"}


@dataclasses.dataclass(frozen=True)
class AuditSettings:
    """Shapes the audit traces at. Small on purpose — the contracts under
    test (callbacks, promotions, constants, census cardinality) are
    shape-independent, and small shapes keep intentional f32 accumulators
    (norm/LSE upcasts) under the byte thresholds so only genuinely large
    silent promotions flag."""
    config: str = "debug"
    max_slots: int = 2
    decode_chunk: int = 2
    # Speculative verify window (serve/engine.py make_verify_fn): the
    # audit traces the verify factories at this K — the max reachable
    # shape, matching the backend default (utils/hw.backend_tuning).
    draft_tokens: int = 4
    # Multi-tenant LoRA pool (serve/lora_pool.py): the adapter-aware
    # program variants are audited at this pool size and rank bucket —
    # the max shapes a pooled engine ships (docs/multi-tenant-lora.md).
    adapter_pool: int = 2
    lora_rank: int = 8
    batch: int = 2
    seq: int = 64
    f32_upcast_bytes: int = 1 << 20   # 1 MiB
    const_bytes: int = 1 << 20        # 1 MiB


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def _sub_jaxprs(value: Any):
    import jax

    if isinstance(value, jax.core.ClosedJaxpr):
        yield value.jaxpr, list(value.consts)
    elif isinstance(value, jax.core.Jaxpr):
        yield value, []
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _sub_jaxprs(v)


def iter_jaxprs(closed) -> List[Tuple[Any, List[Any]]]:
    """Every (jaxpr, consts) pair reachable from a ClosedJaxpr, including
    pjit/scan/while/cond/checkpoint bodies."""
    out: List[Tuple[Any, List[Any]]] = [(closed.jaxpr,
                                         list(closed.consts))]
    stack = [closed.jaxpr]
    while stack:
        jaxpr = stack.pop()
        for eqn in jaxpr.eqns:
            for sub, consts in [
                    p for v in eqn.params.values() for p in _sub_jaxprs(v)]:
                out.append((sub, consts))
                stack.append(sub)
    return out


def _source_hint(eqn) -> str:
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return (f" (traced at "
                    f"{os.path.basename(frame.file_name)}:"
                    f"{frame.start_line})")
    except Exception:  # noqa: BLE001 — the hint is decorative
        pass
    return ""


def audit_jaxpr(closed, program: str,
                settings: AuditSettings) -> Tuple[List[Finding], dict]:
    """Content checks over one program's closed jaxpr. Returns
    (findings, flags) with flags = {callbacks, f32_upcasts,
    const_bytes_max} — the numbers the census baseline pins."""
    path = f"program:{program}"
    findings: List[Finding] = []
    callbacks = 0
    upcasts = 0
    const_max = 0
    for jaxpr, consts in iter_jaxprs(closed):
        for var, const in zip(jaxpr.constvars, consts):
            nbytes = getattr(const, "nbytes", None)
            if nbytes is None:
                size = getattr(const, "size", 0) or 0
                item = getattr(getattr(const, "dtype", None),
                               "itemsize", 1)
                nbytes = int(size) * int(item)
            const_max = max(const_max, int(nbytes))
            if nbytes >= settings.const_bytes:
                findings.append(Finding(
                    rule="program-const", path=path, line=0,
                    message=f"closure-captured constant of {nbytes} bytes "
                            f"(shape {getattr(const, 'shape', '?')}) "
                            "embedded in the jaxpr — it bloats every "
                            "compile and pins HBM per variant; pass it as "
                            "an argument"))
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if "callback" in name:
                callbacks += 1
                findings.append(Finding(
                    rule="program-callback", path=path, line=0,
                    message=f"host callback `{name}` in a steady-state "
                            "program — a device→host round trip per "
                            f"dispatch{_source_hint(eqn)}"))
                continue
            if name != "convert_element_type" or not eqn.invars:
                continue
            in_aval = getattr(eqn.invars[0], "aval", None)
            out_aval = getattr(eqn.outvars[0], "aval", None)
            if in_aval is None or out_aval is None:
                continue
            if str(getattr(in_aval, "dtype", "")) not in LOW_PRECISION:
                continue
            if str(getattr(out_aval, "dtype", "")) != "float32":
                continue
            nbytes = int(math.prod(out_aval.shape)) * 4
            if nbytes >= settings.f32_upcast_bytes:
                upcasts += 1
                findings.append(Finding(
                    rule="program-dtype", path=path, line=0,
                    message=f"silent {in_aval.dtype}→float32 upcast "
                            f"materializing {nbytes} bytes "
                            f"(shape {tuple(out_aval.shape)})"
                            f"{_source_hint(eqn)}; accumulate explicitly "
                            "(preferred_element_type) or keep the tensor "
                            "in the low dtype"))
        for var in list(jaxpr.invars) + list(jaxpr.outvars):
            shape = getattr(getattr(var, "aval", None), "shape", ())
            if not all(isinstance(d, int) for d in shape):
                findings.append(Finding(
                    rule="program-shape", path=path, line=0,
                    message=f"non-static dimension in {shape}: the "
                            "engine's compiled-program census assumes "
                            "static shapes everywhere"))
    return findings, {"callbacks": callbacks, "f32_upcasts": upcasts,
                      "const_bytes_max": const_max}


# ---------------------------------------------------------------------------
# The audited program set
# ---------------------------------------------------------------------------

def _key_sds():
    import jax

    return jax.eval_shape(lambda: jax.random.key(0))


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _engine_specs(settings: AuditSettings) -> List[dict]:
    """(name, fn, args, signatures) for the serve engine's program set —
    built from the same module-level factories and bucket helpers the
    engine itself uses (serve/engine.py)."""
    import jax.numpy as jnp

    from runbooks_tpu.models.config import get_config
    from runbooks_tpu.models.transformer import KVCache, init_params
    from runbooks_tpu.serve.engine import (
        _buckets,
        auto_prefix_plens,
        bucket_for,
        make_decode_fn,
        make_prefill_fn,
        make_prefix_build_fn,
        make_verify_fn,
        view_buckets_for,
    )
    import jax

    cfg = get_config(settings.config)
    max_seq_len = cfg.max_seq_len
    cache_len = max_seq_len + 1
    slots = settings.max_slots
    buckets = _buckets(max_seq_len)
    views = view_buckets_for(max_seq_len)
    rows_set = (1, slots) if slots > 1 else (1,)

    key = _key_sds()
    params = jax.eval_shape(functools.partial(init_params, cfg), key)
    pool = jax.eval_shape(lambda: KVCache.create(
        cfg, slots, max_seq_len, trash_slot=True, quantize_kv=False))

    def prefill_args(rows: int, bucket: int, plen: int = 0):
        args = [params, pool,
                _sds((rows, bucket), jnp.int32),
                _sds((rows, bucket), jnp.int32),
                _sds((rows,), jnp.int32), _sds((rows,), jnp.int32),
                key, _sds((rows,), jnp.float32),
                _sds((rows,), jnp.int32), _sds((rows,), jnp.float32)]
        if plen:
            kv = (cfg.num_layers, plen, cfg.num_kv_heads, cfg.head_dim)
            args += [_sds(kv, cfg.activation_dtype),
                     _sds(kv, cfg.activation_dtype)]
        return args

    prefill = make_prefill_fn(cfg, cache_len)
    # The auto-prefix splice set: every (plen, suffix bucket, rows) the
    # quantized registration path can produce — the bounded census
    # warmup and the worker's background warms walk (engine
    # prefix_warmup_shapes).
    plens = auto_prefix_plens(buckets, max_seq_len)
    splice = [(p, b, r) for p in plens for b in buckets
              if b <= bucket_for(buckets, max_seq_len - p)
              for r in rows_set]
    rep_plen, rep_bucket, rep_rows = splice[-1] if splice \
        else (16, buckets[0], 1)

    decode = make_decode_fn(cfg, settings.decode_chunk, max_seq_len,
                            max_seq_len, views[-1])
    decode_args = [params, pool,
                   _sds((slots,), jnp.int32), _sds((slots,), jnp.int32),
                   key, _sds((slots,), jnp.float32),
                   _sds((slots,), jnp.int32), _sds((slots,), jnp.float32),
                   _sds((slots,), jnp.int32), _sds((slots,), jnp.int32),
                   _sds((slots,), jnp.bool_)]

    prefix_build = make_prefix_build_fn(cfg, cache_len)

    def prefix_splice(p, pool_, pk, pv, *rest):
        return prefill(p, pool_, *rest, pk=pk, pv=pv)

    rest = prefill_args(rep_rows, rep_bucket, plen=rep_plen)

    # Speculative verify (serve/engine.py make_verify_fn): audited at
    # max K (settings.draft_tokens) and the widest row set — one
    # compiled program per decode view, same census shape as decode.
    K = settings.draft_tokens
    verify = make_verify_fn(cfg, K, max_seq_len, views[-1])
    verify_args = [params, pool,
                   _sds((slots, K + 1), jnp.int32),
                   _sds((slots,), jnp.int32), _sds((slots,), jnp.int32),
                   key, _sds((slots,), jnp.float32),
                   _sds((slots,), jnp.int32), _sds((slots,), jnp.float32),
                   _sds((slots,), jnp.bool_)]

    # Paged engine (serve/paging.py): same audit discipline — the paged
    # factories are the bodies the paged engine jits, traced at their
    # most complex reachable shape (largest prefix-page bucket splice;
    # one decode view). Census cardinality comes from the same
    # enumeration helpers warmup walks.
    from runbooks_tpu.serve.paging import (
        PagePool,
        make_kv_swap_in_fn,
        make_kv_swap_out_fn,
        make_paged_decode_fn,
        make_paged_prefill_fn,
        make_paged_verify_fn,
        paged_prefill_shapes,
        view_page_buckets_for,
    )

    page_size = 16
    mpps = max_seq_len // page_size
    pool_pages = slots * mpps
    paged_pool = jax.eval_shape(lambda: PagePool.create(
        cfg, pool_pages, page_size, quantize_kv=False))
    pshapes = paged_prefill_shapes(buckets, mpps, page_size, max_seq_len)
    vp_buckets = view_page_buckets_for(max_seq_len, page_size)
    # Widest gather first: the splice cost scales with the prefix-page
    # bucket (ppb*page_size gathered rows), so audit at max ppb and the
    # largest suffix bucket reachable alongside it.
    rep_ppb, rep_b = max((p, b) for b, p in pshapes if p)
    paged_prefill = make_paged_prefill_fn(cfg, cache_len, page_size,
                                          pool_pages)
    paged_prefill_args = [
        params, paged_pool,
        _sds((slots, rep_b), jnp.int32), _sds((slots, rep_b), jnp.int32),
        _sds((slots, mpps), jnp.int32), _sds((slots,), jnp.int32),
        key, _sds((slots,), jnp.float32), _sds((slots,), jnp.int32),
        _sds((slots,), jnp.float32),
        _sds((slots, rep_ppb), jnp.int32), _sds((slots,), jnp.int32)]
    paged_decode = make_paged_decode_fn(
        cfg, settings.decode_chunk, max_seq_len, page_size,
        vp_buckets[-1], pool_pages)
    paged_decode_args = [
        params, paged_pool, _sds((slots, mpps), jnp.int32),
        _sds((slots,), jnp.int32), _sds((slots,), jnp.int32), key,
        _sds((slots,), jnp.float32), _sds((slots,), jnp.int32),
        _sds((slots,), jnp.float32), _sds((slots,), jnp.int32),
        _sds((slots,), jnp.int32), _sds((slots,), jnp.bool_)]
    paged_verify = make_paged_verify_fn(cfg, K, page_size,
                                        vp_buckets[-1], pool_pages)
    paged_verify_args = [
        params, paged_pool, _sds((slots, mpps), jnp.int32),
        _sds((slots, K + 1), jnp.int32),
        _sds((slots,), jnp.int32), _sds((slots,), jnp.int32), key,
        _sds((slots,), jnp.float32), _sds((slots,), jnp.int32),
        _sds((slots,), jnp.float32), _sds((slots,), jnp.bool_)]

    # Host-tier swap splices (docs/paged-kv.md "Host tier and
    # preemption"): the page index is a traced operand, so each
    # direction is ONE program for every page — signature cardinality 1.
    # The swap-in payload operands mirror the host buffers (one page's
    # K/V, pool dtype, numpy-backed at runtime).
    kv_swap_out = make_kv_swap_out_fn()
    kv_swap_in = make_kv_swap_in_fn()
    page_shape = (paged_pool.k.shape[0],) + paged_pool.k.shape[2:]
    kv_swap_in_args = [paged_pool, _sds((), jnp.int32),
                       _sds(page_shape, paged_pool.k.dtype),
                       _sds(page_shape, paged_pool.v.dtype)]

    # Multi-tenant LoRA adapter variants (docs/multi-tenant-lora.md): a
    # pooled engine jits THESE shapes instead of the plain set — same
    # factories, adapter-pool + lane-index operands live. Audited at
    # settings.adapter_pool/lora_rank (the max reachable pool shapes);
    # signature cardinality matches the plain programs 1:1 (the pool
    # replaces, never multiplies, the census).
    from runbooks_tpu.ops.lora import init_adapter_pool

    apool = jax.eval_shape(lambda: init_adapter_pool(
        cfg, settings.adapter_pool, settings.lora_rank,
        cfg.lora_targets))

    def aslots_sds(rows):
        return _sds((rows,), jnp.int32)

    def adapter_prefill(params_, pool_, apool_, aslots_, *rest):
        return prefill(params_, pool_, *rest, apool=apool_,
                       aslots=aslots_)

    def adapter_decode(params_, pool_, apool_, aslots_, *rest):
        return decode(params_, pool_, *rest, apool=apool_,
                      aslots=aslots_)

    def adapter_verify(params_, pool_, apool_, aslots_, *rest):
        return verify(params_, pool_, *rest, apool=apool_,
                      aslots=aslots_)

    def paged_adapter_prefill(params_, pool_, apool_, aslots_, *rest):
        return paged_prefill(params_, pool_, *rest, apool=apool_,
                             aslots=aslots_)

    def paged_adapter_decode(params_, pool_, apool_, aslots_, *rest):
        return paged_decode(params_, pool_, *rest, apool=apool_,
                            aslots=aslots_)

    def paged_adapter_verify(params_, pool_, apool_, aslots_, *rest):
        return paged_verify(params_, pool_, *rest, apool=apool_,
                            aslots=aslots_)

    # Grammar-constrained decoding (serve/grammar.py,
    # docs/structured-output.md): a grammar-on engine jits THESE shapes
    # instead of the plain set — the gmask bool operand rides every
    # dispatch (all-True rows for unconstrained lanes), so like the
    # adapter variants above it replaces, never multiplies, the census.
    vocab = cfg.vocab_size

    def gmask_sds(*shape):
        return _sds(shape, jnp.bool_)

    def grammar_prefill(params_, pool_, gmask_, *rest):
        return prefill(params_, pool_, *rest, gmask=gmask_)

    def grammar_decode(params_, pool_, gmask_, *rest):
        return decode(params_, pool_, *rest, gmask=gmask_)

    def grammar_verify(params_, pool_, gmask_, *rest):
        return verify(params_, pool_, *rest, gmask=gmask_)

    def paged_grammar_prefill(params_, pool_, gmask_, *rest):
        return paged_prefill(params_, pool_, *rest, gmask=gmask_)

    def paged_grammar_decode(params_, pool_, gmask_, *rest):
        return paged_decode(params_, pool_, *rest, gmask=gmask_)

    def paged_grammar_verify(params_, pool_, gmask_, *rest):
        return paged_verify(params_, pool_, *rest, gmask=gmask_)

    specs = [
        {"component": "serve", "name": "prefill", "fn": prefill,
         "args": prefill_args(rows_set[-1], buckets[-1]),
         "signatures": len(buckets) * len(rows_set)},
        {"component": "serve", "name": "prefill_prefix",
         "fn": prefix_splice,
         "args": rest[:2] + rest[-2:] + rest[2:-2],
         "signatures": len(splice)},
        {"component": "serve", "name": "decode", "fn": decode,
         "args": decode_args, "signatures": len(views)},
        {"component": "serve", "name": "prefix_build", "fn": prefix_build,
         "args": [params, _sds((1, buckets[-1]), jnp.int32),
                  _sds((1, buckets[-1]), jnp.int32)],
         "signatures": len(buckets)},
        {"component": "serve", "name": "paged_prefill",
         "fn": paged_prefill, "args": paged_prefill_args,
         "signatures": len(pshapes) * len(rows_set)},
        {"component": "serve", "name": "paged_decode",
         "fn": paged_decode, "args": paged_decode_args,
         "signatures": len(vp_buckets)},
        {"component": "serve", "name": "verify", "fn": verify,
         "args": verify_args, "signatures": len(views)},
        {"component": "serve", "name": "paged_verify",
         "fn": paged_verify, "args": paged_verify_args,
         "signatures": len(vp_buckets)},
        {"component": "serve", "name": "kv_swap_out", "fn": kv_swap_out,
         "args": [paged_pool, _sds((), jnp.int32)], "signatures": 1},
        {"component": "serve", "name": "kv_swap_in", "fn": kv_swap_in,
         "args": kv_swap_in_args, "signatures": 1},
        {"component": "serve", "name": "adapter_prefill",
         "fn": adapter_prefill,
         "args": ([params, pool, apool, aslots_sds(rows_set[-1])]
                  + prefill_args(rows_set[-1], buckets[-1])[2:]),
         "signatures": len(buckets) * len(rows_set)},
        {"component": "serve", "name": "adapter_decode",
         "fn": adapter_decode,
         "args": ([params, pool, apool, aslots_sds(slots)]
                  + decode_args[2:]),
         "signatures": len(views)},
        {"component": "serve", "name": "adapter_verify",
         "fn": adapter_verify,
         "args": ([params, pool, apool, aslots_sds(slots)]
                  + verify_args[2:]),
         "signatures": len(views)},
        {"component": "serve", "name": "paged_adapter_prefill",
         "fn": paged_adapter_prefill,
         "args": ([params, paged_pool, apool, aslots_sds(slots)]
                  + paged_prefill_args[2:]),
         "signatures": len(pshapes) * len(rows_set)},
        {"component": "serve", "name": "paged_adapter_decode",
         "fn": paged_adapter_decode,
         "args": ([params, paged_pool, apool, aslots_sds(slots)]
                  + paged_decode_args[2:]),
         "signatures": len(vp_buckets)},
        {"component": "serve", "name": "paged_adapter_verify",
         "fn": paged_adapter_verify,
         "args": ([params, paged_pool, apool, aslots_sds(slots)]
                  + paged_verify_args[2:]),
         "signatures": len(vp_buckets)},
        {"component": "serve", "name": "grammar_prefill",
         "fn": grammar_prefill,
         "args": ([params, pool, gmask_sds(rows_set[-1], vocab)]
                  + prefill_args(rows_set[-1], buckets[-1])[2:]),
         "signatures": len(buckets) * len(rows_set)},
        {"component": "serve", "name": "grammar_decode",
         "fn": grammar_decode,
         "args": ([params, pool, gmask_sds(slots, vocab)]
                  + decode_args[2:]),
         "signatures": len(views)},
        {"component": "serve", "name": "grammar_verify",
         "fn": grammar_verify,
         "args": ([params, pool, gmask_sds(slots, K + 1, vocab)]
                  + verify_args[2:]),
         "signatures": len(views)},
        {"component": "serve", "name": "paged_grammar_prefill",
         "fn": paged_grammar_prefill,
         "args": ([params, paged_pool, gmask_sds(slots, vocab)]
                  + paged_prefill_args[2:]),
         "signatures": len(pshapes) * len(rows_set)},
        {"component": "serve", "name": "paged_grammar_decode",
         "fn": paged_grammar_decode,
         "args": ([params, paged_pool, gmask_sds(slots, vocab)]
                  + paged_decode_args[2:]),
         "signatures": len(vp_buckets)},
        {"component": "serve", "name": "paged_grammar_verify",
         "fn": paged_grammar_verify,
         "args": ([params, paged_pool, gmask_sds(slots, K + 1, vocab)]
                  + paged_verify_args[2:]),
         "signatures": len(vp_buckets)},
    ]

    # Sharded serving mesh (docs/tensor-parallel-performance.md): under a
    # mesh_tensor > 1 mesh the SAME factories trace DIFFERENT programs —
    # resolve_collective_matmul flips the ring path on at trace time — so
    # the sharded decode path gets its own census rows, traced under a
    # real tensor=2 mesh exactly as the engine's warmup does. Signature
    # cardinality mirrors the unsharded counterparts (a mesh engine
    # compiles the same bucket walk, just different programs). Skipped
    # below 2 devices; the canonical check env (Makefile TEST_ENV) pins 8
    # virtual CPU devices, so the committed baseline always carries them.
    if len(jax.devices()) >= 2:
        import dataclasses as _dc

        from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(data=1, fsdp=-1, tensor=2))
        cfg_tp = _dc.replace(cfg, collective_matmul="auto")

        def under_mesh(fn):
            def wrapped(*args):
                with jax.set_mesh(mesh):
                    return fn(*args)
            return wrapped

        prefill_tp = make_prefill_fn(cfg_tp, cache_len)
        decode_tp = make_decode_fn(cfg_tp, settings.decode_chunk,
                                   max_seq_len, max_seq_len, views[-1])
        verify_tp = make_verify_fn(cfg_tp, K, max_seq_len, views[-1])
        paged_prefill_tp = make_paged_prefill_fn(cfg_tp, cache_len,
                                                 page_size, pool_pages)
        paged_decode_tp = make_paged_decode_fn(
            cfg_tp, settings.decode_chunk, max_seq_len, page_size,
            vp_buckets[-1], pool_pages)
        paged_verify_tp = make_paged_verify_fn(cfg_tp, K, page_size,
                                               vp_buckets[-1], pool_pages)

        def adapter_decode_tp(params_, pool_, apool_, aslots_, *rest):
            return decode_tp(params_, pool_, *rest, apool=apool_,
                             aslots=aslots_)

        specs += [
            {"component": "serve", "name": "prefill_sharded",
             "fn": under_mesh(prefill_tp),
             "args": prefill_args(rows_set[-1], buckets[-1]),
             "signatures": len(buckets) * len(rows_set)},
            {"component": "serve", "name": "decode_sharded",
             "fn": under_mesh(decode_tp), "args": decode_args,
             "signatures": len(views)},
            {"component": "serve", "name": "verify_sharded",
             "fn": under_mesh(verify_tp), "args": verify_args,
             "signatures": len(views)},
            {"component": "serve", "name": "paged_prefill_sharded",
             "fn": under_mesh(paged_prefill_tp),
             "args": paged_prefill_args,
             "signatures": len(pshapes) * len(rows_set)},
            {"component": "serve", "name": "paged_decode_sharded",
             "fn": under_mesh(paged_decode_tp),
             "args": paged_decode_args, "signatures": len(vp_buckets)},
            {"component": "serve", "name": "paged_verify_sharded",
             "fn": under_mesh(paged_verify_tp),
             "args": paged_verify_args, "signatures": len(vp_buckets)},
            {"component": "serve", "name": "adapter_decode_sharded",
             "fn": under_mesh(adapter_decode_tp),
             "args": ([params, pool, apool, aslots_sds(slots)]
                      + decode_args[2:]),
             "signatures": len(views)},
        ]
    return specs


def _train_specs(settings: AuditSettings) -> List[dict]:
    import jax
    import jax.numpy as jnp
    import optax

    from runbooks_tpu.models.config import get_config
    from runbooks_tpu.models.transformer import (
        init_params,
        param_logical_axes,
    )
    from runbooks_tpu.parallel.mesh import single_device_mesh
    from runbooks_tpu.parallel.sharding import tree_shardings
    from runbooks_tpu.train.lora import (
        LoraConfig,
        init_lora,
        lora_logical_axes,
        make_lora_train_step,
    )
    from runbooks_tpu.train.step import (
        TrainState,
        infer_state_shardings,
        make_train_step,
    )

    cfg = get_config(settings.config)
    mesh = single_device_mesh()
    optimizer = optax.adamw(1e-3)
    key = _key_sds()
    batch = {"tokens": _sds((settings.batch, settings.seq), jnp.int32),
             "targets": _sds((settings.batch, settings.seq), jnp.int32)}

    def init_fn(rng):
        params = init_params(cfg, rng)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=optimizer.init(params))

    state = jax.eval_shape(init_fn, key)
    shardings = infer_state_shardings(param_logical_axes(cfg), state, mesh)
    step = make_train_step(cfg, optimizer, mesh, shardings)

    lcfg = LoraConfig(rank=4)
    base = state.params
    base_shardings = tree_shardings(base, param_logical_axes(cfg), mesh)

    def lora_init_fn(rng):
        lora = init_lora(base, lcfg, rng)
        return TrainState(step=jnp.zeros((), jnp.int32), params=lora,
                          opt_state=optimizer.init(lora))

    lstate = jax.eval_shape(lora_init_fn, key)
    laxes = lora_logical_axes(lcfg, lstate.params)
    lshardings = infer_state_shardings(laxes, lstate, mesh)
    lstep = make_lora_train_step(cfg, lcfg, optimizer, mesh, lshardings,
                                 base_shardings)

    return [
        {"component": "train", "name": "train_step", "fn": step,
         "args": [state, batch], "signatures": 1},
        {"component": "train", "name": "lora_step", "fn": lstep,
         "args": [lstate, base, batch], "signatures": 1},
    ]


def audit_programs(
    settings: Optional[AuditSettings] = None,
) -> Tuple[dict, List[Finding]]:
    """Trace and audit the full registered program set. Returns
    (census, findings). The census is the committed-baseline content:
    per program, its signature cardinality and content flags."""
    import jax

    settings = settings or AuditSettings()
    findings: List[Finding] = []
    programs: List[dict] = []
    for spec in _engine_specs(settings) + _train_specs(settings):
        program = f"{spec['component']}/{spec['name']}"
        try:
            closed = jax.make_jaxpr(spec["fn"])(*spec["args"])
        except Exception as exc:  # noqa: BLE001 — surface, don't crash
            findings.append(Finding(
                rule="program-trace", path=f"program:{program}", line=0,
                message=f"abstract trace failed: {exc!r}"))
            programs.append({"component": spec["component"],
                             "name": spec["name"],
                             "signatures": spec["signatures"],
                             "flags": None})
            continue
        prog_findings, flags = audit_jaxpr(closed, program, settings)
        findings.extend(prog_findings)
        programs.append({"component": spec["component"],
                         "name": spec["name"],
                         "signatures": spec["signatures"],
                         "flags": flags})
    census = {
        "settings": {"config": settings.config,
                     "max_slots": settings.max_slots,
                     "decode_chunk": settings.decode_chunk,
                     "draft_tokens": settings.draft_tokens,
                     "adapter_pool": settings.adapter_pool,
                     "lora_rank": settings.lora_rank,
                     "batch": settings.batch, "seq": settings.seq},
        "programs": programs,
    }
    return census, findings


# ---------------------------------------------------------------------------
# Census baseline (config/program_baseline.json)
# ---------------------------------------------------------------------------

def load_program_baseline(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def write_program_baseline(path: str, census: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(census, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def diff_census(census: dict, baseline: Optional[dict],
                baseline_path: str) -> List[Finding]:
    """Census drift findings (rule ``program-census-drift``), mirroring
    the metrics-catalog drift gate: additions, removals, and signature/
    flag changes all fail until the committed baseline is regenerated."""
    hint = (f"; regenerate {os.path.basename(baseline_path)} with "
            "`rbt check --write-baseline` if intentional")
    if baseline is None:
        return [Finding(
            rule="program-census-drift", path=baseline_path, line=0,
            message="program baseline missing" + hint)]
    findings: List[Finding] = []
    if baseline.get("settings") != census["settings"]:
        findings.append(Finding(
            rule="program-census-drift", path=baseline_path, line=0,
            message=f"audit settings changed: baseline "
                    f"{baseline.get('settings')} vs "
                    f"{census['settings']}" + hint))
    def by_name(c):
        return {(p["component"], p["name"]): p
                for p in c.get("programs", [])}
    base, cur = by_name(baseline), by_name(census)
    for key in sorted(set(base) | set(cur)):
        name = "/".join(key)
        b, c = base.get(key), cur.get(key)
        if b is None:
            findings.append(Finding(
                rule="program-census-drift", path=baseline_path, line=0,
                message=f"new program {name} not in baseline" + hint))
        elif c is None:
            findings.append(Finding(
                rule="program-census-drift", path=baseline_path, line=0,
                message=f"program {name} vanished from the census" + hint))
        elif (b.get("signatures") != c["signatures"]
              or b.get("flags") != c["flags"]):
            findings.append(Finding(
                rule="program-census-drift", path=baseline_path, line=0,
                message=f"program {name} drifted: baseline "
                        f"signatures={b.get('signatures')} "
                        f"flags={b.get('flags')} vs "
                        f"signatures={c['signatures']} "
                        f"flags={c['flags']}" + hint))
    return findings
