"""Local signed-URL upload endpoint: HTTP PUT -> filesystem bucket + md5.

The local SCI's "signed URLs" point here (reference analog: the kind SCI's
HTTP PUT handler writing body + md5 sidecar to local disk —
internal/sci/kind/server.go). Runs alongside the gRPC service in
``python -m runbooks_tpu.sci.main``.
"""

from __future__ import annotations

import hashlib
import time

from aiohttp import web

from runbooks_tpu.sci.base import LocalSCI


def create_app(sci: LocalSCI) -> web.Application:
    app = web.Application(client_max_size=10 * 1024 ** 3)

    async def put_object(request: web.Request) -> web.Response:
        expiry = request.query.get("expiry")
        if expiry and int(expiry) < time.time():
            return web.json_response(
                {"error": "signed URL expired"}, status=403)
        path = request.match_info["path"]
        if "/" not in path:
            return web.json_response(
                {"error": "path must be bucket/object"}, status=400)
        bucket, object_name = path.split("/", 1)
        data = await request.read()
        md5 = hashlib.md5(data).hexdigest()
        want = request.headers.get("Content-MD5", "")
        if want:
            # Standard Content-MD5 is base64(digest); accept hex too.
            # Validate BEFORE storing so a corrupt body can never clobber a
            # previously verified object.
            try:
                want_hex = (want if len(want) == 32 and
                            all(c in "0123456789abcdef" for c in want.lower())
                            else __import__("base64").b64decode(want).hex())
            except Exception:
                want_hex = ""
            if want_hex != md5:
                return web.json_response(
                    {"error": f"md5 mismatch: body {md5} != header {want}"},
                    status=400)
        sci.put_object(bucket, object_name, data)
        return web.json_response({"md5": md5, "bytes": len(data)})

    async def healthz(request: web.Request) -> web.Response:
        return web.json_response({"ok": True})

    app.router.add_put("/{path:.+}", put_object)
    app.router.add_get("/healthz", healthz)
    return app


def run(sci: LocalSCI, port: int = 30080) -> None:
    web.run_app(create_app(sci), port=port, print=lambda *a: None)
