"""gRPC server + client for the SCI service.

The service definition lives in sci.proto; message classes come from
``protoc --python_out`` (sci_pb2). The image has no grpc_tools codegen
plugin, so the service/stub layer is hand-written against grpcio's generic
handler API — wire-compatible with what protoc-gen-grpc would emit (same
method paths ``/runbooks_tpu.sci.Controller/<Method>``, same protobuf
serialization).

Reference analogs: the gRPC server mains under cmd/sci-* and the client dial
in cmd/controllermanager/main.go.
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from runbooks_tpu.sci import sci_pb2
from runbooks_tpu.sci.base import DEFAULT_EXPIRY_SECONDS, SCIClient

SERVICE = "runbooks_tpu.sci.Controller"
DEFAULT_PORT = 10080

_METHODS = {
    "CreateSignedURL": (sci_pb2.CreateSignedURLRequest,
                        sci_pb2.CreateSignedURLResponse),
    "GetObjectMd5": (sci_pb2.GetObjectMd5Request,
                     sci_pb2.GetObjectMd5Response),
    "BindIdentity": (sci_pb2.BindIdentityRequest,
                     sci_pb2.BindIdentityResponse),
    "EnsureTPUNodePool": (sci_pb2.EnsureTPUNodePoolRequest,
                          sci_pb2.EnsureTPUNodePoolResponse),
}


class _Servicer:
    """Adapts an in-process SCIClient implementation to the RPC surface."""

    def __init__(self, impl: SCIClient):
        self.impl = impl

    def CreateSignedURL(self, request, context):
        url = self.impl.create_signed_url(
            request.bucket_name, request.object_name,
            int(request.expiration_seconds) or DEFAULT_EXPIRY_SECONDS,
            request.md5_checksum)
        return sci_pb2.CreateSignedURLResponse(url=url)

    def GetObjectMd5(self, request, context):
        md5 = self.impl.get_object_md5(request.bucket_name,
                                       request.object_name)
        if md5 is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"object {request.object_name} not found")
        return sci_pb2.GetObjectMd5Response(md5_checksum=md5)

    def BindIdentity(self, request, context):
        self.impl.bind_identity(
            principal=request.principal,
            ksa=request.kubernetes_service_account,
            namespace=request.kubernetes_namespace)
        return sci_pb2.BindIdentityResponse()

    def EnsureTPUNodePool(self, request, context):
        ensure = getattr(self.impl, "ensure_tpu_node_pool", None)
        if ensure is None:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "node-pool provisioning not supported by this SCI")
        name, created = ensure(request.tpu_type, request.topology,
                               request.spot)
        return sci_pb2.EnsureTPUNodePoolResponse(node_pool_name=name,
                                                 created=created)


def serve(impl: SCIClient, port: int = DEFAULT_PORT,
          max_workers: int = 8) -> grpc.Server:
    """Start (and return) a gRPC server exposing `impl`. Caller stops it."""
    servicer = _Servicer(impl)
    handlers = {}
    for method, (req_cls, resp_cls) in _METHODS.items():
        handlers[method] = grpc.unary_unary_rpc_method_handler(
            getattr(servicer, method),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),))
    server.add_insecure_port(f"[::]:{port}")
    server.start()
    return server


class GrpcSCI:
    """SCIClient implementation backed by a remote SCI gRPC service (what
    the controller manager dials; reference:
    cmd/controllermanager/main.go grpc.Dial)."""

    def __init__(self, address: str = f"localhost:{DEFAULT_PORT}",
                 timeout: float = 30.0):
        self.channel = grpc.insecure_channel(address)
        self.timeout = timeout

    def _call(self, method: str, request, resp_cls):
        callable_ = self.channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=type(request).SerializeToString,
            response_deserializer=resp_cls.FromString,
        )
        return callable_(request, timeout=self.timeout)

    def create_signed_url(self, bucket_name, object_name,
                          expiration_seconds=DEFAULT_EXPIRY_SECONDS,
                          md5_checksum=""):
        resp = self._call("CreateSignedURL", sci_pb2.CreateSignedURLRequest(
            bucket_name=bucket_name, object_name=object_name,
            expiration_seconds=expiration_seconds,
            md5_checksum=md5_checksum), sci_pb2.CreateSignedURLResponse)
        return resp.url

    def get_object_md5(self, bucket_name, object_name) -> Optional[str]:
        try:
            resp = self._call("GetObjectMd5", sci_pb2.GetObjectMd5Request(
                bucket_name=bucket_name, object_name=object_name),
                sci_pb2.GetObjectMd5Response)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return None
            raise
        return resp.md5_checksum

    def bind_identity(self, principal, ksa, namespace):
        self._call("BindIdentity", sci_pb2.BindIdentityRequest(
            principal=principal, kubernetes_service_account=ksa,
            kubernetes_namespace=namespace), sci_pb2.BindIdentityResponse)

    def ensure_tpu_node_pool(self, tpu_type: str, topology: str,
                             spot: bool = False):
        resp = self._call("EnsureTPUNodePool",
                          sci_pb2.EnsureTPUNodePoolRequest(
                              tpu_type=tpu_type, topology=topology,
                              spot=spot),
                          sci_pb2.EnsureTPUNodePoolResponse)
        return resp.node_pool_name, resp.created
