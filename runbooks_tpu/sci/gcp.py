"""GCP SCI: V4 signed GCS URLs, object MD5s, workload-identity binding, and
TPU node-pool provisioning.

Reference behavior mirrored (reference: internal/sci/gcp/manager.go — signed
PUT URLs via IAMCredentials SignBlob, MD5 from GCS object attrs, BindIdentity
adds roles/iam.workloadIdentityUser for serviceAccount:{project}.svc.id.goog
[{ns}/{ksa}], metadata-server auto-configuration with retry). Node-pool
provisioning is new here: the reference creates TPU-less GPU pools from shell
(reference: install/gcp/up.sh); TPU slices need explicit pools per
(type, topology), so the operator can ask for them via SCI.

The google-cloud SDKs are imported lazily: this module is importable (and
its request/naming logic unit-testable) in SDK-less images; only the actual
cloud calls require them.
"""

from __future__ import annotations

import base64
import dataclasses
import os
import time
from typing import Optional, Tuple

from runbooks_tpu.sci.base import DEFAULT_EXPIRY_SECONDS


def _require_google(module: str):
    try:
        import importlib

        return importlib.import_module(module)
    except ImportError as e:
        raise RuntimeError(
            f"GCP SCI needs {module} (add google-cloud-storage/"
            f"google-api-python-client to the sci image)") from e


def node_pool_name(tpu_type: str, topology: str, spot: bool) -> str:
    """Deterministic pool name so EnsureTPUNodePool is idempotent."""
    suffix = "-spot" if spot else ""
    return f"tpu-{tpu_type}-{topology.replace('x', '-')}{suffix}"


def tpu_machine_type(tpu_type: str, chips_per_host: int) -> str:
    return {
        "v5e": f"ct5lp-hightpu-{chips_per_host}t",
        "v5p": f"ct5p-hightpu-{chips_per_host}t",
        "v4": f"ct4p-hightpu-{chips_per_host}t",
        "v6e": f"ct6e-standard-{chips_per_host}t",
    }[tpu_type]


@dataclasses.dataclass
class GCPSCI:
    project_id: str
    cluster_name: str
    cluster_location: str
    service_account: str        # the signing GSA (PRINCIPAL)

    @classmethod
    def auto_configure(cls) -> "GCPSCI":
        """Metadata-server auto-configuration with env overrides (reference:
        internal/sci/gcp/manager.go AutoConfigure + retrying Validate)."""
        env = os.environ
        project = env.get("PROJECT_ID", "")
        if not project:
            # Shared dual-host (DNS name + literal IP), deadline-bounded
            # metadata fetch — a hanging resolver must not stall SCI
            # startup any more than controller startup (cloud/metadata.py).
            from runbooks_tpu.cloud import metadata

            last_err: Exception | None = None
            for attempt in range(5):  # workload-identity warm-up races
                try:
                    project = metadata.fetch("project/project-id",
                                             timeout=3.0)
                    break
                except LookupError as e:
                    # Server answered 404: deterministic absence — no
                    # amount of retrying heals it.
                    last_err = e
                    break
                except OSError as e:
                    last_err = e
                    time.sleep(2 ** attempt)
            if not project:
                raise RuntimeError(
                    "GCP SCI could not determine the project id: metadata "
                    "server unreachable and PROJECT_ID unset"
                ) from last_err
        return cls(
            project_id=project,
            cluster_name=env.get("CLUSTER_NAME", ""),
            cluster_location=env.get("CLUSTER_LOCATION", ""),
            service_account=env.get("PRINCIPAL", ""),
        )

    # ------------------------------------------------------------------

    def _signing_credentials(self):
        """Credentials able to sign V4 URLs under workload identity, where
        default compute credentials carry no private key: impersonate the
        configured GSA so signing goes through IAMCredentials SignBlob
        (reference: internal/sci/gcp/manager.go signs the same way)."""
        auth = _require_google("google.auth")
        creds, _ = auth.default()
        if hasattr(creds, "sign_bytes"):
            return creds
        imp = _require_google("google.auth.impersonated_credentials")
        return imp.Credentials(
            source_credentials=creds,
            target_principal=self.service_account,
            target_scopes=["https://www.googleapis.com/auth/devstorage"
                           ".read_write"],
        )

    def create_signed_url(self, bucket_name: str, object_name: str,
                          expiration_seconds: int = DEFAULT_EXPIRY_SECONDS,
                          md5_checksum: str = "") -> str:
        storage = _require_google("google.cloud.storage")
        client = storage.Client(project=self.project_id)
        blob = client.bucket(bucket_name).blob(object_name)
        kwargs = {}
        if md5_checksum:
            # GCS expects base64(md5 bytes) in the signed headers.
            kwargs["content_md5"] = base64.b64encode(
                bytes.fromhex(md5_checksum)).decode()
        return blob.generate_signed_url(
            version="v4", method="PUT",
            expiration=expiration_seconds,
            credentials=self._signing_credentials(),
            **kwargs)

    def get_object_md5(self, bucket_name: str,
                       object_name: str) -> Optional[str]:
        storage = _require_google("google.cloud.storage")
        client = storage.Client(project=self.project_id)
        blob = client.bucket(bucket_name).get_blob(object_name)
        if blob is None or blob.md5_hash is None:
            return None
        return base64.b64decode(blob.md5_hash).hex()

    def bind_identity(self, principal: str, ksa: str,
                      namespace: str) -> None:
        """Add roles/iam.workloadIdentityUser on the GSA for the workload-
        identity member of (namespace, ksa)."""
        iam = _require_google("googleapiclient.discovery")
        service = iam.build("iam", "v1")
        resource = (f"projects/{self.project_id}/serviceAccounts/"
                    f"{principal}")
        member = (f"serviceAccount:{self.project_id}.svc.id.goog"
                  f"[{namespace}/{ksa}]")
        policy = service.projects().serviceAccounts().getIamPolicy(
            resource=resource).execute()
        bindings = policy.setdefault("bindings", [])
        for b in bindings:
            if b.get("role") == "roles/iam.workloadIdentityUser":
                if member in b.setdefault("members", []):
                    return
                b["members"].append(member)
                break
        else:
            bindings.append({"role": "roles/iam.workloadIdentityUser",
                             "members": [member]})
        service.projects().serviceAccounts().setIamPolicy(
            resource=resource, body={"policy": policy}).execute()

    def ensure_tpu_node_pool(self, tpu_type: str, topology: str,
                             spot: bool = False) -> Tuple[str, bool]:
        from runbooks_tpu.cloud.resources import parse_tpu

        slice_ = parse_tpu({"type": tpu_type, "topology": topology})
        name = node_pool_name(tpu_type, topology, spot)
        container = _require_google("googleapiclient.discovery")
        service = container.build("container", "v1")
        parent = (f"projects/{self.project_id}/locations/"
                  f"{self.cluster_location}/clusters/{self.cluster_name}")
        pools = service.projects().locations().clusters().nodePools().list(
            parent=parent).execute().get("nodePools", [])
        if any(p["name"] == name for p in pools):
            return name, False
        body = {
            "nodePool": {
                "name": name,
                "initialNodeCount": slice_.hosts,
                "config": {
                    "machineType": tpu_machine_type(tpu_type,
                                                    slice_.chips_per_host),
                    "spot": spot,
                },
                "placementPolicy": {"type": "COMPACT",
                                    "tpuTopology": slice_.topology},
            },
        }
        service.projects().locations().clusters().nodePools().create(
            parent=parent, body=body).execute()
        return name, True
