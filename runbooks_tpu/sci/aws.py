"""AWS SCI: S3 presigned PUT URLs, ETag-as-MD5, IRSA trust-policy binding.

Reference behavior mirrored (reference: internal/sci/aws/server.go —
presigned PUT (:60-86), single-part ETag == MD5 (:36-58), BindIdentity edits
the IAM role trust policy with the cluster's OIDC federated principal
(:88-162)). boto3 is imported lazily — not present in this repo's image; the
request/naming logic stays unit-testable without it.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from runbooks_tpu.sci.base import DEFAULT_EXPIRY_SECONDS


def _boto3():
    try:
        import boto3

        return boto3
    except ImportError as e:
        raise RuntimeError(
            "AWS SCI needs boto3 (add it to the sci image)") from e


def oidc_federated_principal(account_id: str, oidc_url: str) -> str:
    return (f"arn:aws:iam::{account_id}:oidc-provider/"
            f"{oidc_url.removeprefix('https://')}")


def trust_statement(account_id: str, oidc_url: str, namespace: str,
                    ksa: str) -> dict:
    """One federated trust statement for (namespace, ksa) — the IRSA analog
    of GKE workload identity."""
    issuer = oidc_url.removeprefix("https://")
    return {
        "Effect": "Allow",
        "Principal": {"Federated":
                      oidc_federated_principal(account_id, oidc_url)},
        "Action": "sts:AssumeRoleWithWebIdentity",
        "Condition": {"StringEquals": {
            f"{issuer}:sub":
                f"system:serviceaccount:{namespace}:{ksa}",
        }},
    }


@dataclasses.dataclass
class AWSSCI:
    region: str = ""
    role_name: str = ""          # the workload IAM role SCI manages trust for
    account_id: str = ""
    oidc_provider_url: str = ""

    @classmethod
    def auto_configure(cls) -> "AWSSCI":
        env = os.environ
        return cls(
            region=env.get("AWS_REGION", "us-west-2"),
            role_name=env.get("PRINCIPAL", ""),
            account_id=env.get("AWS_ACCOUNT_ID", ""),
            oidc_provider_url=env.get("OIDC_PROVIDER_URL", ""),
        )

    def create_signed_url(self, bucket_name: str, object_name: str,
                          expiration_seconds: int = DEFAULT_EXPIRY_SECONDS,
                          md5_checksum: str = "") -> str:
        s3 = _boto3().client("s3", region_name=self.region)
        params = {"Bucket": bucket_name, "Key": object_name}
        if md5_checksum:
            import base64

            params["ContentMD5"] = base64.b64encode(
                bytes.fromhex(md5_checksum)).decode()
        return s3.generate_presigned_url(
            "put_object", Params=params, ExpiresIn=expiration_seconds)

    def get_object_md5(self, bucket_name: str,
                       object_name: str) -> Optional[str]:
        s3 = _boto3().client("s3", region_name=self.region)
        try:
            head = s3.head_object(Bucket=bucket_name, Key=object_name)
        except s3.exceptions.ClientError:
            return None
        etag = head.get("ETag", "").strip('"')
        # Single-part uploads (our signed PUTs) have ETag == MD5; multipart
        # ETags contain '-' and cannot be used as a checksum.
        return etag if etag and "-" not in etag else None

    def bind_identity(self, principal: str, ksa: str,
                      namespace: str) -> None:
        iam = _boto3().client("iam")
        role = principal or self.role_name
        policy = iam.get_role(RoleName=role)["Role"][
            "AssumeRolePolicyDocument"]
        stmt = trust_statement(self.account_id, self.oidc_provider_url,
                               namespace, ksa)
        statements = policy.setdefault("Statement", [])
        if any(s.get("Condition") == stmt["Condition"] for s in statements):
            return
        statements.append(stmt)
        iam.update_assume_role_policy(
            RoleName=role, PolicyDocument=json.dumps(policy))
