"""SCI server entrypoint: gRPC (+ local HTTP upload endpoint).

Flavor selection mirrors the reference's per-cloud SCI binaries (reference:
cmd/sci-gcp, cmd/sci-kind, cmd/sci-aws) collapsed into one entrypoint:

  SCI_FLAVOR=local  (default) — filesystem bucket + HTTP PUT endpoint
  SCI_FLAVOR=gcp              — GCS signing + IAM workload-identity binding
                                (requires google-cloud SDKs in the image)

Env: SCI_PORT (gRPC, default 10080), SCI_HTTP_PORT (local uploads, 30080),
SCI_BUCKET_ROOT (local bucket dir), SCI_ENDPOINT (URL prefix for local
signed URLs).
"""

from __future__ import annotations

import os
import time


def main() -> int:
    flavor = os.environ.get("SCI_FLAVOR", "local")
    grpc_port = int(os.environ.get("SCI_PORT", "10080"))

    if flavor == "local":
        from runbooks_tpu.sci.base import LocalSCI
        from runbooks_tpu.sci.http_endpoint import run as run_http

        http_port = int(os.environ.get("SCI_HTTP_PORT", "30080"))
        # Root "/" makes file:///bucket/... artifact URLs map 1:1 onto disk
        # (the "bucket" is the first path component of the URL).
        impl = LocalSCI(
            root=os.environ.get("SCI_BUCKET_ROOT", "/"),
            endpoint=os.environ.get("SCI_ENDPOINT",
                                    f"http://localhost:{http_port}"))
        from runbooks_tpu.sci.grpc_service import serve

        server = serve(impl, port=grpc_port)
        print(f"sci[local]: grpc :{grpc_port}, http :{http_port}, "
              f"bucket {impl.root}", flush=True)
        try:
            run_http(impl, port=http_port)  # blocks
        finally:
            server.stop(grace=2)
        return 0

    if flavor == "gcp":
        from runbooks_tpu.sci.gcp import GCPSCI
        from runbooks_tpu.sci.grpc_service import serve

        impl = GCPSCI.auto_configure()
        server = serve(impl, port=grpc_port)
        print(f"sci[gcp]: grpc :{grpc_port}", flush=True)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            server.stop(grace=2)
        return 0

    raise SystemExit(f"unknown SCI_FLAVOR {flavor!r}")


if __name__ == "__main__":
    raise SystemExit(main())
