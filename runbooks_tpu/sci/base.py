"""SCI — the cloud-interface microservice boundary.

Three operations, mirroring the reference's gRPC service exactly (reference:
internal/sci/sci.proto: CreateSignedURL, GetObjectMd5, BindIdentity; dialed
by the controller at startup — cmd/controllermanager/main.go). Controllers
talk to a ``SCIClient``; implementations:

- ``FakeSCI``        — records calls, returns canned URLs (envtest analog of
                       internal/sci/fake_sci_client.go).
- ``LocalSCI``       — filesystem bucket + local HTTP upload endpoint
                       (reference: internal/sci/kind/server.go).
- ``runbooks_tpu.sci.grpc_service`` — the out-of-process gRPC server/client
                       pair wrapping any of the above.
- GCP/AWS impls      — cloud-API-backed; gated on their SDKs (not available
                       in this image; interfaces + glue are here, the API
                       calls raise with instructions).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from typing import Dict, List, Optional, Protocol

DEFAULT_EXPIRY_SECONDS = 300  # same signed-URL lifetime as the reference


class SCIClient(Protocol):
    def create_signed_url(self, bucket_name: str, object_name: str,
                          expiration_seconds: int = DEFAULT_EXPIRY_SECONDS,
                          md5_checksum: str = "") -> str: ...

    def get_object_md5(self, bucket_name: str, object_name: str
                       ) -> Optional[str]: ...

    def bind_identity(self, principal: str, ksa: str,
                      namespace: str) -> None: ...


@dataclasses.dataclass
class FakeSCI:
    """Test double: canned signed URLs, settable object MD5s, recorded
    identity bindings."""

    objects: Dict[str, str] = dataclasses.field(default_factory=dict)
    bindings: List[tuple] = dataclasses.field(default_factory=list)
    signed: List[tuple] = dataclasses.field(default_factory=list)

    def create_signed_url(self, bucket_name, object_name,
                          expiration_seconds=DEFAULT_EXPIRY_SECONDS,
                          md5_checksum=""):
        self.signed.append((bucket_name, object_name, md5_checksum))
        return f"https://signed.example/{bucket_name}/{object_name}"

    def get_object_md5(self, bucket_name, object_name):
        return self.objects.get(f"{bucket_name}/{object_name}")

    def bind_identity(self, principal, ksa, namespace):
        self.bindings.append((principal, ksa, namespace))


class LocalSCI:
    """Filesystem bucket: signed URLs point at a local HTTP PUT endpoint
    (sci.http_endpoint serves it); MD5s come from sidecar files written on
    upload, or are computed on demand."""

    def __init__(self, root: str, endpoint: str = "http://localhost:30080"):
        self.root = os.path.abspath(root)
        self.endpoint = endpoint.rstrip("/")
        os.makedirs(self.root, exist_ok=True)

    def _path(self, bucket_name: str, object_name: str) -> str:
        return os.path.join(self.root, bucket_name.strip("/"),
                            object_name.strip("/"))

    def create_signed_url(self, bucket_name, object_name,
                          expiration_seconds=DEFAULT_EXPIRY_SECONDS,
                          md5_checksum=""):
        expiry = int(time.time()) + expiration_seconds
        return (f"{self.endpoint}/{bucket_name.strip('/')}/"
                f"{object_name.strip('/')}?expiry={expiry}")

    def get_object_md5(self, bucket_name, object_name):
        path = self._path(bucket_name, object_name)
        sidecar = path + ".md5"
        if os.path.exists(sidecar):
            with open(sidecar) as f:
                return f.read().strip()
        if os.path.exists(path):
            h = hashlib.md5()
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            return h.hexdigest()
        return None

    def put_object(self, bucket_name: str, object_name: str,
                   data: bytes) -> str:
        """Store bytes + md5 sidecar (what the HTTP PUT endpoint calls)."""
        path = self._path(bucket_name, object_name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
        md5 = hashlib.md5(data).hexdigest()
        with open(path + ".md5", "w") as f:
            f.write(md5)
        return md5

    def bind_identity(self, principal, ksa, namespace):
        return None  # identity is a no-op locally
