"""The four declarative resources: Model, Dataset, Server, Notebook.

Capability parity with the reference's CRDs (reference: api/v1/
model_types.go, dataset_types.go, server_types.go, notebook_types.go,
common_types.go), redesigned TPU-first:

- ``resources.tpu {type, topology}`` replaces ``resources.gpu {type, count}``
  (reference: api/v1/common_types.go GPUType/GPUResources) and implies
  multi-host pod-slice fan-out when the topology spans hosts.
- Build sources (git | upload with md5/requestID handshake) and the
  signed-URL upload status mirror the reference's contract so the same
  dev-loop CLI flow works (reference: api/v1/common_types.go Build/
  BuildUpload/UploadStatus).

Objects are dict-backed (manifest shape in, manifest shape out); these
classes are thin typed views, not an ORM.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from runbooks_tpu.k8s import objects as ko

GROUP = "runbooks-tpu.dev"
VERSION = "v1"
API_VERSION = f"{GROUP}/{VERSION}"

KINDS = ("Model", "Dataset", "Server", "Notebook")

DEFAULT_RESOURCES = {"cpu": 2, "memory": 10, "disk": 10}


class Resource:
    """Typed view over a dict-shaped custom resource."""

    kind = ""

    def __init__(self, obj: Dict[str, Any]):
        assert obj.get("kind") == self.kind, (obj.get("kind"), self.kind)
        self.obj = obj

    # -- constructors --------------------------------------------------

    @classmethod
    def new(cls, name: str, namespace: str = "default",
            spec: Optional[dict] = None) -> "Resource":
        return cls(ko.new(API_VERSION, cls.kind, name, namespace,
                          spec=spec or {}))

    # -- generic accessors --------------------------------------------

    @property
    def name(self) -> str:
        return ko.name(self.obj)

    @property
    def namespace(self) -> str:
        return ko.namespace(self.obj)

    @property
    def spec(self) -> dict:
        return self.obj.setdefault("spec", {})

    @property
    def status(self) -> dict:
        return self.obj.setdefault("status", {})

    @property
    def generation(self) -> int:
        return ko.deep_get(self.obj, "metadata", "generation", default=0)

    # -- build contract (BuildableObject analog) ----------------------

    @property
    def image(self) -> str:
        return self.spec.get("image", "")

    def set_image(self, image: str) -> None:
        self.spec["image"] = image

    @property
    def build(self) -> Optional[dict]:
        return self.spec.get("build")

    @property
    def build_upload(self) -> Optional[dict]:
        b = self.build or {}
        return b.get("upload")

    @property
    def build_git(self) -> Optional[dict]:
        b = self.build or {}
        return b.get("git")

    @property
    def upload_status(self) -> dict:
        return self.status.setdefault("buildUpload", {})

    # -- workload contract --------------------------------------------

    @property
    def command(self) -> List[str]:
        return self.spec.get("command", [])

    @property
    def env(self) -> Dict[str, str]:
        return self.spec.get("env", {})

    @property
    def params(self) -> Dict[str, Any]:
        return self.spec.get("params", {})

    @property
    def resources(self) -> dict:
        return {**DEFAULT_RESOURCES, **self.spec.get("resources", {})}

    @property
    def tpu(self) -> Optional[dict]:
        return self.spec.get("resources", {}).get("tpu")

    # -- status --------------------------------------------------------

    @property
    def ready(self) -> bool:
        return bool(self.status.get("ready"))

    def set_ready(self, ready: bool) -> None:
        self.status["ready"] = ready

    @property
    def artifacts_url(self) -> str:
        return self.status.get("artifacts", {}).get("url", "")

    def set_artifacts_url(self, url: str) -> None:
        self.status.setdefault("artifacts", {})["url"] = url

    def set_condition(self, ctype: str, ok: bool, reason: str,
                      message: str = "") -> bool:
        return ko.set_condition(self.obj, ctype, ok, reason, message,
                                self.generation)

    def condition_true(self, ctype: str) -> bool:
        return ko.is_condition_true(self.obj, ctype)

    def absorb(self, written: Dict[str, Any]) -> None:
        """Absorb the resourceVersion of a server write (apply/update
        result) so the next write in the same reconcile pass doesn't carry
        a stale one — a real apiserver (and the fake, matching it) 409s
        those."""
        self.obj.setdefault("metadata", {})["resourceVersion"] = \
            ko.deep_get(written, "metadata", "resourceVersion")

    def commit_status(self, client) -> None:
        """Write .status and absorb the new resourceVersion."""
        self.absorb(client.update_status(self.obj))


class Model(Resource):
    """A trained/imported model: running spec.command in spec.image writes
    model artifacts to /content/artifacts (reference: api/v1/model_types.go
    docstrings + container contract)."""

    kind = "Model"

    @property
    def base_model_ref(self) -> Optional[str]:
        ref = self.spec.get("model") or self.spec.get("baseModel")
        return ref.get("name") if ref else None

    @property
    def dataset_ref(self) -> Optional[str]:
        ref = self.spec.get("dataset")
        return ref.get("name") if ref else None


class Dataset(Resource):
    """A dataset produced by a loader job writing /content/artifacts
    (reference: api/v1/dataset_types.go)."""

    kind = "Dataset"


class Server(Resource):
    """An HTTP inference server for a ready Model (reference:
    api/v1/server_types.go — spec.model is required)."""

    kind = "Server"

    @property
    def model_ref(self) -> Optional[str]:
        ref = self.spec.get("model")
        return ref.get("name") if ref else None


class Notebook(Resource):
    """A Jupyter workspace pod, suspendable (reference:
    api/v1/notebook_types.go Suspend/IsSuspended)."""

    kind = "Notebook"

    @property
    def suspended(self) -> bool:
        return bool(self.spec.get("suspend"))

    @property
    def model_ref(self) -> Optional[str]:
        ref = self.spec.get("model")
        return ref.get("name") if ref else None

    @property
    def dataset_ref(self) -> Optional[str]:
        ref = self.spec.get("dataset")
        return ref.get("name") if ref else None


KIND_TO_CLASS = {c.kind: c for c in (Model, Dataset, Server, Notebook)}


def wrap(obj: Dict[str, Any]) -> Resource:
    cls = KIND_TO_CLASS.get(obj.get("kind", ""))
    if cls is None:
        raise ValueError(f"not a runbooks-tpu kind: {obj.get('kind')}")
    return cls(obj)
