"""Condition types + reasons for the runbooks-tpu resources.

Capability mirror of the reference's condition vocabulary (reference:
api/v1/conditions.go — Uploaded/Built/Complete/Serving + reasons), with one
addition: Launched, used by multi-host TPU workloads to report pod-slice
fan-out before completion.
"""

# Condition types
UPLOADED = "Uploaded"
BUILT = "Built"
COMPLETE = "Complete"
SERVING = "Serving"
SUSPENDED = "Suspended"
LAUNCHED = "Launched"
# Declarative serving SLOs (Server.spec.slo, docs/observability.md):
# status True while any objective is violated by the scraped fleet
# telemetry — the autoscaler's scale-out trigger. Net-new vs the
# reference, which has no telemetry to evaluate against.
SLO_VIOLATED = "SLOViolated"

# Reasons
REASON_AWAITING_UPLOAD = "AwaitingUpload"
REASON_UPLOAD_FOUND = "UploadFound"
REASON_BUILD_JOB_RUNNING = "BuildJobRunning"
REASON_BUILD_JOB_FAILED = "BuildJobFailed"
REASON_BUILT = "ImageBuilt"
REASON_JOB_RUNNING = "JobRunning"
REASON_JOB_COMPLETE = "JobComplete"
REASON_JOB_FAILED = "JobFailed"
# A multi-host slice Job failed (e.g. one host died) and was recreated to
# resume from the last checkpoint (SURVEY §7 hard part #1). Net-new vs the
# reference, which treats any Job failure as terminal.
REASON_JOB_RESTARTED = "JobRestarted"
REASON_DEPLOYMENT_READY = "DeploymentReady"
REASON_DEPLOYMENT_NOT_READY = "DeploymentNotReady"
REASON_POD_READY = "PodReady"
REASON_POD_NOT_READY = "PodNotReady"
REASON_SUSPENDED = "Suspended"
REASON_MODEL_NOT_FOUND = "ModelNotFound"
REASON_MODEL_NOT_READY = "ModelNotReady"
REASON_DATASET_NOT_FOUND = "DatasetNotFound"
REASON_DATASET_NOT_READY = "DatasetNotReady"
REASON_BASEMODEL_NOT_FOUND = "BaseModelNotFound"
REASON_BASEMODEL_NOT_READY = "BaseModelNotReady"
REASON_SLICE_PENDING = "PodSlicePending"
REASON_SLICE_RUNNING = "PodSliceRunning"
# spec.params validation failed (e.g. quantize outside none|int8|int4) —
# terminal until the spec changes, like the reference's webhook rejections.
REASON_INVALID_PARAMS = "InvalidParams"
# Shared-engine tenant Servers (spec.engineRef, docs/multi-tenant-lora.md):
# a tenant maps onto another Server's pooled engine instead of its own
# Deployment. Not-found/not-ready mirror the Model gating reasons; NoPool
# flags a host without an adapter_pool (the tenant's per-request adapter
# would 400 on every call).
REASON_ENGINE_NOT_FOUND = "SharedEngineNotFound"
REASON_ENGINE_NOT_READY = "SharedEngineNotReady"
REASON_ENGINE_NO_POOL = "SharedEngineNoAdapterPool"
# SLOViolated reasons: the violated objective by name (the condition
# message carries measured-vs-target for every violated objective), or
# the healthy/empty states.
REASON_SLO_TTFT = "TTFTP99AboveTarget"
REASON_SLO_QUEUE_WAIT = "QueueWaitP90AboveTarget"
REASON_SLO_ERROR_RATE = "ErrorRateAboveTarget"
REASON_SLO_MET = "AllObjectivesMet"
REASON_SLO_NO_DATA = "NoTelemetry"

# Multi-window burn-rate reasons (controller/burnrate.py,
# docs/observability.md "Error budgets & burn rates"): once the fleet
# history is warm the SLOViolated reason names BOTH the objective and
# the window pair that fired — e.g. "TTFTP99BurnRateFast5m" (severe,
# current: burn >= 14.4x over 5m AND 1h) vs "ErrorRateBurnRateSlow30m"
# (sustained simmer: burn >= 6x over 30m AND 6h). The instant-threshold
# reasons above remain the cold-history fallback.
SLO_BURN_TOKENS = {
    "ttftP99Ms": "TTFTP99",
    "queueWaitP90Ms": "QueueWaitP90",
    "errorRatePct": "ErrorRate",
}


def slo_burn_reason(objective_key: str, window_token: str) -> str:
    """Condition reason for a fired burn-rate window, e.g.
    ('ttftP99Ms', 'Fast5m') -> 'TTFTP99BurnRateFast5m'."""
    return f"{SLO_BURN_TOKENS[objective_key]}BurnRate{window_token}"
