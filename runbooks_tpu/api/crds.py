"""CRD manifest generation for the four resources.

The reference generates its CRDs with controller-gen (reference:
config/crd/bases/*.yaml, Makefile `manifests` target); here the schemas are
emitted programmatically: ``python -m runbooks_tpu.api.crds config/crd``.
"""

from __future__ import annotations

import os
import sys
from typing import Dict

import yaml

from runbooks_tpu.api.types import GROUP, KINDS, VERSION


def _obj_ref():
    return {"type": "object",
            "properties": {"name": {"type": "string"}},
            "required": ["name"]}


def _resources_schema() -> Dict:
    return {
        "type": "object",
        "properties": {
            "cpu": {"type": "integer", "default": 2},
            "memory": {"type": "integer", "default": 10,
                       "description": "GiB"},
            "disk": {"type": "integer", "default": 10,
                     "description": "GiB ephemeral"},
            "spot": {"type": "boolean"},
            "tpu": {
                "type": "object",
                "description": "Schedules onto a TPU pod slice; topologies "
                               "spanning hosts fan out one pod per host.",
                "properties": {
                    "type": {"type": "string",
                             "enum": ["v4", "v5e", "v5p", "v6e"]},
                    "topology": {"type": "string",
                                 "pattern": r"^\d+x\d+(x\d+)?$"},
                },
                "required": ["type", "topology"],
            },
        },
    }


def _build_schema() -> Dict:
    return {
        "type": "object",
        "properties": {
            "git": {
                "type": "object",
                "properties": {
                    "url": {"type": "string"},
                    "branch": {"type": "string"},
                    "tag": {"type": "string"},
                    "path": {"type": "string"},
                },
                "required": ["url"],
            },
            "upload": {
                "type": "object",
                "properties": {
                    "md5checksum": {"type": "string",
                                    "pattern": "^[a-f0-9]{32}$"},
                    "requestID": {"type": "string"},
                },
            },
        },
    }


def _common_spec() -> Dict:
    return {
        "image": {"type": "string"},
        "build": _build_schema(),
        "command": {"type": "array", "items": {"type": "string"}},
        "env": {"type": "object",
                "additionalProperties": {"type": "string"}},
        "params": {"type": "object",
                   "x-kubernetes-preserve-unknown-fields": True},
        "resources": _resources_schema(),
    }


def _status_schema() -> Dict:
    return {
        "type": "object",
        "properties": {
            "ready": {"type": "boolean"},
            "conditions": {
                "type": "array",
                "items": {"type": "object",
                          "x-kubernetes-preserve-unknown-fields": True},
            },
            "artifacts": {"type": "object",
                          "properties": {"url": {"type": "string"}}},
            "buildUpload": {
                "type": "object",
                "properties": {
                    "signedURL": {"type": "string"},
                    "requestID": {"type": "string"},
                    "expiration": {"type": "integer"},
                    "storedMD5": {"type": "string"},
                },
            },
        },
    }


def crd_for(kind: str) -> Dict:
    spec_props = _common_spec()
    if kind == "Model":
        spec_props["model"] = _obj_ref()
        spec_props["dataset"] = _obj_ref()
    elif kind == "Server":
        spec_props["model"] = _obj_ref()
        spec_props["replicas"] = {"type": "integer", "default": 1}
    elif kind == "Notebook":
        spec_props["model"] = _obj_ref()
        spec_props["dataset"] = _obj_ref()
        spec_props["suspend"] = {"type": "boolean"}

    plural = kind.lower() + "s"
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {"kind": kind, "listKind": f"{kind}List",
                      "plural": plural, "singular": kind.lower()},
            "scope": "Namespaced",
            "versions": [{
                "name": VERSION,
                "served": True,
                "storage": True,
                "subresources": {"status": {}},
                "additionalPrinterColumns": [{
                    "name": "Ready", "type": "string",
                    "jsonPath": ".status.ready",
                }],
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "properties": {
                        "spec": {"type": "object",
                                 "properties": spec_props},
                        "status": _status_schema(),
                    },
                }},
            }],
        },
    }


def write_all(out_dir: str) -> None:
    os.makedirs(out_dir, exist_ok=True)
    for kind in KINDS:
        path = os.path.join(out_dir, f"{GROUP}_{kind.lower()}s.yaml")
        with open(path, "w") as f:
            yaml.safe_dump(crd_for(kind), f, sort_keys=False)
        print(f"wrote {path}")


if __name__ == "__main__":
    write_all(sys.argv[1] if len(sys.argv) > 1 else "config/crd")
