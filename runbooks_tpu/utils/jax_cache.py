"""Persistent JAX compilation cache under the artifacts dir.

A restarted trainer Job (slice restart with resume — controller/model.py)
or serve worker otherwise pays the full XLA compile again; pointing JAX's
persistent compilation cache at the durable artifacts mount
(/content/artifacts per the container contract) makes restarts start
stepping in seconds instead of minutes. Worth real money on TPU: the chips
idle for the whole recompile.

Env knobs:
  RBT_JAX_CACHE=0                disable entirely
  RBT_JAX_CACHE=1                force-enable (including on CPU, see below)
  JAX_COMPILATION_CACHE_DIR      override the cache location

CPU is opt-in only: deserializing a warm cache entry on the CPU backend of
older jaxlib (0.4.x) corrupts the heap ("corrupted double-linked list" /
segfault on the run AFTER the one that wrote the cache — reproduced with a
two-process resume against one artifacts dir). The accelerator backends,
where the recompile actually costs money, are the production contract and
stay enabled by default.
"""

from __future__ import annotations

import os
from typing import Optional


def enable_compilation_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir`` (default:
    $JAX_COMPILATION_CACHE_DIR, else <artifacts>/jax_cache). Returns the
    directory in use, or None when disabled/unavailable. Safe to call more
    than once and before/after other jax.config use; never raises — a
    missing cache is a perf bug, not a correctness one."""
    force = os.environ.get("RBT_JAX_CACHE")
    if force == "0":
        return None
    try:
        import jax

        if force != "1" and jax.default_backend() == "cpu":
            return None  # known-crashy warm-read path (module docstring)

        if cache_dir is None:
            cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
        if cache_dir is None:
            from runbooks_tpu.utils import contract

            cache_dir = os.path.join(contract.artifacts_dir(), "jax_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache every compile that takes noticeable time: the default
        # 1s floor skips the many small serve/trainer helper jits whose
        # compiles still add up across a restart.
        try:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.2)
        except Exception:
            pass  # knob renamed/absent on some versions; dir alone works
        return cache_dir
    except Exception as exc:
        print(f"jax_cache: persistent compilation cache disabled ({exc!r})",
              flush=True)
        return None
