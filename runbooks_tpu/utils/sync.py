"""Notebook file sync: mirror /content changes in the pod back to the
workstation.

Reference behavior mirrored (reference: internal/client/sync.go +
containertools/cmd/nbwatch): copy the nbwatch binary into the pod
(kubectl cp), exec it, stream its JSON events, and for each changed file
kubectl-cp it back (delete locally on REMOVE/RENAME). The watcher itself is
the native C++ tool in native/nbwatch (built per-arch; inside the workload
images it ships at /usr/local/bin/nbwatch).

``sync_loop`` is the blocking engine with a progress callback (the TUI runs
it on a command thread and renders the events — reference:
notebookSyncFilesCmd); ``start_sync`` is the plain-CLI wrapper that runs it
on a daemon thread printing progress lines.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
from typing import Callable, Optional

NBWATCH_DIR = os.path.join(os.path.dirname(__file__), "..", "..",
                           "native", "nbwatch")
NBWATCH_REMOTE = "/tmp/nbwatch"
CONTENT_ROOT = "/content"

# on_event(file, complete, error, removed=False): file started syncing
# (complete=False), finished (complete=True; removed=True when the event was
# a local deletion rather than a pull), or failed (error set).
OnEvent = Callable[..., None]


def _kubectl(*args: str, **kwargs):
    return subprocess.run(["kubectl", *args], check=True, **kwargs)


def node_arch(pod: str, namespace: str) -> str:
    """Architecture of the node running the pod, so the matching nbwatch
    binary gets copied in (reference: internal/client/sync.go:275-293 —
    per-arch container-tools selection from node labels)."""
    try:
        node = _kubectl(
            "get", "pod", "-n", namespace, pod,
            "-o", "jsonpath={.spec.nodeName}",
            capture_output=True, text=True).stdout.strip()
        if not node:
            return ""
        return _kubectl(
            "get", "node", node,
            "-o", "jsonpath={.status.nodeInfo.architecture}",
            capture_output=True, text=True).stdout.strip()
    except (subprocess.CalledProcessError, FileNotFoundError):
        return ""


def _select_nbwatch(pod: str, namespace: str) -> Optional[str]:
    """Per-arch local binary (nbwatch-linux-{arch}, from `make -C
    native/nbwatch release` or the release workflow); None means rely on
    the one the workload image ships."""
    arch = node_arch(pod, namespace)
    candidates = []
    if arch:
        candidates.append(os.path.join(NBWATCH_DIR, f"nbwatch-linux-{arch}"))
    # Un-suffixed dev build: trustworthy when the workstation is Linux (pods
    # are) and the node arch matches — or is unknown (RBAC may forbid 'get
    # node'); a wrong guess is surfaced by the no-READY-output check in
    # sync_loop rather than failing silently.
    import platform

    local_arch = {"x86_64": "amd64", "aarch64": "arm64"}.get(
        platform.machine(), platform.machine())
    if platform.system() == "Linux" and arch in ("", local_arch):
        candidates.append(os.path.join(NBWATCH_DIR, "nbwatch"))
    for c in candidates:
        if os.path.exists(c):
            return os.path.abspath(c)
    return None


def copy_from_pod(pod: str, namespace: str, remote_path: str,
                  local_path: str) -> None:
    # Absolute pod paths: stripping the slash would resolve against the
    # container's workdir (/app), not the filesystem root.
    os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
    _kubectl("cp", "-n", namespace, f"{pod}:{remote_path}", local_path)


def copy_to_pod(pod: str, namespace: str, local_path: str,
                remote_path: str) -> None:
    _kubectl("cp", "-n", namespace, local_path, f"{pod}:{remote_path}")


def sync_loop(pod: str, namespace: str, local_dir: str,
              nbwatch_path: Optional[str] = None,
              on_event: OnEvent = lambda f, c, e, r=False: None) -> None:
    """Blocking sync loop: exec nbwatch in the pod, mirror each event."""
    binary = nbwatch_path or _select_nbwatch(pod, namespace)
    try:
        if binary and os.path.exists(binary):
            copy_to_pod(pod, namespace, binary, NBWATCH_REMOTE)
            _kubectl("exec", "-n", namespace, pod, "--", "chmod", "+x",
                     NBWATCH_REMOTE)
            watcher_cmd = NBWATCH_REMOTE
        else:
            # Image ships its own (workload images install it).
            watcher_cmd = "nbwatch"
        proc = subprocess.Popen(
            ["kubectl", "exec", "-n", namespace, pod, "--",
             watcher_cmd, CONTENT_ROOT],
            stdout=subprocess.PIPE, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        on_event("", True, e, False)
        return
    assert proc.stdout is not None
    saw_output = False
    for line in proc.stdout:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            event = json.loads(line)
        except json.JSONDecodeError:
            continue
        saw_output = True
        if event.get("op") == "READY":  # nbwatch startup announcement
            continue
        rel = os.path.relpath(event["path"], CONTENT_ROOT)
        local_path = os.path.join(local_dir, rel)
        removed = event["op"] in ("REMOVE", "RENAME")
        on_event(rel, False, None, removed)
        try:
            if removed:
                if os.path.exists(local_path):
                    os.remove(local_path)
            else:
                copy_from_pod(pod, namespace, event["path"], local_path)
            on_event(rel, True, None, removed)
        except subprocess.CalledProcessError as e:
            on_event(rel, True, e, removed)
    # The watcher exiting non-zero *having produced nothing* — not even the
    # READY announcement — means the binary was missing or the wrong format
    # for the node; surface it instead of pretending the sync ran. A
    # non-zero exit after READY is normal pod teardown (exec killed). In
    # the image-binary path also tolerate silent SIGKILL/SIGTERM exits
    # (137/143): an image-shipped nbwatch predating the READY announcement,
    # killed at pod teardown before any file event, is not a failure
    # (r4 advisor).
    code = proc.wait()
    if code != 0 and not saw_output and not (
            watcher_cmd != NBWATCH_REMOTE and code in (137, 143)):
        on_event("", True, RuntimeError(
            f"nbwatch ({watcher_cmd}) exited with code {code}"), False)


def start_sync(pod: str, namespace: str, local_dir: str,
               nbwatch_path: Optional[str] = None) -> threading.Thread:
    """Plain-CLI mode: run the sync loop in a daemon thread, print events."""

    def on_event(rel, complete, err, removed=False):
        if err is not None:
            print(f"sync: failed to mirror {rel or '(setup)'}: {err}")
        elif complete and rel:
            print(f"sync: {'removed' if removed else 'pulled'} {rel}")

    thread = threading.Thread(
        target=sync_loop, args=(pod, namespace, local_dir, nbwatch_path,
                                on_event),
        daemon=True)
    thread.start()
    return thread
