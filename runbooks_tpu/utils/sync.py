"""Notebook file sync: mirror /content changes in the pod back to the
workstation.

Reference behavior mirrored (reference: internal/client/sync.go +
containertools/cmd/nbwatch): copy the nbwatch binary into the pod
(kubectl cp), exec it, stream its JSON events, and for each changed file
kubectl-cp it back (delete locally on REMOVE/RENAME). The watcher itself is
the native C++ tool in native/nbwatch (built per-arch; inside the workload
images it ships at /usr/local/bin/nbwatch).
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
from typing import Optional

NBWATCH_LOCAL = os.path.join(os.path.dirname(__file__), "..", "..",
                             "native", "nbwatch", "nbwatch")
NBWATCH_REMOTE = "/tmp/nbwatch"
CONTENT_ROOT = "/content"


def _kubectl(*args: str, **kwargs):
    return subprocess.run(["kubectl", *args], check=True, **kwargs)


def copy_from_pod(pod: str, namespace: str, remote_path: str,
                  local_path: str) -> None:
    # Absolute pod paths: stripping the slash would resolve against the
    # container's workdir (/app), not the filesystem root.
    os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
    _kubectl("cp", "-n", namespace, f"{pod}:{remote_path}", local_path)


def copy_to_pod(pod: str, namespace: str, local_path: str,
                remote_path: str) -> None:
    _kubectl("cp", "-n", namespace, local_path, f"{pod}:{remote_path}")


def start_sync(pod: str, namespace: str, local_dir: str,
               nbwatch_path: Optional[str] = None) -> threading.Thread:
    """Start the sync loop in a daemon thread; returns the thread."""

    def run():
        binary = nbwatch_path or os.path.abspath(NBWATCH_LOCAL)
        try:
            if os.path.exists(binary):
                copy_to_pod(pod, namespace, binary, NBWATCH_REMOTE)
                _kubectl("exec", "-n", namespace, pod, "--", "chmod", "+x",
                         NBWATCH_REMOTE)
                watcher_cmd = NBWATCH_REMOTE
            else:
                # Image ships its own (workload images install it).
                watcher_cmd = "nbwatch"
            proc = subprocess.Popen(
                ["kubectl", "exec", "-n", namespace, pod, "--",
                 watcher_cmd, CONTENT_ROOT],
                stdout=subprocess.PIPE, text=True)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            print(f"sync: disabled ({e})")
            return
        assert proc.stdout is not None
        for line in proc.stdout:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue
            rel = os.path.relpath(event["path"], CONTENT_ROOT)
            local_path = os.path.join(local_dir, rel)
            try:
                if event["op"] in ("REMOVE", "RENAME"):
                    if os.path.exists(local_path):
                        os.remove(local_path)
                        print(f"sync: removed {rel}")
                else:
                    copy_from_pod(pod, namespace, event["path"], local_path)
                    print(f"sync: pulled {rel}")
            except subprocess.CalledProcessError:
                print(f"sync: failed to mirror {rel}")

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    return thread
