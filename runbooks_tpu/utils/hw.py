"""Hardware peak numbers for MFU accounting (shared by bench + trainer)."""

from __future__ import annotations

# Dense bf16 peak FLOP/s per chip by TPU generation.
PEAK_BF16 = {
    "v5 lite": 197e12,   # v5e
    "v5litepod": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
}


def chip_peak_flops(device) -> float:
    """Peak bf16 FLOP/s for a jax.Device; 0.0 when unknown (e.g. CPU), so
    callers can skip MFU reporting rather than report nonsense."""
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_BF16.items():
        if key in kind:
            return val
    return 0.0


# HBM bandwidth (bytes/s) per chip by TPU generation — the memory roofline
# (obs/device.py classifies programs against peak_flops / bandwidth).
HBM_BW = {
    "v5 lite": 819e9,    # v5e
    "v5litepod": 819e9,
    "v5e": 819e9,
    "v5p": 2765e9,
    "v4": 1228e9,
    "v6e": 1640e9,
}


def chip_hbm_bandwidth(device) -> float:
    """HBM bandwidth (bytes/s) for a jax.Device; 0.0 when unknown, so
    callers substitute an explicit nominal instead of dividing by a
    silent guess."""
    kind = getattr(device, "device_kind", "").lower()
    for key, val in HBM_BW.items():
        if key in kind:
            return val
    return 0.0


def backend_tuning() -> dict:
    """Backend-dependent serving defaults, probed in ONE place instead of
    per-module ``"tpu" in jax.default_backend()`` sniffing (the engine's
    decode_chunk default and the speculative-decoding defaults both used
    to hard-code the probe).

    - ``on_tpu``: whether the default JAX backend is a TPU.
    - ``decode_chunk``: decode steps per host round-trip. 8 on TPU — a
      per-step host sync dominates small-batch inter-token latency
      there; 1 elsewhere (CPU dispatch is cheap and tests want
      step-at-a-time).
    - ``draft_tokens``: default speculative draft window K
      (docs/speculative-decoding.md). 4 on every backend today; kept
      here so a backend-specific retune is one edit, not a sniff hunt.
    """
    import jax

    on_tpu = "tpu" in jax.default_backend().lower()
    return {"on_tpu": on_tpu,
            "decode_chunk": 8 if on_tpu else 1,
            "draft_tokens": 4}
