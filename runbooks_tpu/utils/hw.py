"""Hardware peak numbers for MFU accounting (shared by bench + trainer)."""

from __future__ import annotations

# Dense bf16 peak FLOP/s per chip by TPU generation.
PEAK_BF16 = {
    "v5 lite": 197e12,   # v5e
    "v5litepod": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
}


def chip_peak_flops(device) -> float:
    """Peak bf16 FLOP/s for a jax.Device; 0.0 when unknown (e.g. CPU), so
    callers can skip MFU reporting rather than report nonsense."""
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_BF16.items():
        if key in kind:
            return val
    return 0.0
