"""Client-side upload: tarball preparation + the signed-URL handshake.

Reference behavior mirrored (reference: internal/client/upload.go —
PrepareImageTarball requires a Dockerfile and produces tar.gz + md5 (:38-68);
Upload watches status.buildUpload for a signed URL matching its requestID,
HTTP-PUTs with Content-MD5, then pokes the controller via an annotation
(:126-192))."""

from __future__ import annotations

import base64
import hashlib
import io
import os
import tarfile
import time
import urllib.request
import uuid
from typing import Tuple

from runbooks_tpu.api.types import API_VERSION
from runbooks_tpu.k8s import objects as ko

UPLOAD_TIMESTAMP_ANNOTATION = "runbooks-tpu.dev/upload-timestamp"

_SKIP_DIRS = {".git", "__pycache__", ".venv", "node_modules"}


def prepare_image_tarball(src_dir: str) -> Tuple[bytes, str]:
    """tar.gz the build context; returns (bytes, hex md5). Requires a
    Dockerfile at the root, like the reference."""
    if not os.path.exists(os.path.join(src_dir, "Dockerfile")):
        raise FileNotFoundError(
            f"no Dockerfile in {src_dir}: an uploadable build context needs "
            "one (see the container contract)")
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for root, dirs, files in os.walk(src_dir):
            dirs[:] = [d for d in sorted(dirs) if d not in _SKIP_DIRS]
            for fname in sorted(files):
                full = os.path.join(root, fname)
                arc = os.path.relpath(full, src_dir)
                tar.add(full, arcname=arc, recursive=False)
    data = buf.getvalue()
    return data, hashlib.md5(data).hexdigest()


def set_upload_spec(obj: dict, md5: str, request_id: str) -> None:
    ko.deep_set(obj, {"md5checksum": md5, "requestID": request_id},
                "spec", "build", "upload")


def put_signed_url(url: str, data: bytes, md5_hex: str) -> None:
    md5_b64 = base64.b64encode(bytes.fromhex(md5_hex)).decode()
    req = urllib.request.Request(
        url, data=data, method="PUT",
        headers={"Content-MD5": md5_b64,
                 "Content-Type": "application/gzip"})
    with urllib.request.urlopen(req, timeout=300) as resp:
        resp.read()


def upload_build_context(client, obj: dict, src_dir: str,
                         timeout_s: float = 120.0,
                         progress=lambda msg: None) -> dict:
    """Full flow: tarball -> spec.build.upload -> wait for signed URL ->
    PUT -> nudge annotation. Returns the updated object."""
    data, md5 = prepare_image_tarball(src_dir)
    request_id = uuid.uuid4().hex
    progress(f"packed {len(data)} bytes (md5 {md5[:12]}…)")

    set_upload_spec(obj, md5, request_id)
    applied = client.apply(obj, "rbt-cli")

    kind, ns, name = ko.kind(obj), ko.namespace(obj), ko.name(obj)
    deadline = time.monotonic() + timeout_s
    signed_url = None
    while time.monotonic() < deadline:
        cur = client.get(API_VERSION, kind, ns, name)
        status = ko.deep_get(cur, "status", "buildUpload", default={}) or {}
        if status.get("requestID") == request_id and status.get("signedURL"):
            signed_url = status["signedURL"]
            break
        time.sleep(0.25)
    if signed_url is None:
        raise TimeoutError(
            f"no signed URL for {kind}/{name} within {timeout_s}s — is the "
            "controller manager running?")
    progress(f"uploading to {signed_url.split('?')[0]}")
    put_signed_url(signed_url, data, md5)

    # Nudge the controller to re-verify the upload (reference :172-190).
    # Minimal apply patch: re-applying the full live object would 422 on a
    # real apiserver (managedFields) and steal field ownership.
    nudge = {
        "apiVersion": API_VERSION, "kind": kind,
        "metadata": {
            "name": name, "namespace": ns,
            "annotations": {
                UPLOAD_TIMESTAMP_ANNOTATION:
                    time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            },
        },
    }
    progress("upload complete")
    # Distinct field manager: under real SSA semantics, re-applying with the
    # same manager that owns the full spec would prune every field omitted
    # here (including build.upload). A dedicated manager owns only this
    # annotation.
    return client.apply(nudge, "rbt-cli-nudge")
