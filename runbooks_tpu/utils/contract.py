"""Container contract: the filesystem/env interface between the operator and
workload containers.

Mirrors the reference's contract (reference: docs/container-contract.md):
  /content/params.json   — run parameters (mounted from a ConfigMap)
  /content/data          — dataset mount (RO)
  /content/model         — base/saved model mount (RO)
  /content/artifacts     — output mount (RW, durable bucket)
  ports: 8080 (serve), 8888 (notebook)
plus the env-var convention PARAM_{NAME} (documented in the reference but
implemented only as a file mount there; here both halves are real).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

SERVE_PORT = 8080
NOTEBOOK_PORT = 8888

# Workload exit codes (docs/fault-tolerance.md). EXIT_PREEMPTED is the
# trainer's "I was told to stop (SIGTERM/SIGINT/maintenance event) and wrote
# an emergency checkpoint" exit: the controller's train-Job podFailurePolicy
# restarts on it (bounded by spec.params.preemption_restarts) but treats any
# other non-zero exit as an application error and fails the Job immediately.
# Lives here (not in the trainer module) so the controller can reference it
# without importing JAX.
EXIT_PREEMPTED = 42
# SIGTERM's default disposition (128 + 15): what a trainer that never got to
# install its handler exits with when the kubelet kills it.
EXIT_SIGTERM_DEFAULT = 143


def content_dir() -> str:
    # Read dynamically so tests/tools can repoint /content via env.
    return os.environ.get("RBT_CONTENT_DIR", "/content")


def content_path(*parts: str) -> str:
    return os.path.join(content_dir(), *parts)


def data_dir() -> str:
    return content_path("data")


def model_dir() -> str:
    return content_path("model")


def artifacts_dir() -> str:
    return content_path("artifacts")


def load_params(path: Optional[str] = None) -> Dict[str, Any]:
    """Merge params.json (if present) with PARAM_* env vars (env wins).

    PARAM_FOO_BAR=x corresponds to params key "foo_bar". Values are parsed as
    JSON when possible, else kept as strings.
    """
    params: Dict[str, Any] = {}
    path = path or content_path("params.json")
    if os.path.exists(path):
        with open(path) as f:
            params.update(json.load(f))
    for key, val in os.environ.items():
        if not key.startswith("PARAM_"):
            continue
        name = key[len("PARAM_"):].lower()
        try:
            params[name] = json.loads(val)
        except (json.JSONDecodeError, ValueError):
            params[name] = val
    return params


def params_to_env(params: Dict[str, Any]) -> Dict[str, str]:
    """The operator-side half: params dict -> PARAM_* env map."""
    env = {}
    for key, val in params.items():
        name = "PARAM_" + re.sub(r"[^A-Za-z0-9]", "_", str(key)).upper()
        env[name] = val if isinstance(val, str) else json.dumps(val)
    return env
