"""Model architecture configs for the decoder-only transformer families.

The reference framework ships no model code at all — it schedules external
CUDA/PyTorch containers for families documented in its examples/ tree
(reference: examples/llama2-7b/finetuned-model.yaml, examples/falcon-40b/
server.yaml, examples/facebook-opt-125m/base-model.yaml). Here those families
are first-class: one `ModelConfig` describes any of them, and
`runbooks_tpu.models.transformer` consumes it.

All sizes chosen to map well onto the TPU MXU (multiples of 128 where the
family allows it); dtypes default to bfloat16 params/activations with float32
logits/softmax.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

# Allowed collective_matmul modes (framework-side single source of truth;
# the controller's jax-free validation table mirrors it, like quantize).
COLLECTIVE_MATMUL_MODES = ("off", "ring", "auto")
# Accepted spec.params spellings: snake_case params.json convention, the
# reference's camelCase spec style, and the PARAM_* env round-trip's
# lowercase — same set the controller validates and the trainer aliases.
COLLECTIVE_MATMUL_PARAM_KEYS = (
    "collective_matmul", "collectiveMatmul", "collectivematmul")


def check_collective_matmul(mode: str) -> str:
    """Validate a collective_matmul mode string (single source for the
    error message — transformer/serve/trainer all funnel through here,
    mirroring ops.quantization.resolve_quantize_mode)."""
    mode = str(mode)
    if mode not in COLLECTIVE_MATMUL_MODES:
        raise ValueError(
            f"unknown collective_matmul {mode!r}; expected "
            f"{'|'.join(COLLECTIVE_MATMUL_MODES)}")
    return mode


def resolve_collective_matmul_param(params: dict) -> Optional[str]:
    """First present spelling of the collective_matmul contract param,
    validated; None when the spec doesn't set it. Shared by the serving
    entrypoint and anything else reading a raw params dict, so a
    controller-validated spec can never be silently ignored over a
    spelling mismatch."""
    val = next((params[k] for k in COLLECTIVE_MATMUL_PARAM_KEYS
                if params.get(k) is not None), None)
    return None if val is None else check_collective_matmul(val)


# Speculative decoding on the serve decode path (serve/engine.py,
# docs/speculative-decoding.md): "off" | "ngram" (model-free
# prompt-lookup drafting + one batched verify forward). Same
# single-source-of-truth pattern as collective_matmul: the controller's
# jax-free validation table mirrors this enum.
SPECULATIVE_MODES = ("off", "ngram")


def check_speculative(mode: str) -> str:
    """Validate a speculative mode string (single source for the error
    message — engine, serve entrypoint, and trainer-adjacent readers all
    funnel through here)."""
    mode = str(mode)
    if mode not in SPECULATIVE_MODES:
        raise ValueError(
            f"unknown speculative {mode!r}; expected "
            f"{'|'.join(SPECULATIVE_MODES)}")
    return mode


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters for a decoder-only transformer."""

    name: str = "custom"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32            # < num_heads => GQA; == 1 => MQA
    head_dim: int = 128
    max_seq_len: int = 4096

    # Normalization
    norm_type: str = "rmsnorm"        # "rmsnorm" | "layernorm"
    norm_eps: float = 1e-5

    # MLP
    gated_mlp: bool = True            # SwiGLU-style gate (llama) vs plain MLP
    activation: str = "silu"          # "silu" | "gelu" | "relu"
    mlp_bias: bool = False

    # Mixture of Experts (models/moe.py). 0 experts = dense MLP. With
    # experts, the FFN becomes top-k-routed gated experts whose leading dim
    # shards over the "expert" mesh axis (expert parallelism).
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01        # load-balance loss weight

    # Attention
    attn_bias: bool = False
    qk_norm: bool = False
    logit_softcap: Optional[float] = None

    # Positional encoding
    position_type: str = "rope"       # "rope" | "alibi" | "learned"
    rope_theta: float = 10000.0

    # Block structure
    parallel_block: bool = False      # falcon/gpt-neox parallel attn+mlp
    shared_layer_norm: bool = True    # for parallel_block: one LN feeds both

    # Embeddings / head
    tie_embeddings: bool = False
    embed_scale: bool = False         # multiply embeddings by sqrt(hidden)

    # Attention implementation: "auto" picks ring when the active mesh has
    # a sequence axis > 1, else the Pallas flash kernel on TPU, else the
    # XLA reference path. Explicit: "xla" | "flash" | "ring".
    # Measured (v5e-1, bench-410m-d128 bs8x2048 train): flash 44.2% MFU vs
    # xla 23.1% — the XLA path materializes [b,h,s,s] f32 scores in HBM.
    attention_impl: str = "auto"
    # Flash kernel tile sizes (clamped to seq len). Bigger tiles amortize
    # the sequential grid and raise arithmetic intensity; v5e sweep:
    # 512x1024 best (44.2%), 1024x1024/512x512 within 4%; 1024x2048
    # exceeds the 16 MiB scoped-VMEM limit.
    flash_block_q: int = 512
    flash_block_k: int = 1024

    # Ring attention's per-step inner kernel. None = auto: the Pallas
    # flash kernel per rotated K/V block on TPU (out/lse merge forward, a
    # hand-written second ring pass backward — parallel/ring_attention.py),
    # the XLA einsum path elsewhere. Without the flash inner a
    # sequence-parallel mesh pays the HBM-materialized-scores cost that
    # flash exists to avoid (measured 0.10-0.23 vs 0.44 MFU single-chip).
    ring_flash_inner: Optional[bool] = None

    # Overlapped collective-matmul tensor parallelism
    # (ops/collective_matmul.py): decompose the per-layer TP collectives
    # into lax.ppermute ring steps hidden behind per-shard partial dots —
    # ring all-gather-matmul for the column-parallel q/k/v/gate/up
    # projections, matmul-reduce-scatter for the row-parallel o/down
    # projections (the post-dot all-reduce never exists; the residual
    # stream stays tensor-sharded between layers). "off" (default) keeps
    # the GSPMD collectives — the parity-oracle reference path; "ring"
    # requests the ring; "auto" = ring whenever the active mesh has
    # tensor > 1 ("ring" and "auto" resolve identically today). The
    # pipeline (stage > 1) path always keeps GSPMD TP (see
    # transformer.resolve_collective_matmul); weights whose shapes don't
    # divide the ring fall back per-matmul.
    collective_matmul: str = "off"
    # Circulate ring shards in both directions, halving sequential hop
    # count (takes effect at tensor > 2; a 2-ring has one hop either way).
    collective_matmul_bidirectional: bool = True

    # Embedding lookup as one-hot matmul instead of gather. Under a
    # tensor-sharded vocab, GSPMD partitions the matmul cleanly where the
    # gather forces an involuntary full-remat reshard. Measured on the
    # 8-way virtual mesh (fsdp2 x seq2 x tp2 train step): one-hot removes
    # the all-to-all + all 3 collective-permutes and 3 all-gathers from
    # the compiled HLO; a sequence-sharded mesh hits the same involuntary
    # reshard through the gather's scatter-add transpose. None = auto
    # (one-hot when the active mesh has tensor > 1 OR sequence > 1);
    # True/False force.
    embed_one_hot: Optional[bool] = None

    # Dtypes
    dtype: str = "bfloat16"           # activation dtype
    param_dtype: str = "float32"      # master param dtype

    # Weight-only quantization applied at load time (ops/quantization.py):
    # "none" | "int8" | "int4" (blockwise symmetric; int4 packs two
    # nibbles/byte). Mirrors the reference's Server `quantize: int4`
    # contract (reference: examples/llama2-70b/server.yaml) — the knob that
    # fits the 70B tier on a v5e-8 host and feeds the bandwidth-bound
    # decode path packed weights. The transformer dispatches on the param
    # type (QuantizedArray), so this field only drives the loaders.
    quantize: str = "none"
    # Serving KV-cache quantization block: int8 k/v + per-slot-per-head f32
    # scales. None = follow `quantize` (any quantized weight tier also
    # quantizes the cache); True/False force.
    quantize_kv: Optional[bool] = None

    # Speculative decoding on the serve decode path
    # (docs/speculative-decoding.md): "off" | "ngram". "ngram" turns on
    # model-free prompt-lookup drafting — a host-side per-slot n-gram
    # index over each request's prompt + generated tokens proposes up to
    # `draft_tokens` continuation tokens, and one batched [B, K+1]
    # verify forward scores them for every slot at once. Decode is
    # HBM-bandwidth-bound, so each verified-accepted draft token is
    # nearly free bandwidth-wise (the roofline gauge
    # xla_program_bandwidth_bound confirms it live).
    speculative: str = "off"
    # Draft window K: tokens proposed (and verified) per speculative
    # step. None = backend default (utils/hw.backend_tuning). Fixed at
    # engine construction — K is a static program shape, never a
    # per-request knob.
    draft_tokens: Optional[int] = None
    # Prompt-lookup n-gram sizes: the drafter matches the trailing
    # n-gram of the context for n from ngram_max down to ngram_min and
    # proposes the tokens that followed its most recent occurrence.
    ngram_max: int = 3
    ngram_min: int = 1

    # Multi-tenant batched LoRA serving (serve/lora_pool.py,
    # docs/multi-tenant-lora.md): adapter_pool > 0 gives the serve engine
    # an HBM-resident pool of that many LoRA adapters (plus one all-zero
    # trash lane for base-only rows) and compiles adapter-aware
    # prefill/decode/verify programs — per-request `adapter` then selects
    # a lane per slot inside ONE batched dispatch. 0 (default) = off: the
    # engine compiles the plain program set, and a Server-level
    # `adapter: <path>` folds the weights at load time instead
    # (train/lora.py apply_lora — the single-tenant baseline).
    adapter_pool: int = 0
    # Static rank bucket every pool lane is padded to. A per-tenant rank
    # would be a per-tenant compiled program; adapters trained at r <=
    # lora_rank zero-pad (exact), larger ranks are rejected at load.
    lora_rank: int = 8
    # Targets eligible for pooled injection (dotted paths into
    # params["layers"], same vocabulary as train/lora.py). Attention-only
    # by default, mirroring the training default.
    lora_targets: tuple = ("attn.wq", "attn.wk", "attn.wv", "attn.wo")

    # Training-time behavior. "nothing_saveable" = full remat (memory-safe
    # default); "dots_saveable" / "dots_with_no_batch_dims_saveable" save
    # matmul outputs; "save_attn_out" saves only the named per-layer
    # attention output (skips the O(s^2) attention recompute in bwd at
    # O(L*b*s*h) bf16 cost — the selective middle ground); "none" disables
    # remat entirely (all activations saved — single-chip HBM-rich configs
    # only).
    remat_policy: str = "nothing_saveable"

    # Pipeline parallelism: microbatches per step when the mesh has a
    # "stage" axis > 1 (parallel/pipeline.py). 0 = one microbatch per
    # stage; more microbatches shrink the (S-1)/(S+M-1) bubble.
    pipeline_microbatches: int = 0
    # Training schedule when stage > 1: "1f1b" (default) runs the explicit
    # fwd/bwd-interleaved schedule with in-flight activations bounded by
    # O(stages) regardless of microbatch count (parallel/pipeline.py:
    # pipeline_1f1b_grads); "gpipe" differentiates through the forward
    # pipeline (simpler, O(microbatches) live activations — the oracle the
    # 1F1B parity tests compare against).
    pipeline_schedule: str = "1f1b"

    @property
    def activation_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def parameter_dtype(self):
        return jnp.dtype(self.param_dtype)

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    @property
    def num_params(self) -> int:
        """Parameter count (embedding included once if tied)."""
        h, v = self.hidden_size, self.vocab_size
        embed = v * h
        head = 0 if self.tie_embeddings else v * h
        pos = v * 0
        if self.position_type == "learned":
            pos = self.max_seq_len * h
        attn = h * self.q_dim + 2 * h * self.kv_dim + self.q_dim * h
        if self.attn_bias:
            attn += self.q_dim + 2 * self.kv_dim + h
        mlp_mats = (2 if self.gated_mlp else 1) * h * self.intermediate_size
        mlp_mats += self.intermediate_size * h
        if self.mlp_bias:
            mlp_mats += (2 if self.gated_mlp else 1) * self.intermediate_size + h
        if self.moe_num_experts:
            # E expert copies of the (gated) FFN + the router matrix.
            mlp_mats = self.moe_num_experts * mlp_mats \
                + h * self.moe_num_experts
        norms_per_layer = h if (self.parallel_block and self.shared_layer_norm) else 2 * h
        if self.norm_type == "layernorm":
            norms_per_layer *= 2  # scale + bias
        per_layer = attn + mlp_mats + norms_per_layer
        final_norm = h * (2 if self.norm_type == "layernorm" else 1)
        return embed + head + pos + self.num_layers * per_layer + final_norm

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Forward-pass matmul FLOPs per token (2*N plus attention quadratic).

        Used for MFU accounting (train step multiplies by 3 for fwd+bwd).
        """
        s = seq_len or self.max_seq_len
        h = self.hidden_size
        attn_proj = 2 * (h * self.q_dim + 2 * h * self.kv_dim + self.q_dim * h)
        attn_scores = 2 * 2 * s * self.q_dim  # QK^T and PV, per token
        mlp = 2 * ((2 if self.gated_mlp else 1) * h * self.intermediate_size
                   + self.intermediate_size * h)
        if self.moe_num_experts:
            # top-k active experts per token + the router matmul.
            mlp = mlp * self.moe_top_k + 2 * h * self.moe_num_experts
        per_layer = attn_proj + attn_scores + mlp
        head = 2 * h * self.vocab_size
        return float(self.num_layers * per_layer + head)


def _llama(name, v=32000, h=4096, i=11008, l=32, q=32, kv=32, d=128, s=4096,
           theta=10000.0):
    return ModelConfig(
        name=name, vocab_size=v, hidden_size=h, intermediate_size=i,
        num_layers=l, num_heads=q, num_kv_heads=kv, head_dim=d, max_seq_len=s,
        norm_type="rmsnorm", norm_eps=1e-5, gated_mlp=True, activation="silu",
        position_type="rope", rope_theta=theta,
    )


def _falcon(name, v=65024, h=4544, l=32, q=71, kv=71, s=2048):
    # Falcon: parallel attention+MLP block, layernorm, no gate, GELU,
    # rotary embeddings, biases off for matmuls but LN has bias.
    return ModelConfig(
        name=name, vocab_size=v, hidden_size=h, intermediate_size=4 * h,
        num_layers=l, num_heads=q, num_kv_heads=kv, head_dim=h // q,
        max_seq_len=s, norm_type="layernorm", norm_eps=1e-5, gated_mlp=False,
        activation="gelu", position_type="rope", parallel_block=True,
        tie_embeddings=True,
    )


def _opt(name, v=50272, h=768, i=3072, l=12, q=12, s=2048):
    return ModelConfig(
        name=name, vocab_size=v, hidden_size=h, intermediate_size=i,
        num_layers=l, num_heads=q, num_kv_heads=q, head_dim=h // q,
        max_seq_len=s, norm_type="layernorm", norm_eps=1e-5, gated_mlp=False,
        activation="relu", position_type="learned", attn_bias=True,
        mlp_bias=True, tie_embeddings=True,
    )


def _gemma(name, v=256000, h=2048, i=16384, l=18, q=8, kv=1, d=256, s=8192):
    # Gemma: GeGLU (gated tanh-gelu), embeddings scaled by sqrt(h), tied
    # head, RMSNorm with a (1 + w) scale (handled in the converter).
    return ModelConfig(
        name=name, vocab_size=v, hidden_size=h, intermediate_size=i,
        num_layers=l, num_heads=q, num_kv_heads=kv, head_dim=d,
        max_seq_len=s, norm_type="rmsnorm", norm_eps=1e-6, gated_mlp=True,
        activation="gelu", position_type="rope", tie_embeddings=True,
        embed_scale=True,
    )


def _gpt2(name, v=50257, h=768, i=3072, l=12, q=12, s=1024):
    return ModelConfig(
        name=name, vocab_size=v, hidden_size=h, intermediate_size=i,
        num_layers=l, num_heads=q, num_kv_heads=q, head_dim=h // q,
        max_seq_len=s, norm_type="layernorm", norm_eps=1e-5, gated_mlp=False,
        activation="gelu", position_type="learned", attn_bias=True,
        mlp_bias=True, tie_embeddings=True,
    )


# Registry mirrors the reference's documented example configs
# (reference: examples/ tree — llama2-7b, llama2-70b, falcon-7b/40b,
# facebook-opt-125m) plus debug sizes for tests/benchmarks.
CONFIGS = {
    # Llama-2 family (reference: examples/llama2-7b, examples/llama2-70b)
    "llama2-7b": _llama("llama2-7b"),
    "llama2-13b": _llama("llama2-13b", h=5120, i=13824, l=40, q=40, kv=40, d=128),
    "llama2-70b": _llama("llama2-70b", h=8192, i=28672, l=80, q=64, kv=8, d=128),
    # Llama-3-ish long-context config (net-new capability; SURVEY.md §5.7)
    "llama3-8b": _llama("llama3-8b", v=128256, h=4096, i=14336, l=32, q=32,
                        kv=8, d=128, s=8192, theta=500000.0),
    # Falcon family (reference: examples/falcon-7b-instruct, examples/falcon-40b)
    # 7b: multi-query (1 kv head), single shared layernorm per block;
    # 40b: 8 kv groups, separate attn/mlp layernorms.
    "falcon-7b": _falcon("falcon-7b", kv=1),
    "falcon-40b": dataclasses.replace(
        _falcon("falcon-40b", h=8192, l=60, q=128, kv=8),
        shared_layer_norm=False),
    # OPT (reference: examples/facebook-opt-125m — the CPU smoke model)
    "opt-125m": _opt("opt-125m"),
    "opt-1.3b": _opt("opt-1.3b", h=2048, i=8192, l=24, q=32),
    # Mixtral-style MoE (net-new: the reference has no MoE; expert
    # parallelism over the "expert" mesh axis — models/moe.py)
    "mixtral-8x7b": dataclasses.replace(
        _llama("mixtral-8x7b", v=32000, h=4096, i=14336, l=32, q=32, kv=8,
               d=128, s=32768, theta=1e6),
        moe_num_experts=8, moe_top_k=2),
    # Gemma (MQA 2b / MHA 7b; GeGLU, scaled embeddings, tied head)
    "gemma-2b": _gemma("gemma-2b"),
    "gemma-7b": _gemma("gemma-7b", h=3072, i=24576, l=28, q=16, kv=16),
    # GPT-2 (fused-qkv Conv1D checkpoints; learned positions)
    "gpt2": _gpt2("gpt2"),
    "gpt2-xl": _gpt2("gpt2-xl", h=1600, i=6400, l=48, q=25),
    # Debug/bench sizes
    "debug": _llama("debug", v=512, h=128, i=384, l=2, q=4, kv=2, d=32, s=256),
    "bench-1b": _llama("bench-1b", h=2048, i=5632, l=22, q=16, kv=16, d=128, s=2048),
    "bench-410m": _llama("bench-410m", h=1024, i=2816, l=24, q=16, kv=16, d=64, s=2048),
    # Same params/FLOPs as bench-410m but 8 heads x d128: wider MXU
    # contractions (the 128x128 systolic array wants k>=128).
    "bench-410m-d128": _llama("bench-410m-d128", h=1024, i=2816, l=24, q=8,
                              kv=8, d=128, s=2048),
}


def get_config(name: str, **overrides) -> ModelConfig:
    if name not in CONFIGS:
        raise KeyError(f"unknown model config {name!r}; known: {sorted(CONFIGS)}")
    cfg = CONFIGS[name]
    return dataclasses.replace(cfg, **overrides) if overrides else cfg
