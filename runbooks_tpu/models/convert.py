"""HuggingFace checkpoint conversion: state dict -> runbooks-tpu param tree.

The reference delegates model import to an external image
(substratusai/model-loader-huggingface — reference: examples/
facebook-opt-125m/base-model.yaml); here conversion is in-framework so the
loader workload (models/loader.py) can import Llama/Falcon/OPT checkpoints
into the stacked-layer layout natively.

Conventions verified against HF implementations by the parity tests
(tests/test_convert.py builds tiny HF models and compares logits):
- Llama: HF rotate_half == our split-half RoPE, weights transpose directly.
- Falcon: fused query_key_value is unfused; 7b-style MQA (1 kv head) and
  40b-style grouped-KV both supported; parallel block with shared or split
  layernorms.
- OPT: learned positions with HF's +2 row offset dropped; pre-LN variant.
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np

from runbooks_tpu.models.config import ModelConfig

Array = np.ndarray
StateDict = Mapping[str, Array]


def _t(x: Array) -> Array:
    return np.ascontiguousarray(np.asarray(x).T)


def _stack(arrs) -> Array:
    return np.stack([np.asarray(a) for a in arrs])


def convert_llama(cfg: ModelConfig, sd: StateDict) -> Dict:
    L = cfg.num_layers
    p = lambda i, name: np.asarray(sd[f"model.layers.{i}.{name}"])
    params = {
        "embed": np.asarray(sd["model.embed_tokens.weight"]),
        "final_norm": {"scale": np.asarray(sd["model.norm.weight"])},
        "layers": {
            "attn": {
                "wq": _stack(_t(p(i, "self_attn.q_proj.weight"))
                             for i in range(L)),
                "wk": _stack(_t(p(i, "self_attn.k_proj.weight"))
                             for i in range(L)),
                "wv": _stack(_t(p(i, "self_attn.v_proj.weight"))
                             for i in range(L)),
                "wo": _stack(_t(p(i, "self_attn.o_proj.weight"))
                             for i in range(L)),
            },
            "mlp": {
                "wi_gate": _stack(_t(p(i, "mlp.gate_proj.weight"))
                                  for i in range(L)),
                "wi_up": _stack(_t(p(i, "mlp.up_proj.weight"))
                                for i in range(L)),
                "wo": _stack(_t(p(i, "mlp.down_proj.weight"))
                             for i in range(L)),
            },
            "ln1": {"scale": _stack(p(i, "input_layernorm.weight")
                                    for i in range(L))},
            "ln2": {"scale": _stack(p(i, "post_attention_layernorm.weight")
                                    for i in range(L))},
        },
    }
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight")
        params["head"] = (_t(head) if head is not None
                          else _t(params["embed"]))
    return params


def convert_falcon(cfg: ModelConfig, sd: StateDict) -> Dict:
    L, h = cfg.num_layers, cfg.hidden_size
    nq, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    rep = nq // nkv

    def unfuse(i):
        w = np.asarray(sd[f"transformer.h.{i}.self_attention"
                          f".query_key_value.weight"])   # [(nkv*(rep+2))*d, h]
        w = w.reshape(nkv, rep + 2, d, h)
        q = w[:, :rep].reshape(nq * d, h)
        k = w[:, rep].reshape(nkv * d, h)
        v = w[:, rep + 1].reshape(nkv * d, h)
        return _t(q), _t(k), _t(v)

    qs, ks, vs = zip(*(unfuse(i) for i in range(L)))
    g = lambda i, name: np.asarray(sd[f"transformer.h.{i}.{name}"])
    layers: Dict = {
        "attn": {
            "wq": _stack(qs), "wk": _stack(ks), "wv": _stack(vs),
            "wo": _stack(_t(g(i, "self_attention.dense.weight"))
                         for i in range(L)),
        },
        "mlp": {
            "wi": _stack(_t(g(i, "mlp.dense_h_to_4h.weight"))
                         for i in range(L)),
            "wo": _stack(_t(g(i, "mlp.dense_4h_to_h.weight"))
                         for i in range(L)),
        },
    }
    if cfg.shared_layer_norm:
        layers["ln1"] = {
            "scale": _stack(g(i, "input_layernorm.weight")
                            for i in range(L)),
            "bias": _stack(g(i, "input_layernorm.bias") for i in range(L)),
        }
    else:
        layers["ln1"] = {
            "scale": _stack(g(i, "ln_attn.weight") for i in range(L)),
            "bias": _stack(g(i, "ln_attn.bias") for i in range(L)),
        }
        layers["ln2"] = {
            "scale": _stack(g(i, "ln_mlp.weight") for i in range(L)),
            "bias": _stack(g(i, "ln_mlp.bias") for i in range(L)),
        }
    return {
        "embed": np.asarray(sd["transformer.word_embeddings.weight"]),
        "final_norm": {
            "scale": np.asarray(sd["transformer.ln_f.weight"]),
            "bias": np.asarray(sd["transformer.ln_f.bias"]),
        },
        "layers": layers,
    }


def convert_opt(cfg: ModelConfig, sd: StateDict) -> Dict:
    L = cfg.num_layers
    g = lambda i, name: np.asarray(sd[f"model.decoder.layers.{i}.{name}"])
    params = {
        "embed": np.asarray(sd["model.decoder.embed_tokens.weight"]),
        # HF OPT offsets learned positions by 2 rows.
        "pos_embed": np.asarray(
            sd["model.decoder.embed_positions.weight"])[2:],
        "final_norm": {
            "scale": np.asarray(sd["model.decoder.final_layer_norm.weight"]),
            "bias": np.asarray(sd["model.decoder.final_layer_norm.bias"]),
        },
        "layers": {
            "attn": {
                "wq": _stack(_t(g(i, "self_attn.q_proj.weight"))
                             for i in range(L)),
                "wk": _stack(_t(g(i, "self_attn.k_proj.weight"))
                             for i in range(L)),
                "wv": _stack(_t(g(i, "self_attn.v_proj.weight"))
                             for i in range(L)),
                "wo": _stack(_t(g(i, "self_attn.out_proj.weight"))
                             for i in range(L)),
                "bq": _stack(g(i, "self_attn.q_proj.bias")
                             for i in range(L)),
                "bk": _stack(g(i, "self_attn.k_proj.bias")
                             for i in range(L)),
                "bv": _stack(g(i, "self_attn.v_proj.bias")
                             for i in range(L)),
                "bo": _stack(g(i, "self_attn.out_proj.bias")
                             for i in range(L)),
            },
            "mlp": {
                "wi": _stack(_t(g(i, "fc1.weight")) for i in range(L)),
                "bi": _stack(g(i, "fc1.bias") for i in range(L)),
                "wo": _stack(_t(g(i, "fc2.weight")) for i in range(L)),
                "bo": _stack(g(i, "fc2.bias") for i in range(L)),
            },
            "ln1": {
                "scale": _stack(g(i, "self_attn_layer_norm.weight")
                                for i in range(L)),
                "bias": _stack(g(i, "self_attn_layer_norm.bias")
                               for i in range(L)),
            },
            "ln2": {
                "scale": _stack(g(i, "final_layer_norm.weight")
                                for i in range(L)),
                "bias": _stack(g(i, "final_layer_norm.bias")
                               for i in range(L)),
            },
        },
    }
    return params


def convert_mixtral(cfg: ModelConfig, sd: StateDict) -> Dict:
    """Mixtral = llama attention + per-layer MoE FFN. HF layout:
    block_sparse_moe.gate.weight [E, h] (router) and
    block_sparse_moe.experts.{e}.w1/w3/w2 (gate/up/down)."""
    L, E = cfg.num_layers, cfg.moe_num_experts
    p = lambda i, name: np.asarray(sd[f"model.layers.{i}.{name}"])

    def expert(i, e, w):
        return _t(p(i, f"block_sparse_moe.experts.{e}.{w}.weight"))

    params = {
        "embed": np.asarray(sd["model.embed_tokens.weight"]),
        "final_norm": {"scale": np.asarray(sd["model.norm.weight"])},
        "layers": {
            "attn": {
                "wq": _stack(_t(p(i, "self_attn.q_proj.weight"))
                             for i in range(L)),
                "wk": _stack(_t(p(i, "self_attn.k_proj.weight"))
                             for i in range(L)),
                "wv": _stack(_t(p(i, "self_attn.v_proj.weight"))
                             for i in range(L)),
                "wo": _stack(_t(p(i, "self_attn.o_proj.weight"))
                             for i in range(L)),
            },
            "moe": {
                "router": _stack(_t(p(i, "block_sparse_moe.gate.weight"))
                                 for i in range(L)),      # [L, h, E]
                "wi_gate": _stack(
                    _stack(expert(i, e, "w1") for e in range(E))
                    for i in range(L)),                   # [L, E, h, m]
                "wi_up": _stack(
                    _stack(expert(i, e, "w3") for e in range(E))
                    for i in range(L)),
                "wo": _stack(
                    _stack(expert(i, e, "w2") for e in range(E))
                    for i in range(L)),                   # [L, E, m, h]
            },
            "ln1": {"scale": _stack(p(i, "input_layernorm.weight")
                                    for i in range(L))},
            "ln2": {"scale": _stack(p(i, "post_attention_layernorm.weight")
                                    for i in range(L))},
        },
    }
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight")
        params["head"] = (_t(head) if head is not None
                          else _t(params["embed"]))
    return params


def convert_gemma(cfg: ModelConfig, sd: StateDict) -> Dict:
    """Gemma uses llama key names but RMSNorm computes x * (1 + w): fold
    the +1 into the stored scales. Head is tied to the embedding."""
    params = convert_llama(cfg, sd)
    params["final_norm"]["scale"] = params["final_norm"]["scale"] + 1.0
    for ln in ("ln1", "ln2"):
        params["layers"][ln]["scale"] = params["layers"][ln]["scale"] + 1.0
    return params


def convert_gpt2(cfg: ModelConfig, sd: StateDict) -> Dict:
    """GPT-2: Conv1D weights are already [in, out] (no transpose), the
    attention projection is a fused c_attn [h, 3h] split into q/k/v, and
    learned positions have no row offset (unlike OPT's +2)."""
    L, h = cfg.num_layers, cfg.hidden_size
    g = lambda i, name: np.asarray(sd[f"transformer.h.{i}.{name}"])

    def split_qkv(i):
        w = g(i, "attn.c_attn.weight")        # [h, 3h]
        b = g(i, "attn.c_attn.bias")          # [3h]
        return (w[:, :h], w[:, h:2 * h], w[:, 2 * h:],
                b[:h], b[h:2 * h], b[2 * h:])

    qs, ks, vs, bqs, bks, bvs = zip(*(split_qkv(i) for i in range(L)))
    return {
        "embed": np.asarray(sd["transformer.wte.weight"]),
        "pos_embed": np.asarray(sd["transformer.wpe.weight"]),
        "final_norm": {
            "scale": np.asarray(sd["transformer.ln_f.weight"]),
            "bias": np.asarray(sd["transformer.ln_f.bias"]),
        },
        "layers": {
            "attn": {
                "wq": _stack(qs), "wk": _stack(ks), "wv": _stack(vs),
                "bq": _stack(bqs), "bk": _stack(bks), "bv": _stack(bvs),
                "wo": _stack(g(i, "attn.c_proj.weight") for i in range(L)),
                "bo": _stack(g(i, "attn.c_proj.bias") for i in range(L)),
            },
            "mlp": {
                "wi": _stack(g(i, "mlp.c_fc.weight") for i in range(L)),
                "bi": _stack(g(i, "mlp.c_fc.bias") for i in range(L)),
                "wo": _stack(g(i, "mlp.c_proj.weight") for i in range(L)),
                "bo": _stack(g(i, "mlp.c_proj.bias") for i in range(L)),
            },
            "ln1": {
                "scale": _stack(g(i, "ln_1.weight") for i in range(L)),
                "bias": _stack(g(i, "ln_1.bias") for i in range(L)),
            },
            "ln2": {
                "scale": _stack(g(i, "ln_2.weight") for i in range(L)),
                "bias": _stack(g(i, "ln_2.bias") for i in range(L)),
            },
        },
    }


CONVERTERS = {
    "mixtral": convert_mixtral,  # before "llama": shares its attention
    "gemma": convert_gemma,      # likewise llama-keyed
    "gpt2": convert_gpt2,
    "llama": convert_llama,
    "falcon": convert_falcon,
    "opt": convert_opt,
}


def family_of(cfg: ModelConfig) -> str:
    name = cfg.name.lower()
    for fam in CONVERTERS:
        if fam in name:
            return fam
    # Structural fallback
    if cfg.moe_num_experts:
        return "mixtral"
    if cfg.parallel_block:
        return "falcon"
    if cfg.position_type == "learned":
        return "opt"
    return "llama"


def convert(cfg: ModelConfig, state_dict: StateDict,
            dtype: str = "float32", quantize: str = "none") -> Dict:
    """HF state dict -> param tree (numpy, cast to `dtype`).

    quantize="int8"|"int4" applies blockwise weight-only quantization to
    the attention/MLP matmuls right after conversion (ops/quantization.py),
    walking stacked weights one layer at a time so importing a 70B-class
    checkpoint peaks at ~one f32 layer above the packed size."""
    import jax

    params = CONVERTERS[family_of(cfg)](cfg, state_dict)
    params = jax.tree.map(lambda x: np.asarray(x, dtype=dtype), params)
    if quantize != "none":
        from runbooks_tpu.ops.quantization import quantize_params

        params = quantize_params(params, quantize)
    return params


def load_torch_state_dict(model_dir: str) -> Dict[str, Array]:
    """Read a local HF checkpoint directory (safetensors preferred, torch
    .bin fallback) into a numpy state dict."""
    import glob
    import os

    sd: Dict[str, Array] = {}
    st_files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if st_files:
        from safetensors import safe_open

        for path in st_files:
            with safe_open(path, framework="np") as f:
                for key in f.keys():
                    sd[key] = f.get_tensor(key)
        return sd
    import torch

    for path in sorted(glob.glob(os.path.join(model_dir, "*.bin"))):
        part = torch.load(path, map_location="cpu", weights_only=True)
        for key, val in part.items():
            sd[key] = val.float().numpy()
    if not sd:
        raise FileNotFoundError(f"no safetensors/bin weights in {model_dir}")
    return sd
