"""Mixture-of-Experts layer with expert parallelism (GShard/Switch-style).

TPU-first design: routing is expressed as dense one-hot dispatch/combine
einsums over a static expert *capacity* — no dynamic shapes, no scatter —
so XLA tiles everything onto the MXU, and sharding the expert leading dim
over the "expert" mesh axis turns the dispatch/combine contractions into
cross-device token exchange (all-to-all family) handled by GSPMD.
(Reference has no MoE — SURVEY §2a — this is net-new capability; pattern
references: the GShard/Switch dispatch formulation in PAPERS.md.)

Tokens beyond an expert's capacity are dropped (contribute zero); size
capacity_factor so drops are rare. The router aux (load-balance) loss is
returned to the caller and added to the training loss with moe_aux_coef.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

from runbooks_tpu.parallel.sharding import with_logical_constraint


def moe_capacity(cfg, n_tokens: int) -> int:
    """Static per-expert token capacity."""
    cap = math.ceil(cfg.moe_top_k * n_tokens / cfg.moe_num_experts
                    * cfg.moe_capacity_factor)
    return max(int(cap), 1)


def _dispatch_combine(cfg, probs: jax.Array, n_tokens: int):
    """Top-k routing -> (dispatch [T,E,C] bool-ish, combine [T,E,C] float,
    aux load-balance scalar). Choice-major priority: every token's first
    choice is placed before any token's second choice (Switch convention),
    so capacity pressure drops low-weight assignments first."""
    E = cfg.moe_num_experts
    k = cfg.moe_top_k
    C = moe_capacity(cfg, n_tokens)

    gate_vals, gate_idx = jax.lax.top_k(probs, k)          # [T,k]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)

    onehot = jax.nn.one_hot(gate_idx, E, dtype=probs.dtype)  # [T,k,E]
    # Choice-major flatten: [k*T, E], first choices of all tokens first.
    flat = onehot.transpose(1, 0, 2).reshape(k * n_tokens, E)
    pos_in_expert = (jnp.cumsum(flat, axis=0) - flat)        # [kT, E]
    pos = (pos_in_expert * flat).sum(-1).astype(jnp.int32)   # [kT]
    keep = (pos < C).astype(probs.dtype)
    slot = jax.nn.one_hot(pos, C, dtype=probs.dtype)         # [kT, C]
    disp_flat = flat[:, :, None] * slot[:, None, :] * keep[:, None, None]
    dispatch = disp_flat.reshape(k, n_tokens, E, C).sum(0)   # [T,E,C]
    weights = gate_vals.transpose(1, 0).reshape(k * n_tokens)
    comb_flat = disp_flat * weights[:, None, None]
    combine = comb_flat.reshape(k, n_tokens, E, C).sum(0)    # [T,E,C]

    # Switch load-balance loss: E * sum_e mean_prob_e * mean_assigned_e
    # (first-choice assignment fraction), minimized by uniform routing.
    me = probs.mean(axis=0)                                  # [E]
    first = jax.nn.one_hot(gate_idx[:, 0], E, dtype=probs.dtype)
    ce = first.mean(axis=0)                                  # [E]
    aux = (E * (me * ce).sum()).astype(jnp.float32)
    return dispatch, combine, aux


def moe_block(cfg, p, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """MoE FFN over x [b, s, h] -> (out [b, s, h], aux loss scalar)."""
    ad = cfg.activation_dtype
    b, s, h = x.shape
    T = b * s
    xt = x.reshape(T, h)

    # Router in f32: routing decisions are precision-sensitive.
    logits = jnp.einsum("th,he->te", xt.astype(jnp.float32),
                        p["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine, aux = _dispatch_combine(cfg, probs, T)
    dispatch = dispatch.astype(ad)
    combine = combine.astype(ad)

    # Token exchange: with E sharded over the "expert" axis and T over
    # data/fsdp, these contractions are the all-to-alls.
    expert_in = jnp.einsum("tec,th->ech", dispatch, xt.astype(ad),
                           preferred_element_type=jnp.float32).astype(ad)
    expert_in = with_logical_constraint(
        expert_in, ("act_experts", None, None))

    from runbooks_tpu.models.transformer import _activation

    gate = jnp.einsum("ech,ehm->ecm", expert_in, p["wi_gate"].astype(ad),
                      preferred_element_type=jnp.float32).astype(ad)
    up = jnp.einsum("ech,ehm->ecm", expert_in, p["wi_up"].astype(ad),
                    preferred_element_type=jnp.float32).astype(ad)
    hidden = _activation(cfg, gate) * up
    hidden = with_logical_constraint(
        hidden, ("act_experts", None, "act_mlp"))
    out_e = jnp.einsum("ecm,emh->ech", hidden, p["wo"].astype(ad),
                       preferred_element_type=jnp.float32).astype(ad)

    out = jnp.einsum("tec,ech->th", combine, out_e,
                     preferred_element_type=jnp.float32).astype(ad)
    return out.reshape(b, s, h), aux


def moe_logical_axes():
    """Logical axes for the stacked [L, ...] MoE params."""
    return {
        "router": ("layers", "embed", "experts"),
        "wi_gate": ("layers", "experts", "embed", "mlp"),
        "wi_up": ("layers", "experts", "embed", "mlp"),
        "wo": ("layers", "experts", "mlp", "embed"),
    }
