"""Model-loader workload: import weights into the artifact store.

The TPU-native replacement for the reference's external loader image
(substratusai/model-loader-huggingface — reference: examples/
facebook-opt-125m/base-model.yaml). Runs under the container contract:

  params.json: {"model": "<config name>",
                "source": "huggingface" | "dir" | "random",
                "hf_name": "facebook/opt-125m",   # for source=huggingface
                "dir": "/content/model"}          # for source=dir

Writes an orbax checkpoint {"params": ...} + model.json metadata under
/content/artifacts, which the trainer (as base model) and server mount and
restore.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.convert import convert, load_torch_state_dict
from runbooks_tpu.train.checkpoint import CheckpointManager
from runbooks_tpu.utils import contract


def load_weights(params_cfg: dict):
    cfg = get_config(params_cfg.get("model", "debug"),
                     **params_cfg.get("model_overrides", {}))
    source = params_cfg.get("source", "random")
    if source == "huggingface":
        hf_name = params_cfg["hf_name"]
        from huggingface_hub import snapshot_download  # ships w/ transformers

        local_dir = snapshot_download(
            hf_name, allow_patterns=["*.safetensors", "*.bin", "*.json",
                                     "tokenizer*"])
        state_dict = load_torch_state_dict(local_dir)
        weights = convert(cfg, state_dict, dtype=cfg.param_dtype)
    elif source == "dir":
        model_dir = params_cfg.get("dir", contract.model_dir())
        state_dict = load_torch_state_dict(model_dir)
        weights = convert(cfg, state_dict, dtype=cfg.param_dtype)
    elif source == "random":
        from runbooks_tpu.models.transformer import init_params

        weights = init_params(cfg, jax.random.key(
            int(params_cfg.get("seed", 0))))
    else:
        raise ValueError(f"unknown source {source!r}")
    return cfg, weights


def main() -> int:
    params_cfg = contract.load_params()
    cfg, weights = load_weights(params_cfg)

    artifacts = params_cfg.get("artifacts_dir") or contract.artifacts_dir()
    os.makedirs(artifacts, exist_ok=True)
    mgr = CheckpointManager(artifacts, async_save=False)
    mgr.save(0, {"params": weights}, force=True)
    mgr.wait()
    mgr.close()

    n_params = sum(int(np.prod(np.shape(x)))
                   for x in jax.tree.leaves(weights))
    meta = {"model": cfg.name, "num_params": n_params,
            "vocab_size": cfg.vocab_size,
            "source": params_cfg.get("source", "random")}
    with open(os.path.join(artifacts, "model.json"), "w") as f:
        json.dump(meta, f, indent=2)
    print(json.dumps({"done": True, **meta}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
