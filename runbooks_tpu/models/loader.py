"""Model-loader workload: import weights into the artifact store.

The TPU-native replacement for the reference's external loader image
(substratusai/model-loader-huggingface — reference: examples/
facebook-opt-125m/base-model.yaml). Runs under the container contract:

  params.json: {"model": "<config name>",
                "source": "huggingface" | "dir" | "random",
                "hf_name": "facebook/opt-125m",   # for source=huggingface
                "dir": "/content/model"}          # for source=dir

Writes an orbax checkpoint {"params": ...} + model.json metadata under
/content/artifacts, which the trainer (as base model) and server mount and
restore.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.convert import convert, load_torch_state_dict
from runbooks_tpu.train.checkpoint import CheckpointManager
from runbooks_tpu.utils import contract


def load_weights(params_cfg: dict):
    """params.quantize ("none"|"int8"|"int4" — the reference contract's
    `quantize:` field) imports the checkpoint straight into the packed
    representation: HF sources quantize layer-by-layer during conversion,
    so a 70B import never holds both a full-precision and a packed copy."""
    from runbooks_tpu.ops.quantization import resolve_quantize_mode

    cfg = get_config(params_cfg.get("model", "debug"),
                     **params_cfg.get("model_overrides", {}))
    quantize = resolve_quantize_mode(params_cfg, cfg)
    import dataclasses

    cfg = dataclasses.replace(cfg, quantize=quantize)
    source = params_cfg.get("source", "random")
    if source == "huggingface":
        hf_name = params_cfg["hf_name"]
        from huggingface_hub import snapshot_download  # ships w/ transformers

        local_dir = snapshot_download(
            hf_name, allow_patterns=["*.safetensors", "*.bin", "*.json",
                                     "tokenizer*"])
        state_dict = load_torch_state_dict(local_dir)
        weights = convert(cfg, state_dict, dtype=cfg.param_dtype,
                          quantize=quantize)
    elif source == "dir":
        model_dir = params_cfg.get("dir", contract.model_dir())
        state_dict = load_torch_state_dict(model_dir)
        weights = convert(cfg, state_dict, dtype=cfg.param_dtype,
                          quantize=quantize)
    elif source == "random":
        from runbooks_tpu.models.transformer import init_params

        weights = init_params(cfg, jax.random.key(
            int(params_cfg.get("seed", 0))))
        if quantize != "none":
            from runbooks_tpu.ops.quantization import quantize_params

            weights = quantize_params(weights, quantize)
    else:
        raise ValueError(f"unknown source {source!r}")
    return cfg, weights


def main() -> int:
    params_cfg = contract.load_params()
    cfg, weights = load_weights(params_cfg)

    artifacts = params_cfg.get("artifacts_dir") or contract.artifacts_dir()
    os.makedirs(artifacts, exist_ok=True)
    mgr = CheckpointManager(artifacts, async_save=False)
    # QuantizedArray nodes save as plain dicts (orbax restores without a
    # target); serve/api.load_model reconstructs them on restore.
    from runbooks_tpu.ops.quantization import (
        pack_for_checkpoint,
        tree_weight_bytes,
    )

    mgr.save(0, {"params": pack_for_checkpoint(weights)}, force=True)
    mgr.wait()
    mgr.close()

    from runbooks_tpu.ops.quantization import QuantizedArray

    def _count(x):
        if isinstance(x, QuantizedArray):  # logical (pre-packing) count
            return int(np.prod(x.values.shape[:-2])) * x.in_dim \
                * x.values.shape[-1]
        return int(np.prod(np.shape(x)))

    n_params = sum(_count(x) for x in jax.tree.leaves(
        weights, is_leaf=lambda x: isinstance(x, QuantizedArray)))
    meta = {"model": cfg.name, "num_params": n_params,
            "vocab_size": cfg.vocab_size,
            "quantize": cfg.quantize,
            "weight_bytes": tree_weight_bytes(weights),
            "source": params_cfg.get("source", "random")}
    with open(os.path.join(artifacts, "model.json"), "w") as f:
        json.dump(meta, f, indent=2)
    print(json.dumps({"done": True, **meta}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
