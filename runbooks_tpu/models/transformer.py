"""Decoder-only transformer: functional JAX, one definition for every family.

Design (TPU-first, not a port — the reference contains no model code and
delegates compute to external containers, SURVEY.md §2a):

- Params are a plain pytree: {"embed": …, "layers": {…stacked [L, …] arrays…},
  "final_norm": …, "head": …}. Layers are *stacked* and the forward pass scans
  over them with ``lax.scan`` — one compiled block instead of L unrolled ones
  (faster compiles, natural remat boundary, later the unit of pipeline
  parallelism).
- Every major activation gets a logical sharding constraint
  (runbooks_tpu.parallel.sharding) so pjit can propagate DP/FSDP/SP/TP layouts
  from a rule table.
- fp32 softmax/norms/logits; bf16 everything else by default.
- One code path serves training (no cache) and inference (KVCache dataclass),
  including chunked prefill: attention masking is by *absolute position*, so
  sequence-parallel shards and cache decode use the same op.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from runbooks_tpu.models.config import ModelConfig
from runbooks_tpu.ops.attention import (
    alibi_slopes,
    dot_product_attention,
    make_attention_mask,
)
from runbooks_tpu.ops.norms import layer_norm, rms_norm
from runbooks_tpu.ops.quantization import (
    QuantizedArray,
    dequantize_kv,
    quantize_kv,
    quantized_matmul,
)
from runbooks_tpu.ops.rotary import apply_rope
from runbooks_tpu.parallel.sharding import with_logical_constraint

Params = Dict[str, Any]

# Flash cached-prefill only pays off once the query block is at least one
# sublane tile; below this the XLA path's mask build is noise anyway.
FLASH_CACHED_PREFILL_MIN_Q = 16


def _matmul(x: jax.Array, w, ad, ring: Optional[str] = None,
            ring_bidir: bool = True) -> jax.Array:
    """x[..., k] @ w[k, out] in the activation dtype, f32 accumulation.
    Weight-only-quantized layers (QuantizedArray) take the fused
    dequant-matmul: integer blocks enter the einsum directly and the
    per-block scales apply post-dot (ops/quantization.py), so the bf16
    weight is never materialized — the point of weight-only quantization
    on the bandwidth-bound decode path.

    ring ("ag" column-parallel | "rs" row-parallel, None = off) selects
    the overlapped collective matmul (ops/collective_matmul.py): the
    tensor-parallel collective decomposes into ppermute ring steps hidden
    behind per-shard partial dots instead of GSPMD's blocking
    all-gather/all-reduce. Falls back to the GSPMD path per-weight when
    the shapes don't divide the ring (ring_supported)."""
    if ring is not None:
        from runbooks_tpu.ops.collective_matmul import (
            matmul_reduce_scatter,
            ring_ag_matmul,
            ring_supported,
        )
        from runbooks_tpu.parallel.sharding import _current_mesh

        mesh = _current_mesh()
        if ring_supported(ring, x.shape, w, mesh):
            fn = ring_ag_matmul if ring == "ag" else matmul_reduce_scatter
            return fn(x, w, mesh=mesh, compute_dtype=ad,
                      bidirectional=ring_bidir).astype(ad)
    if isinstance(w, QuantizedArray):
        return quantized_matmul(x, w, compute_dtype=ad).astype(ad)
    return jnp.einsum("...k,ko->...o", x, w.astype(ad),
                      preferred_element_type=jnp.float32).astype(ad)


def resolve_collective_matmul(cfg: ModelConfig) -> bool:
    """Resolve cfg.collective_matmul ("off" | "ring" | "auto") against the
    active mesh: the ring path runs only when the mesh tensor-parallelizes
    ("auto" and "ring" are equivalent today — "ring" states intent, "auto"
    may later grow heuristics). The pipeline (stage > 1) path keeps GSPMD
    tensor parallelism: its blocks already run inside a stage-manual
    shard_map, and nesting the ring's manual region there trips the pinned
    jaxlib's partial-manual SPMD limitation (see tests/conftest.py
    probe)."""
    from runbooks_tpu.models.config import check_collective_matmul

    mode = check_collective_matmul(cfg.collective_matmul)
    if mode == "off":
        return False
    from runbooks_tpu.parallel.sharding import _current_mesh

    mesh = _current_mesh()
    if mesh is None or int(mesh.shape.get("tensor", 1)) <= 1:
        return False
    if int(mesh.shape.get("stage", 1)) > 1:
        return False
    return True


def _act_embed_rules(ring_on: bool):
    """Sharding rules for the residual stream. With the ring path on, the
    hidden axis of every [b, s, h] activation shards over tensor: the
    row-parallel matmul-reduce-scatter leaves it that way and the next
    column-parallel ring re-gathers it behind its dots — an exposed
    all-gather between them would give back exactly what the overlap
    bought. Norms on the sharded stream cost one [b, s] partial-sum
    all-reduce, inserted by GSPMD."""
    if not ring_on:
        return None
    from runbooks_tpu.parallel.sharding import DEFAULT_RULES

    return {**DEFAULT_RULES, "act_embed": "tensor"}


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------

def _dense_init(rng, shape, dtype, in_axis_size):
    scale = in_axis_size ** -0.5
    return (jax.random.normal(rng, shape) * scale).astype(dtype)


def _norm_params(cfg: ModelConfig, shape_prefix=()):
    h = cfg.hidden_size
    pd = cfg.parameter_dtype
    if cfg.norm_type == "rmsnorm":
        return {"scale": jnp.ones(shape_prefix + (h,), pd)}
    return {"scale": jnp.ones(shape_prefix + (h,), pd),
            "bias": jnp.zeros(shape_prefix + (h,), pd)}


def init_params(cfg: ModelConfig, rng: jax.Array) -> Params:
    """Random-init parameters (stacked layers). For real checkpoints use
    runbooks_tpu.models.convert (HF weight import)."""
    h, v, L = cfg.hidden_size, cfg.vocab_size, cfg.num_layers
    pd = cfg.parameter_dtype
    keys = iter(jax.random.split(rng, 16))

    params: Params = {
        "embed": (jax.random.normal(next(keys), (v, h)) * h ** -0.5).astype(pd),
        "final_norm": _norm_params(cfg),
    }
    if cfg.position_type == "learned":
        params["pos_embed"] = (
            jax.random.normal(next(keys), (cfg.max_seq_len, h)) * 0.02
        ).astype(pd)
    if not cfg.tie_embeddings:
        params["head"] = _dense_init(next(keys), (h, v), pd, h)

    layers: Params = {
        "attn": {
            "wq": _dense_init(next(keys), (L, h, cfg.q_dim), pd, h),
            "wk": _dense_init(next(keys), (L, h, cfg.kv_dim), pd, h),
            "wv": _dense_init(next(keys), (L, h, cfg.kv_dim), pd, h),
            "wo": _dense_init(next(keys), (L, cfg.q_dim, h), pd, cfg.q_dim),
        },
        "ln1": _norm_params(cfg, (L,)),
    }
    if cfg.attn_bias:
        layers["attn"]["bq"] = jnp.zeros((L, cfg.q_dim), pd)
        layers["attn"]["bk"] = jnp.zeros((L, cfg.kv_dim), pd)
        layers["attn"]["bv"] = jnp.zeros((L, cfg.kv_dim), pd)
        layers["attn"]["bo"] = jnp.zeros((L, h), pd)
    if cfg.qk_norm:
        layers["attn"]["q_norm"] = jnp.ones((L, cfg.head_dim), pd)
        layers["attn"]["k_norm"] = jnp.ones((L, cfg.head_dim), pd)

    if cfg.moe_num_experts:
        assert cfg.gated_mlp, "MoE experts are gated (mixtral-style)"
        E, m = cfg.moe_num_experts, cfg.intermediate_size
        layers["moe"] = {
            "router": (jax.random.normal(next(keys), (L, h, E))
                       * h ** -0.5).astype(pd),
            "wi_gate": _dense_init(next(keys), (L, E, h, m), pd, h),
            "wi_up": _dense_init(next(keys), (L, E, h, m), pd, h),
            "wo": _dense_init(next(keys), (L, E, m, h), pd, m),
        }
    else:
        mlp: Params = {
            "wo": _dense_init(next(keys), (L, cfg.intermediate_size, h), pd,
                              cfg.intermediate_size),
        }
        if cfg.gated_mlp:
            mlp["wi_gate"] = _dense_init(next(keys), (L, h, cfg.intermediate_size), pd, h)
            mlp["wi_up"] = _dense_init(next(keys), (L, h, cfg.intermediate_size), pd, h)
        else:
            mlp["wi"] = _dense_init(next(keys), (L, h, cfg.intermediate_size), pd, h)
        if cfg.mlp_bias:
            for k in ("wi_gate", "wi_up", "wi"):
                if k in mlp:
                    mlp["b" + k[1:]] = jnp.zeros((L, cfg.intermediate_size), pd)
            mlp["bo"] = jnp.zeros((L, h), pd)
        layers["mlp"] = mlp

    if not (cfg.parallel_block and cfg.shared_layer_norm):
        layers["ln2"] = _norm_params(cfg, (L,))

    params["layers"] = layers
    return params


def param_logical_axes(cfg: ModelConfig) -> Params:
    """Pytree matching init_params, with logical axis names per dimension."""
    norm1 = lambda pre: {k: pre + ("norm",) for k in
                         (("scale", "bias") if cfg.norm_type == "layernorm"
                          else ("scale",))}
    axes: Params = {
        "embed": ("vocab", "embed"),
        "final_norm": norm1(()),
    }
    if cfg.position_type == "learned":
        axes["pos_embed"] = ("pos", "embed")
    if not cfg.tie_embeddings:
        axes["head"] = ("embed", "vocab")

    # The stacked-layer leading dim carries the "layers" logical axis: it
    # maps to the "stage" mesh axis for pipeline parallelism and drops to
    # replicated on meshes without one (parallel/sharding.py rules).
    attn = {
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
    }
    if cfg.attn_bias:
        attn.update({"bq": ("layers", "heads"),
                     "bk": ("layers", "kv_heads"),
                     "bv": ("layers", "kv_heads"),
                     "bo": ("layers", "norm")})
    if cfg.qk_norm:
        attn.update({"q_norm": ("layers", "head_dim"),
                     "k_norm": ("layers", "head_dim")})

    if cfg.moe_num_experts:
        from runbooks_tpu.models.moe import moe_logical_axes
        ffn_key, ffn_axes = "moe", moe_logical_axes()
    else:
        mlp = {"wo": ("layers", "mlp", "embed")}
        if cfg.gated_mlp:
            mlp.update({"wi_gate": ("layers", "embed", "mlp"),
                        "wi_up": ("layers", "embed", "mlp")})
        else:
            mlp["wi"] = ("layers", "embed", "mlp")
        if cfg.mlp_bias:
            for k in list(mlp):
                if k.startswith("wi"):
                    mlp["b" + k[1:]] = ("layers", "mlp")
            mlp["bo"] = ("layers", "norm")
        ffn_key, ffn_axes = "mlp", mlp

    layers = {"attn": attn, ffn_key: ffn_axes, "ln1": norm1(("layers",))}
    if not (cfg.parallel_block and cfg.shared_layer_norm):
        layers["ln2"] = norm1(("layers",))
    axes["layers"] = layers
    return axes


# ---------------------------------------------------------------------------
# KV cache
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """Per-model KV cache, layers stacked on the leading axis.

    k, v: [num_layers, batch, cache_len, num_kv_heads, head_dim]
    index: [] int32 — number of tokens already written (same for the whole
    batch). Two write modes in ``forward``:

    - scalar-index mode (positions omitted): tokens append at ``index``;
      every row advances together.
    - position-scatter mode (positions given): token j of row b writes to
      slot ``positions[b, j]`` (clipped to cache_len-1). Rows advance
      independently — this is what slot-based continuous batching uses.
      Allocate with ``trash_slot=True`` (cache_len = max_len+1) and point
      padding at slot max_len so pad tokens land in a slot no real query
      ever attends (slot s is visible only to queries with position >= s).

    quantize_kv=True stores k/v as int8 with one f32 scale per
    (layer, row, slot, kv-head) in k_scale/v_scale
    ([num_layers, batch, cache_len, num_kv_heads]) — halving the HBM the
    bandwidth-bound decode step streams, which doubles max_slots x
    max_seq_len at fixed memory. forward() detects the int8 dtype and
    quantizes on write / dequantizes on read transparently.
    """

    k: jax.Array
    v: jax.Array
    index: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @classmethod
    def create(cls, cfg: ModelConfig, batch: int, max_len: int,
               trash_slot: bool = False,
               quantize_kv: bool = False) -> "KVCache":
        cache_len = max_len + 1 if trash_slot else max_len
        shape = (cfg.num_layers, batch, cache_len, cfg.num_kv_heads,
                 cfg.head_dim)
        if quantize_kv:
            return cls(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                index=jnp.zeros((), jnp.int32),
                k_scale=jnp.zeros(shape[:-1], jnp.float32),
                v_scale=jnp.zeros(shape[:-1], jnp.float32),
            )
        return cls(
            k=jnp.zeros(shape, cfg.activation_dtype),
            v=jnp.zeros(shape, cfg.activation_dtype),
            index=jnp.zeros((), jnp.int32),
        )

    @property
    def quantized(self) -> bool:
        return self.k.dtype == jnp.int8


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------

def _norm(cfg: ModelConfig, p: Params, x: jax.Array) -> jax.Array:
    if cfg.norm_type == "rmsnorm":
        return rms_norm(x, p["scale"], cfg.norm_eps)
    return layer_norm(x, p["scale"], p["bias"], cfg.norm_eps)


def _activation(cfg: ModelConfig, x: jax.Array) -> jax.Array:
    if cfg.activation == "silu":
        return jax.nn.silu(x)
    if cfg.activation == "gelu":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.relu(x)


def _auto_embed_one_hot(cfg: ModelConfig, has_cache: bool) -> bool:
    """One-hot-vs-gather auto rule, shared by forward() and the 1F1B
    embed (they must not drift). One-hot when the mesh tensor-shards the
    vocab (the gather forces a full-remat reshard), or — training only —
    when the sequence axis is sharded (the gather's scatter-add TRANSPOSE
    hits the same involuntary-remat path; a cached/serving forward has no
    backward, and the one-hot there would materialize a [b, s, vocab]
    tensor for nothing)."""
    if cfg.embed_one_hot is not None:
        return cfg.embed_one_hot
    from runbooks_tpu.parallel.sharding import _current_mesh

    m0 = _current_mesh()
    if m0 is None:
        return False
    if int(m0.shape.get("tensor", 1)) > 1:
        return True
    return not has_cache and int(m0.shape.get("sequence", 1)) > 1


def resolve_attention_impl(cfg: ModelConfig) -> str:
    """Resolve cfg.attention_impl ("auto" included) to a concrete impl for
    the no-cache (training) path: ring when the active mesh is
    sequence-parallel, flash on TPU, else xla. ALiBi bias and logit softcap
    force xla (not yet in the kernels). Single source of truth — used both
    for dispatch and for skipping the O(s^2) mask build."""
    impl = cfg.attention_impl
    if impl not in ("auto", "xla", "flash", "ring"):
        raise ValueError(
            f"unknown attention_impl {impl!r}; expected auto|xla|flash|ring")
    if impl == "auto":
        from runbooks_tpu.parallel.sharding import _current_mesh

        mesh = _current_mesh()
        if mesh is not None and mesh.shape.get("sequence", 1) > 1:
            impl = "ring"
        elif "tpu" in jax.default_backend().lower():
            impl = "flash"
        else:
            impl = "xla"
    if cfg.position_type == "alibi" or cfg.logit_softcap is not None:
        impl = "xla"
    return impl


def use_flash_cached_prefill(cfg: ModelConfig, q_len: int) -> bool:
    """Route a prefill-with-cache through the flash kernel instead of the
    XLA O(s*kv) path? True when the query block is at least one flash tile
    and the kernel covers the config (ALiBi bias and logit softcap are
    XLA-only, as in resolve_attention_impl). Decode (q_len=1) always stays
    XLA. forward() skips the mask build entirely on this path — the kernel
    masks from absolute positions, which for a cache (slot i == position i)
    is exactly the XLA mask."""
    if q_len < FLASH_CACHED_PREFILL_MIN_Q:
        return False
    if cfg.position_type == "alibi" or cfg.logit_softcap is not None:
        return False
    impl = cfg.attention_impl
    if impl == "flash":
        return True
    if impl != "auto":
        return False
    from runbooks_tpu.ops.flash_attention import is_tpu_backend

    return is_tpu_backend()


def _dispatch_attention(cfg: ModelConfig, q, k, v, positions, segment_ids,
                        mask, bias):
    """Pick the attention implementation for the no-cache (training) path.
    k/v stay at kv_heads width on every path (GQA-native kernels)."""
    impl = resolve_attention_impl(cfg)  # forces xla for alibi/softcap

    if impl == "flash":
        from runbooks_tpu.ops.flash_attention import flash_attention

        return flash_attention(
            q, k, v, positions, positions, segment_ids, segment_ids,
            True, None, cfg.flash_block_q, cfg.flash_block_k)

    if impl == "ring":
        from runbooks_tpu.parallel.ring_attention import (
            ring_attention,
            ring_flash_attention_sharded,
            use_flash_inner_default,
        )
        from runbooks_tpu.parallel.sharding import (
            _current_mesh, spec_for_array)

        mesh = _current_mesh()
        if mesh is None or mesh.shape.get("sequence", 1) == 1:
            # No ring to run; single-shard blockwise math is plain attention.
            return dot_product_attention(
                q, k, v, mask=mask, logit_softcap=cfg.logit_softcap)
        qspec = spec_for_array(q.shape, ("batch", "seq", "act_heads", None),
                               mesh)
        kspec = spec_for_array(k.shape, ("batch", "seq", "act_heads", None),
                               mesh)
        rspec = spec_for_array(positions.shape, ("batch", "seq"), mesh)
        seg = (segment_ids if segment_ids is not None
               else jnp.ones_like(positions))

        use_flash = cfg.ring_flash_inner
        if use_flash is None:
            use_flash = use_flash_inner_default()
        if use_flash:
            lse_spec = spec_for_array(
                (q.shape[0], q.shape[2], q.shape[1]),
                ("batch", "act_heads", "seq"), mesh)
            return ring_flash_attention_sharded(
                q, k, v, positions, seg, mesh, qspec, kspec, rspec,
                lse_spec, block_q=cfg.flash_block_q,
                block_k=cfg.flash_block_k)

        def local(ql, kl, vl, pl_, sl):
            return ring_attention(ql, kl, vl, pl_, pl_, sl, sl,
                                  axis_name="sequence")

        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(qspec, kspec, kspec, rspec, rspec),
            out_specs=qspec,
            # The scan carry starts unvarying (zeros) and becomes varying
            # after the first ppermute; skip the VMA check rather than
            # pcast-annotating for every possible mesh shape.
            check_vma=False,
        )(q, k, v, positions, seg)

    return dot_product_attention(q, k, v, mask=mask, bias=bias,
                                 logit_softcap=cfg.logit_softcap)


def _adapter_delta(adapter, name: str, x_in: jax.Array, y: jax.Array,
                   ad) -> jax.Array:
    """Add the grouped per-row LoRA delta for one target to the base
    projection's output (docs/multi-tenant-lora.md). ``adapter`` is
    None (off) or (pool_layer, lane_idx): pool_layer a nested
    {"attn"/"mlp": {target: {"a", "b"}}} slice for THIS layer, lane_idx
    the per-row int32 lane indices (already trash-mapped). Targets
    absent from the pool pass through untouched, so a pool configured
    for attention-only injection costs the MLP nothing."""
    if adapter is None:
        return y
    sub, idx = adapter
    ab = sub.get(name)
    if ab is None:
        return y
    from runbooks_tpu.ops.lora import grouped_lora_delta

    return y + grouped_lora_delta(x_in, ab, idx, ad)


def _attention_block(
    cfg: ModelConfig,
    p: Params,
    x: jax.Array,                      # [b, s, h] activation dtype
    positions: jax.Array,              # [b, s]
    segment_ids: Optional[jax.Array],
    mask: Optional[jax.Array],
    bias: Optional[jax.Array],
    layer_cache: Optional[Tuple[jax.Array, jax.Array, jax.Array]],
    adapter=None,
):
    b, s, _ = x.shape
    ad = cfg.activation_dtype
    ring_on = resolve_collective_matmul(cfg)
    ring_col = "ag" if ring_on else None
    ring_row = "rs" if ring_on else None
    bidir = cfg.collective_matmul_bidirectional

    def proj(w, bname, aname):
        y = _matmul(x, w, ad, ring=ring_col, ring_bidir=bidir)
        y = _adapter_delta(adapter, aname, x, y, ad)
        if bname in p:
            y = y + p[bname].astype(ad)
        return y

    q = proj(p["wq"], "bq", "wq").reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = proj(p["wk"], "bk", "wk").reshape(b, s, cfg.num_kv_heads,
                                          cfg.head_dim)
    v = proj(p["wv"], "bv", "wv").reshape(b, s, cfg.num_kv_heads,
                                          cfg.head_dim)
    q = with_logical_constraint(q, ("batch", "seq", "act_heads", None))
    k = with_logical_constraint(k, ("batch", "seq", "act_heads", None))
    v = with_logical_constraint(v, ("batch", "seq", "act_heads", None))

    if cfg.qk_norm:
        q = rms_norm(q, p["q_norm"], cfg.norm_eps)
        k = rms_norm(k, p["k_norm"], cfg.norm_eps)
    if cfg.position_type == "rope":
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)

    new_layer_cache = None
    if layer_cache is not None:
        ck, cv, ck_s, cv_s, index, view = layer_cache
        quantized = ck.dtype == jnp.int8
        if quantized:
            # int8 KV: one f32 scale per (row, slot, kv-head) rides next to
            # the int8 values; both scatter with the same indices.
            k_w, k_s = quantize_kv(k)
            v_w, v_s = quantize_kv(v)
        else:
            k_w, v_w, k_s, v_s = k, v, None, None
        if index is None:
            # Position-scatter mode: row b token j -> slot positions[b, j].
            cache_len = ck.shape[1]
            slot = jnp.clip(positions, 0, cache_len - 1)
            b_idx = jnp.arange(b, dtype=jnp.int32)[:, None]
            ck = ck.at[b_idx, slot].set(k_w)
            cv = cv.at[b_idx, slot].set(v_w)
            if quantized:
                ck_s = ck_s.at[b_idx, slot].set(k_s)
                cv_s = cv_s.at[b_idx, slot].set(v_s)
        else:
            ck = jax.lax.dynamic_update_slice(ck, k_w, (0, index, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v_w, (0, index, 0, 0))
            if quantized:
                ck_s = jax.lax.dynamic_update_slice(ck_s, k_s, (0, index, 0))
                cv_s = jax.lax.dynamic_update_slice(cv_s, v_s, (0, index, 0))
        # Writes go to the FULL cache; attention READS only [0, view).
        # Exact for any view > max query position: slot s is attended only
        # by queries at positions >= s, so slots beyond the view hold
        # nothing a masked-in query could see. Serving uses this to stop
        # decode from streaming the whole max-length cache through HBM
        # when occupancy is low (the decode step is bandwidth-bound).
        if view is None:
            k, v = ck, cv
            rk_s, rv_s = ck_s, cv_s
        else:
            k, v = ck[:, :view], cv[:, :view]
            rk_s = ck_s[:, :view] if quantized else None
            rv_s = cv_s[:, :view] if quantized else None
        if quantized:
            # Dequantize at the read: the scale multiply fuses into the
            # attention contraction, so HBM streams int8 + one scale per
            # row — half the bytes of the bf16 cache the decode step is
            # bound on.
            k = dequantize_kv(k, rk_s, ad)
            v = dequantize_kv(v, rv_s, ad)
        new_layer_cache = (ck, cv, ck_s, cv_s)
        if mask is None:
            # Flash cached-prefill (forward() skipped the O(s*kv) mask
            # build): cache slot i holds absolute position i by
            # construction, so the kernel's causal-by-absolute-position
            # masking reproduces the XLA path's mask exactly — unwritten
            # or future slots are never attended. block_skip stays off:
            # query rows start at position cache.index, not 0, so grid
            # index alignment does not hold.
            from runbooks_tpu.ops.flash_attention import flash_attention

            kv_pos = jnp.broadcast_to(
                jnp.arange(k.shape[1], dtype=jnp.int32)[None, :],
                (b, k.shape[1]))
            out = flash_attention(
                q, k, v, positions, kv_pos, None, None, True, None,
                cfg.flash_block_q, cfg.flash_block_k, block_skip=False)
        else:
            # Decode (s=1) keeps the XLA path: a one-row query block has no
            # O(s^2) term and the step is bandwidth-bound anyway.
            out = dot_product_attention(
                q, k, v, mask=mask, bias=bias,
                logit_softcap=cfg.logit_softcap)
    else:
        out = _dispatch_attention(cfg, q, k, v, positions, segment_ids,
                                  mask, bias)
    out = out.reshape(b, s, cfg.q_dim)
    attn_ctx = out
    out = _matmul(out, p["wo"], ad, ring=ring_row, ring_bidir=bidir)
    out = _adapter_delta(adapter, "wo", attn_ctx, out, ad)
    if "bo" in p:
        out = out + p["bo"].astype(ad)
    return out, new_layer_cache


def _mlp_block(cfg: ModelConfig, p: Params, x: jax.Array,
               adapter=None) -> jax.Array:
    ad = cfg.activation_dtype
    ring_on = resolve_collective_matmul(cfg)
    bidir = cfg.collective_matmul_bidirectional

    def mm(y, w, ring=None):
        return _matmul(y, w, ad, ring=ring, ring_bidir=bidir)

    ring_col = "ag" if ring_on else None
    ring_row = "rs" if ring_on else None
    if cfg.gated_mlp:
        gate = _adapter_delta(adapter, "wi_gate", x,
                              mm(x, p["wi_gate"], ring_col), ad)
        up = _adapter_delta(adapter, "wi_up", x,
                            mm(x, p["wi_up"], ring_col), ad)
        if "bi_gate" in p:
            gate = gate + p["bi_gate"].astype(ad)
            up = up + p["bi_up"].astype(ad)
        hidden = _activation(cfg, gate) * up
    else:
        hidden = _adapter_delta(adapter, "wi", x,
                                mm(x, p["wi"], ring_col), ad)
        if "bi" in p:
            hidden = hidden + p["bi"].astype(ad)
        hidden = _activation(cfg, hidden)
    hidden = with_logical_constraint(hidden, ("batch", "seq", "act_mlp"))
    out = _adapter_delta(adapter, "wo", hidden,
                         mm(hidden, p["wo"], ring_row), ad)
    if "bo" in p:
        out = out + p["bo"].astype(ad)
    return out


def _ffn_block(cfg: ModelConfig, layer: Params, x: jax.Array,
               adapter=None):
    """Dense MLP or MoE, returning (out, aux-loss scalar)."""
    if cfg.moe_num_experts:
        from runbooks_tpu.models.moe import moe_block

        return moe_block(cfg, layer["moe"], x)
    return (_mlp_block(cfg, layer["mlp"], x, adapter=adapter),
            jnp.zeros((), jnp.float32))


def _adapter_group(adapter, group: str):
    """(group_pool, idx) for one block sub-module, or None when the pool
    has no targets there."""
    if adapter is None:
        return None
    pool_layer, idx = adapter
    sub = pool_layer.get(group)
    return None if sub is None else (sub, idx)


def _block(cfg: ModelConfig, layer: Params, x, positions, segment_ids, mask,
           bias, layer_cache, adapter=None):
    """One transformer block. x: [b, s, h]. Returns (x, cache, aux).
    ``adapter``: None or (per-layer adapter-pool slice, lane indices) —
    the grouped LoRA injection (docs/multi-tenant-lora.md)."""
    act_rules = _act_embed_rules(resolve_collective_matmul(cfg))
    x = with_logical_constraint(x, ("batch", "seq", "act_embed"),
                                rules=act_rules)
    h1 = _norm(cfg, layer["ln1"], x)
    attn_out, new_cache = _attention_block(
        cfg, layer["attn"], h1, positions, segment_ids, mask, bias,
        layer_cache, adapter=_adapter_group(adapter, "attn"))
    # Named checkpoint for selective remat: remat_policy="save_attn_out"
    # saves this [b, s, h] tensor (plus the flash kernel's hoisted
    # "attn_context"/"attn_lse" residuals — see ops/flash_attention.py) so
    # the backward never re-runs the O(s^2) flash fwd kernel, while
    # activations stay O(layers * b * s * h) instead of the dots_saveable
    # blow-up.
    attn_out = checkpoint_name(attn_out, "attn_out")
    mlp_adapter = _adapter_group(adapter, "mlp")
    if cfg.parallel_block:
        h2 = h1 if cfg.shared_layer_norm else _norm(cfg, layer["ln2"], x)
        mlp_out, aux = _ffn_block(cfg, layer, h2, adapter=mlp_adapter)
        x = x + attn_out + mlp_out
    else:
        x = x + attn_out
        h2 = _norm(cfg, layer["ln2"], x)
        ffn_out, aux = _ffn_block(cfg, layer, h2, adapter=mlp_adapter)
        x = x + ffn_out
    x = with_logical_constraint(x, ("batch", "seq", "act_embed"),
                                rules=act_rules)
    return x, new_cache, aux


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def forward(
    cfg: ModelConfig,
    params: Params,
    tokens: jax.Array,                      # [b, s] int32
    *,
    positions: Optional[jax.Array] = None,  # [b, s] absolute positions
    segment_ids: Optional[jax.Array] = None,  # [b, s] packed-seq ids (0 = pad)
    cache: Optional[KVCache] = None,
    cache_view: Optional[int] = None,
    remat: bool = False,
    with_aux: bool = False,
    return_activations: bool = False,
    adapters=None,
) -> Tuple[jax.Array, Optional[KVCache]]:
    """Returns (logits [b, s, vocab] float32, updated cache or None) — or,
    with_aux=True, (logits, cache, aux) where aux is the summed per-layer
    auxiliary loss (MoE load balance; 0.0 for dense models).

    return_activations=True skips the head matmul and returns the
    post-final-norm activations [b, s, hidden] in place of logits — the
    input to the chunked fused cross-entropy (train/step.py
    chunked_cross_entropy), which consumes activations + head weights in
    sequence chunks so the [b, s, vocab] f32 logits tensor is never
    materialized.

    Without cache: standard training/eval forward, causal + segment masking.
    With cache: tokens are appended at cache.index (prefill chunks or single-
    token decode); positions default to index + arange(s).

    cache_view (static): attention reads only cache slots [0, cache_view) —
    writes still land in the full cache. Exact whenever every query position
    is < cache_view; the serving engine picks the smallest bucketed view
    covering current occupancy so decode doesn't stream the whole
    max-length cache through HBM each step.

    adapters: None or (pool, lane_idx) — the multi-tenant batched LoRA
    injection (ops/lora.py, docs/multi-tenant-lora.md). ``pool`` is the
    stacked adapter pytree ({"attn"/"mlp": {target: {"a": [L, lanes,
    d_in, r], "b": [L, lanes, r, d_out]}}}); ``lane_idx`` [b] int32
    selects each row's adapter lane (-1 = base-only, mapped to the
    all-zero trash lane). The pool scans with the layers, and every
    targeted projection adds its row's ``(x @ A) @ B`` delta — one
    program for any tenant mix. Not supported on the pipeline (stage >
    1) path.
    """
    b, s = tokens.shape
    ad = cfg.activation_dtype

    if cache is not None and segment_ids is not None:
        raise NotImplementedError(
            "packed sequences (segment_ids) are not supported together with a "
            "KV cache: the cache mask is positional-only. Prefill packed "
            "batches without a cache, or one sequence per batch row with one."
        )

    # With a cache, explicitly-passed positions select position-scatter
    # writes (per-row slots); omitted positions select append-at-index.
    scatter_mode = cache is not None and positions is not None

    if positions is None:
        if cache is not None:
            positions = cache.index + jnp.arange(s, dtype=jnp.int32)[None, :]
            positions = jnp.broadcast_to(positions, (b, s))
        else:
            positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :],
                                         (b, s))

    use_one_hot = _auto_embed_one_hot(cfg, has_cache=cache is not None)
    if use_one_hot:
        one_hot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=ad)
        x = jnp.einsum("bsv,vh->bsh", one_hot, params["embed"].astype(ad),
                       preferred_element_type=jnp.float32).astype(ad)
    else:
        x = params["embed"].astype(ad)[tokens]
    if cfg.embed_scale:
        x = x * (cfg.hidden_size ** 0.5)
    if cfg.position_type == "learned":
        x = x + params["pos_embed"].astype(ad)[positions]
    # Deliberately the DEFAULT (replicated-h) constraint even when the
    # ring path tensor-shards the residual stream: constraining the
    # one-hot embed einsum's output tensor-sharded while its vocab
    # contraction is also tensor-sharded miscompiles on the pinned
    # jaxlib's SPMD partitioner (wrong VALUES, reproduced and bisected —
    # not just a slow reshard). The first block's constraint shards the
    # stream one op later, which the partitioner handles correctly.
    x = with_logical_constraint(x, ("batch", "seq", "act_embed"))

    # Mask & bias over the full kv extent (or the static read view).
    if cache is not None:
        max_kv = cache_view if cache_view is not None else cache.k.shape[2]
        kv_positions = jnp.broadcast_to(
            jnp.arange(max_kv, dtype=jnp.int32)[None, :], (b, max_kv))
        if use_flash_cached_prefill(cfg, s):
            # Flash cached-prefill: the kernel masks causally from absolute
            # positions; no O(s*kv) mask tensor (see _attention_block).
            mask = None
        else:
            # Slots at arange > q position are either future or unwritten:
            # the causal comparison masks both, so no separate validity
            # mask needed.
            mask = make_attention_mask(positions, kv_positions, causal=True)
    else:
        kv_positions = positions
        if resolve_attention_impl(cfg) == "flash":
            mask = None  # the kernel masks from positions/segments directly
        else:
            mask = make_attention_mask(
                positions, kv_positions, segment_ids, segment_ids, causal=True)

    bias = None
    if cfg.position_type == "alibi":
        slopes = alibi_slopes(cfg.num_heads)  # [h]
        rel = (kv_positions[:, None, :] - positions[:, :, None]).astype(jnp.float32)
        bias = slopes[None, :, None, None] * rel[:, None, :, :]

    block = _block
    if remat and cfg.remat_policy != "none":
        block = jax.checkpoint(
            _block, policy=_remat_policy(cfg.remat_policy),
            static_argnums=(0,))

    apool = aidx = None
    if adapters is not None:
        from runbooks_tpu.ops.lora import map_lane_indices, pool_lanes

        apool, aidx = adapters
        aidx = map_lane_indices(jnp.asarray(aidx), pool_lanes(apool))

    def scan_body(carry, scanned):
        x, aux_sum = carry
        if apool is not None:
            *scanned, pool_layer = scanned
            adapter = (pool_layer, aidx)
        else:
            adapter = None
        if cache is not None:
            layer, ck, cv, ck_s, cv_s = scanned
            layer_cache = (ck, cv, ck_s, cv_s,
                           None if scatter_mode else cache.index,
                           cache_view)
        else:
            (layer,) = scanned if apool is not None else (scanned,)
            layer_cache = None
        x, new_cache, aux = block(cfg, layer, x, positions, segment_ids,
                                  mask, bias, layer_cache, adapter)
        return (x, aux_sum + aux), new_cache

    aux_total = jnp.zeros((), jnp.float32)
    if cache is not None:
        # k_scale/v_scale are None (empty pytrees) for an unquantized
        # cache; scan threads them through untouched either way. The
        # adapter pool (leading L axis) rides the same scan when given.
        xs = (params["layers"], cache.k, cache.v,
              cache.k_scale, cache.v_scale)
        if apool is not None:
            xs = xs + (apool,)
        (x, aux_total), (new_k, new_v, new_ks, new_vs) = jax.lax.scan(
            scan_body, (x, aux_total), xs)
        new_index = cache.index if scatter_mode else cache.index + s
        new_cache = KVCache(k=new_k, v=new_v, index=new_index,
                            k_scale=new_ks, v_scale=new_vs)
    else:
        from runbooks_tpu.parallel.sharding import _current_mesh

        mesh = _current_mesh()
        n_stages = int(mesh.shape.get("stage", 1)) if mesh is not None \
            else 1
        if n_stages > 1:
            if apool is not None:
                raise NotImplementedError(
                    "adapter pools are not supported on the pipeline "
                    "(stage > 1) path; serve adapters with tensor/data "
                    "parallelism (docs/multi-tenant-lora.md)")
            # Pipeline-parallel path: same block, stacked layers sharded
            # over the stage axis, activations ppermuted between stages
            # (parallel/pipeline.py).
            from runbooks_tpu.parallel.pipeline import pipeline_apply

            def pipe_block(layer, xx, mb_consts):
                pos, seg, mk, bs = mb_consts
                y, _, aux = block(cfg, layer, xx, pos, seg, mk, bs, None)
                return y, aux

            x, aux_total = pipeline_apply(
                pipe_block, params["layers"], x,
                (positions, segment_ids, mask, bias),
                mesh=mesh, n_stages=n_stages,
                n_microbatches=cfg.pipeline_microbatches or None)
        else:
            xs = (params["layers"] if apool is None
                  else (params["layers"], apool))
            (x, aux_total), _ = jax.lax.scan(
                scan_body, (x, aux_total), xs)
        new_cache = None

    x = _norm(cfg, params["final_norm"], x)
    if return_activations:
        act_rules = _act_embed_rules(resolve_collective_matmul(cfg))
        x = with_logical_constraint(x, ("batch", "seq", "act_embed"),
                                    rules=act_rules)
        if with_aux:
            return x, new_cache, aux_total
        return x, new_cache
    head = params["embed"].T if cfg.tie_embeddings else params["head"]
    # bf16 operands + f32 accumulation: the MXU accumulates in f32 either
    # way, but f32 operands run at 1/4 the bf16 MXU rate on v5e/v5p.
    logits = jnp.einsum("bsh,hv->bsv", x.astype(cfg.activation_dtype),
                        head.astype(cfg.activation_dtype),
                        preferred_element_type=jnp.float32)
    logits = with_logical_constraint(logits, ("batch", "seq", None))
    if with_aux:
        return logits, new_cache, aux_total
    return logits, new_cache


def loss_and_grads_1f1b(
    cfg: ModelConfig,
    params: Params,
    tokens: jax.Array,                      # [b, s] int32
    targets: jax.Array,                     # [b, s] int32
    loss_mask: Optional[jax.Array] = None,  # [b, s] float {0,1}
    positions: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Params, jax.Array]:
    """Masked-mean CE loss + grads via the 1F1B pipeline schedule.

    Numerically equivalent to
    ``jax.value_and_grad(ce(forward(...)))`` on a stage>1 mesh (the GPipe
    autodiff path is the test oracle), but the backward is explicit: the
    pipeline interleaves per-microbatch vjp ticks so in-flight activations
    are O(stages) and full-batch logits never materialize (see
    parallel/pipeline.pipeline_1f1b_grads). Embedding fwd/bwd runs outside
    the pipeline via jax.vjp; head grads (incl. tied-embedding head) come
    back from the last stage and are tree-added.

    Returns (loss, grads, total_weight) with grads matching params'
    structure — a drop-in for the value_and_grad call in train/step.py.
    """
    from runbooks_tpu.parallel.pipeline import pipeline_1f1b_grads
    from runbooks_tpu.parallel.sharding import _current_mesh

    mesh = _current_mesh()
    n_stages = int(mesh.shape.get("stage", 1)) if mesh is not None else 1
    if n_stages <= 1:
        raise ValueError("loss_and_grads_1f1b needs a mesh with stage > 1")
    b, s = tokens.shape
    ad = cfg.activation_dtype
    M = cfg.pipeline_microbatches or n_stages

    if positions is None:
        positions = jnp.broadcast_to(
            jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))

    weights = (jnp.ones((b, s), jnp.float32) if loss_mask is None
               else loss_mask.astype(jnp.float32))
    total_weight = jnp.maximum(jnp.sum(weights), 1.0)
    inv_total = 1.0 / total_weight

    nl_params = {k: v for k, v in params.items() if k != "layers"}

    def embed_fn(nl):
        use_one_hot = _auto_embed_one_hot(cfg, has_cache=False)
        if use_one_hot:
            one_hot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=ad)
            x = jnp.einsum("bsv,vh->bsh", one_hot, nl["embed"].astype(ad),
                           preferred_element_type=jnp.float32).astype(ad)
        else:
            x = nl["embed"].astype(ad)[tokens]
        if cfg.embed_scale:
            x = x * (cfg.hidden_size ** 0.5)
        if cfg.position_type == "learned":
            x = x + nl["pos_embed"].astype(ad)[positions]
        return with_logical_constraint(x, ("batch", "seq", "act_embed"))

    x, embed_vjp = jax.vjp(embed_fn, nl_params)

    # Mask/bias exactly as the no-cache forward builds them.
    if resolve_attention_impl(cfg) == "flash":
        mask = None
    else:
        mask = make_attention_mask(positions, positions, segment_ids,
                                   segment_ids, causal=True)
    bias = None
    if cfg.position_type == "alibi":
        slopes = alibi_slopes(cfg.num_heads)
        rel = (positions[:, None, :]
               - positions[:, :, None]).astype(jnp.float32)
        bias = slopes[None, :, None, None] * rel[:, None, :, :]

    def blk_fn(layer, xx, mb_consts):
        pos, seg, mk, bs = mb_consts
        y, _, aux = _block(cfg, layer, xx, pos, seg, mk, bs, None)
        return y, aux

    def head_loss_fn(nl, y, lc):
        tgt, w = lc
        h = _norm(cfg, nl["final_norm"], y)
        head = nl["embed"].T if cfg.tie_embeddings else nl["head"]
        logits = jnp.einsum("bsh,hv->bsv", h.astype(ad), head.astype(ad),
                            preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        # One-hot select, NOT take_along_axis: the gather's transpose is a
        # scatter-add into the tensor-sharded logits, which crashes the
        # GSPMD partitioner inside the stage-manual shard_map
        # (spmd_partitioner_util.cc CHECK, reduced and verified); the
        # masked-sum transpose is a broadcast-multiply and partitions
        # cleanly (and is exactly how embed_one_hot sidesteps the same
        # class of problem on the embedding side).
        onehot = (jnp.arange(logits.shape[-1], dtype=tgt.dtype)[None, None]
                  == tgt[..., None])
        nll = -jnp.sum(jnp.where(onehot, logp, 0.0), axis=-1)
        return jnp.sum(nll * w) * inv_total

    # Vocab-parallel head for untied models: the [h, vocab] head shards
    # over the stage axis (head FLOPs drop S x back to the oracle's —
    # see pipeline_1f1b_grads docstring) and the loss becomes a global
    # log-softmax over the stage-sharded vocab: a stop-gradient'ed pmax
    # for stability, a psum'd sum-exp, and a per-stage PARTIAL loss
    # (lse/S - local target logit) whose stage-psum is the true loss —
    # autodiff through the psums yields exactly w*(softmax - onehot) on
    # each slice. Tied embeddings keep the replicated path (the embedding
    # must stay whole for the embedding fwd/bwd outside the pipeline).
    Vs = cfg.vocab_size // n_stages
    use_sharded_head = (not cfg.tie_embeddings
                        and cfg.vocab_size % n_stages == 0)

    def head_loss_fn_sharded(nl, y, lc):
        tgt, w = lc
        h = _norm(cfg, nl["final_norm"], y)
        z = jnp.einsum("bsh,hv->bsv", h.astype(ad), nl["head"].astype(ad),
                       preferred_element_type=jnp.float32)  # [b, s, V/S]
        # stop_gradient BEFORE pmax: pmax has no differentiation rule,
        # and the max is only a stabilization shift anyway.
        m = jax.lax.pmax(
            jax.lax.stop_gradient(jnp.max(z, axis=-1)), "stage")
        sumexp = jax.lax.psum(
            jnp.sum(jnp.exp(z - m[..., None]), axis=-1), "stage")
        lse = m + jnp.log(sumexp)
        lo = jax.lax.axis_index("stage").astype(tgt.dtype) * Vs
        onehot = (jnp.arange(Vs, dtype=tgt.dtype)[None, None]
                  == (tgt[..., None] - lo))
        z_t_local = jnp.sum(jnp.where(onehot, z, 0.0), axis=-1)
        partial_nll = lse / n_stages - z_t_local
        return jnp.sum(partial_nll * w) * inv_total

    head_specs = None
    active_head_loss = head_loss_fn
    if use_sharded_head:
        from jax.sharding import PartitionSpec as P

        head_specs = jax.tree.map(lambda _: P(), nl_params)
        head_specs["head"] = P(None, "stage")
        active_head_loss = head_loss_fn_sharded

    aux_scale = (cfg.moe_aux_coef / M) if cfg.moe_num_experts else 0.0
    loss_sum, layer_grads, head_grads, dx, aux_mean = pipeline_1f1b_grads(
        blk_fn, active_head_loss, params["layers"], nl_params, x,
        (positions, segment_ids, mask, bias), (targets, weights),
        mesh=mesh, n_stages=n_stages, n_microbatches=M,
        aux_scale=aux_scale, head_specs=head_specs)

    (embed_grads,) = embed_vjp(dx)
    nl_grads = jax.tree.map(lambda a, g: a + g, embed_grads, head_grads)
    grads = dict(nl_grads)
    grads["layers"] = layer_grads
    loss = loss_sum
    if cfg.moe_num_experts:
        loss = loss + cfg.moe_aux_coef * aux_mean
    return loss, grads, total_weight


def _remat_policy(name: str):
    # "none" never reaches here: it disables the jax.checkpoint wrapper
    # entirely at the call site (remat off, all activations saved).
    policies = {
        "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
        "dots_with_no_batch_dims_saveable":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        # Selective: save the per-layer attention outputs — the post-wo
        # "attn_out" tagged in _block plus the flash kernel's hoisted
        # residuals "attn_context"/"attn_lse" (ops/flash_attention.py) —
        # and remat everything else. On the flash path the backward then
        # feeds the dq/dkv kernels from saved residuals instead of
        # re-running the O(s^2) fwd kernel (verified: the recompute pallas
        # call disappears from the grad jaxpr); on the xla path the s^2
        # einsum residuals are not nameable at O(s) memory, so this is
        # ~nothing_saveable plus a saved wo output there.
        "save_attn_out":
            jax.checkpoint_policies.save_only_these_names(
                "attn_out", "attn_context", "attn_lse"),
    }
    if name not in policies:
        raise ValueError(
            f"unknown remat_policy {name!r}; expected none|{'|'.join(policies)}")
    return policies[name]
