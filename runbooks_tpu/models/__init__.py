from runbooks_tpu.models.config import CONFIGS, ModelConfig, get_config
from runbooks_tpu.models.transformer import (
    KVCache,
    forward,
    init_params,
    param_logical_axes,
)

__all__ = ["CONFIGS", "ModelConfig", "get_config", "KVCache", "forward",
           "init_params", "param_logical_axes"]
