"""Lightweight JSONL trace spans (Chrome ``trace_event`` compatible).

``RBT_TRACE=1`` turns emission on; everything else is a near-zero-cost
no-op (one env lookup + one shared null context manager per span, so the
instrumented hot loops — trainer steps, engine ticks, reconciles — pay
nothing when tracing is off).

File format: the Chrome/Perfetto "JSON Array Format" with one event per
line — an opening ``[`` line, then ``{...},`` per event. The spec allows
the closing ``]`` to be omitted, so the file is loadable in Perfetto /
chrome://tracing at any moment (including mid-run or after a crash), and
each line (minus the trailing comma) is a complete JSON object — greppable
and streamable like any JSONL log.

Default output: ``{artifacts}/trace.jsonl`` (the container contract's
durable mount); ``configure(path)`` repoints it (the trainer does, per
run). Writes are lock-serialized line appends, so concurrent spans from
the engine worker, checkpoint threads, and reconcilers interleave without
tearing.

Rotation: a long-running traced server would otherwise grow the file
without bound. When the file exceeds ``RBT_TRACE_MAX_MB`` (default 256)
it rolls to ``<path>.1`` (one generation kept, the previous ``.1``
replaced) and a fresh file starts with its own ``[`` header — both
generations stay independently Perfetto-loadable and line-parseable.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


def trace_enabled() -> bool:
    """Read the switch per call (not cached at import): tests and operators
    flip RBT_TRACE around individual runs."""
    return os.environ.get("RBT_TRACE", "") == "1"


class _NullSpan:
    """Shared no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


def _max_trace_bytes() -> int:
    """Rotation threshold from RBT_TRACE_MAX_MB (default 256; fractional
    values allowed — tests rotate at a few hundred bytes). Read per open,
    not per write."""
    try:
        return int(float(os.environ.get("RBT_TRACE_MAX_MB", "256")) * 2**20)
    except ValueError:
        return 256 * 2**20


class _Writer:
    def __init__(self):
        self._lock = threading.Lock()
        self._path: Optional[str] = None   # guarded-by: _lock
        self._file = None                  # guarded-by: _lock
        self._bytes = 0                    # guarded-by: _lock
        self._max_bytes = 0                # guarded-by: _lock

    def configure(self, path: Optional[str]) -> None:
        with self._lock:
            if self._file is not None and path != self._path:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
            self._path = path

    def path(self) -> Optional[str]:
        with self._lock:
            if self._path is not None:
                return self._path
        from runbooks_tpu.utils import contract

        return os.path.join(contract.artifacts_dir(), "trace.jsonl")

    def write(self, event: dict) -> None:
        line = json.dumps(event, separators=(",", ":"))
        with self._lock:
            if self._file is None:
                path = self._path
                if path is None:
                    from runbooks_tpu.utils import contract

                    path = os.path.join(contract.artifacts_dir(),
                                        "trace.jsonl")
                    self._path = path
                try:
                    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                    size = (os.path.getsize(path)
                            if os.path.exists(path) else 0)
                    self._file = open(path, "a", buffering=1)
                    if size == 0:
                        self._file.write("[\n")
                        size = 2
                    self._bytes = size
                    self._max_bytes = _max_trace_bytes()
                except OSError:
                    # Tracing must never take down the workload: an
                    # unwritable path drops this event. The CONFIGURED
                    # path is kept (resetting it would silently reroute
                    # the rest of the run's spans to the contract-default
                    # location); the next write retries the open — e.g. a
                    # not-yet-mounted artifacts volume heals in place.
                    return
            try:
                self._file.write(line + ",\n")
                self._bytes += len(line) + 2
                if self._bytes >= self._max_bytes:
                    self._rotate_locked()
            except OSError:
                pass

    def _rotate_locked(self) -> None:  # guarded-by: _lock
        """Size cap hit: roll the live file to <path>.1 (replacing the
        previous generation) and start fresh. Caller holds the lock; the
        open failure mode matches write() — drop and retry later."""
        path = self._path
        try:
            self._file.close()
        except OSError:
            pass
        self._file = None
        if path is None:
            return
        try:
            os.replace(path, path + ".1")
            self._file = open(path, "a", buffering=1)
            self._file.write("[\n")
            self._bytes = 2
        except OSError:
            self._file = None

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


_WRITER = _Writer()


def configure(path: Optional[str]) -> None:
    """Repoint trace output (e.g. the trainer sets
    ``{artifacts}/trace.jsonl`` for its run). None reverts to the
    contract default."""
    _WRITER.configure(path)


def close() -> None:
    """Flush and close the trace file (end of a run; the next span
    reopens in append mode)."""
    _WRITER.close()


class _Span:
    """One complete event (``ph: "X"``): records wall-clock start and
    monotonic duration, written at exit."""

    __slots__ = ("name", "args", "_ts", "_t0")

    def __init__(self, name: str, args: dict):
        self.name = name
        self.args = args

    def __enter__(self):
        self._ts = time.time() * 1e6          # trace_event ts is in µs
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = (time.perf_counter() - self._t0) * 1e6
        event = {
            "name": self.name,
            "ph": "X",
            "ts": round(self._ts, 1),
            "dur": round(dur, 1),
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if self.args:
            event["args"] = self.args
        if exc_type is not None:
            event.setdefault("args", {})["error"] = exc_type.__name__
        _WRITER.write(event)
        return False


def span(name: str, /, **args):
    """Context manager tracing one phase: ``with span("prefill",
    bucket=128): ...``. Emits a Chrome complete event when RBT_TRACE=1;
    otherwise returns a shared no-op (no allocation beyond the env read).
    ``name`` is positional-only so span attributes may freely use "name"
    as a key (e.g. reconcile spans labeling the object name)."""
    if not trace_enabled():
        return _NULL
    return _Span(name, args)


def complete(name: str, duration_s: float, /, **args) -> None:
    """Emit a completed span for an interval measured elsewhere, ending
    now (``ph: "X"`` with ts backdated by the duration). Used for
    request-scoped phases whose start predates the code that knows their
    name — e.g. a request's queue wait, measured by the engine at
    admission time."""
    if not trace_enabled():
        return
    dur = max(float(duration_s), 0.0) * 1e6
    event = {
        "name": name,
        "ph": "X",
        "ts": round(time.time() * 1e6 - dur, 1),
        "dur": round(dur, 1),
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0x7FFFFFFF,
    }
    if args:
        event["args"] = args
    _WRITER.write(event)


def instant(name: str, /, **args) -> None:
    """Point-in-time marker (``ph: "i"``): checkpoint landed, preemption
    signal caught, profile started."""
    if not trace_enabled():
        return
    event = {
        "name": name,
        "ph": "i",
        "s": "p",
        "ts": round(time.time() * 1e6, 1),
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0x7FFFFFFF,
    }
    if args:
        event["args"] = args
    _WRITER.write(event)
