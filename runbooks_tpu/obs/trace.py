"""Lightweight JSONL trace spans (Chrome ``trace_event`` compatible).

``RBT_TRACE=1`` turns FILE emission on; independent of that switch,
every event built here also tees into the in-memory flight-recorder
ring (obs/flight.py, always on unless ``RBT_FLIGHT=0``) so the recent
timeline survives for ``/debug/flight``, tail sampling, and incident
bundles. With both switches off a span is a near-zero-cost no-op (one
env lookup + one shared null context manager per span, so the
instrumented hot loops — trainer steps, engine ticks, reconciles — pay
nothing when recording is off).

File format: the Chrome/Perfetto "JSON Array Format" with one event per
line — an opening ``[`` line, then ``{...},`` per event. The spec allows
the closing ``]`` to be omitted, so the file is loadable in Perfetto /
chrome://tracing at any moment (including mid-run or after a crash), and
each line (minus the trailing comma) is a complete JSON object — greppable
and streamable like any JSONL log.

Multi-pod merges: events carry a *trace pid* derived from host+pid (not
the bare OS pid), so concatenating trace files from a gateway and N
replica pods cannot collide two processes onto one Perfetto track; each
file generation opens with ``process_name``/``thread_name`` metadata
events (``ph: "M"``) naming the component, host, and real pid.

Default output: ``{artifacts}/trace.jsonl`` (the container contract's
durable mount); ``configure(path)`` repoints it (the trainer does, per
run). Writes are lock-serialized line appends, so concurrent spans from
the engine worker, checkpoint threads, and reconcilers interleave without
tearing.

Rotation: a long-running traced server would otherwise grow the file
without bound. When the file exceeds ``RBT_TRACE_MAX_MB`` (default 256)
it rolls to ``<path>.1`` (one generation kept, the previous ``.1``
replaced) and a fresh file starts with its own ``[`` header — both
generations stay independently Perfetto-loadable and line-parseable.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import socket
import threading
import time
import uuid
from typing import Optional, Tuple

from runbooks_tpu.obs import flight


def trace_enabled() -> bool:
    """Read the switch per call (not cached at import): tests and operators
    flip RBT_TRACE around individual runs."""
    return os.environ.get("RBT_TRACE", "") == "1"


def record_enabled() -> bool:
    """True when span events go ANYWHERE (trace file or flight ring) —
    the gate hot paths use before materializing span attributes
    (request-id lists etc.)."""
    return trace_enabled() or flight.recording()


class _NullSpan:
    """Shared no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


# -- trace pid (multi-pod merge safety) -------------------------------------

_TRACE_PID: Optional[Tuple[int, int]] = None  # (os pid, derived trace pid)


def trace_pid() -> int:
    """A stable 31-bit pid derived from host+pid: unique enough that
    merged traces from many pods don't collapse processes onto one
    Perfetto track (two hosts routinely share os pids like 1). Fork-safe
    (re-derived when os.getpid() changes)."""
    global _TRACE_PID
    pid = os.getpid()
    if _TRACE_PID is None or _TRACE_PID[0] != pid:
        digest = hashlib.sha1(
            f"{socket.gethostname()}:{pid}".encode()).digest()
        _TRACE_PID = (pid,
                      (int.from_bytes(digest[:4], "big") & 0x7FFFFFFF) or 1)
    return _TRACE_PID[1]


def _tid() -> int:
    return threading.get_ident() & 0x7FFFFFFF


def _max_trace_bytes() -> int:
    """Rotation threshold from RBT_TRACE_MAX_MB (default 256; fractional
    values allowed — tests rotate at a few hundred bytes). Read per open,
    not per write."""
    try:
        return int(float(os.environ.get("RBT_TRACE_MAX_MB", "256")) * 2**20)
    except ValueError:
        return 256 * 2**20


class _Writer:
    def __init__(self):
        self._lock = threading.Lock()
        self._path: Optional[str] = None   # guarded-by: _lock
        self._file = None                  # guarded-by: _lock
        self._bytes = 0                    # guarded-by: _lock
        self._max_bytes = 0                # guarded-by: _lock
        self._meta_tids: set = set()       # guarded-by: _lock

    def configure(self, path: Optional[str]) -> None:
        with self._lock:
            if self._file is not None and path != self._path:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
            self._path = path

    def path(self) -> Optional[str]:
        with self._lock:
            if self._path is not None:
                return self._path
        from runbooks_tpu.utils import contract

        return os.path.join(contract.artifacts_dir(), "trace.jsonl")

    def _write_line_locked(self, obj: dict) -> None:  # guarded-by: _lock
        line = json.dumps(obj, separators=(",", ":"))
        self._file.write(line + ",\n")
        self._bytes += len(line) + 2

    def _write_meta_locked(self, tid: Optional[int]) -> None:  # guarded-by: _lock
        """Perfetto metadata for this file generation: one process_name
        naming component@host + the real pid, then one thread_name per
        tid seen — merged multi-pod traces stay attributable even though
        events carry the derived trace pid."""
        ident = flight.identity()
        ts = round(time.time() * 1e6, 1)  # tolerated on M events; keeps
        # every line uniform for line-oriented consumers
        if not self._meta_tids:
            self._write_line_locked({
                "name": "process_name", "ph": "M", "ts": ts,
                "pid": trace_pid(), "tid": 0,
                "args": {"name": f"{ident['component']}@{ident['host']} "
                                 f"pid={ident['pid']}"}})
            self._meta_tids.add(0)
        if tid is not None and tid not in self._meta_tids:
            self._write_line_locked({
                "name": "thread_name", "ph": "M", "ts": ts,
                "pid": trace_pid(), "tid": tid,
                "args": {"name": f"{ident['component']}-{tid}"}})
            self._meta_tids.add(tid)

    def write(self, event: dict) -> None:
        with self._lock:
            if self._file is None:
                path = self._path
                if path is None:
                    from runbooks_tpu.utils import contract

                    path = os.path.join(contract.artifacts_dir(),
                                        "trace.jsonl")
                    self._path = path
                try:
                    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                    size = (os.path.getsize(path)
                            if os.path.exists(path) else 0)
                    self._file = open(path, "a", buffering=1)
                    if size == 0:
                        self._file.write("[\n")
                        size = 2
                    self._bytes = size
                    self._max_bytes = _max_trace_bytes()
                    self._meta_tids = set()
                except OSError:
                    # Tracing must never take down the workload: an
                    # unwritable path drops this event. The CONFIGURED
                    # path is kept (resetting it would silently reroute
                    # the rest of the run's spans to the contract-default
                    # location); the next write retries the open — e.g. a
                    # not-yet-mounted artifacts volume heals in place.
                    return
            try:
                self._write_meta_locked(event.get("tid"))
                self._write_line_locked(event)
                if self._bytes >= self._max_bytes:
                    self._rotate_locked()
            except OSError:
                pass

    def _rotate_locked(self) -> None:  # guarded-by: _lock
        """Size cap hit: roll the live file to <path>.1 (replacing the
        previous generation) and start fresh. Caller holds the lock; the
        open failure mode matches write() — drop and retry later."""
        path = self._path
        try:
            self._file.close()
        except OSError:
            pass
        self._file = None
        if path is None:
            return
        try:
            os.replace(path, path + ".1")
            self._file = open(path, "a", buffering=1)
            self._file.write("[\n")
            self._bytes = 2
            self._meta_tids = set()
        except OSError:
            self._file = None

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


_WRITER = _Writer()


def configure(path: Optional[str]) -> None:
    """Repoint trace output (e.g. the trainer sets
    ``{artifacts}/trace.jsonl`` for its run). None reverts to the
    contract default."""
    _WRITER.configure(path)


def close() -> None:
    """Flush and close the trace file (end of a run; the next span
    reopens in append mode)."""
    _WRITER.close()


def write_event(event: dict) -> None:
    """Write one already-built event to the trace file REGARDLESS of
    RBT_TRACE — the tail-sampling promotion path (obs/flight.py) uses it
    to land an interesting request's ring timeline on disk."""
    _WRITER.write(event)


def _emit(event: dict) -> None:
    """Route one event: the trace file when file tracing is on, the
    flight ring whenever the recorder is."""
    if trace_enabled():
        _WRITER.write(event)
    if flight.recording():
        flight.RING.record(event)


class _Span:
    """One complete event (``ph: "X"``): records wall-clock start and
    monotonic duration, emitted at exit."""

    __slots__ = ("name", "args", "_ts", "_t0")

    def __init__(self, name: str, args: dict):
        self.name = name
        self.args = args

    def __enter__(self):
        self._ts = time.time() * 1e6          # trace_event ts is in µs
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = (time.perf_counter() - self._t0) * 1e6
        event = {
            "name": self.name,
            "ph": "X",
            "ts": round(self._ts, 1),
            "dur": round(dur, 1),
            "pid": trace_pid(),
            "tid": _tid(),
        }
        if self.args:
            event["args"] = self.args
        if exc_type is not None:
            event.setdefault("args", {})["error"] = exc_type.__name__
        _emit(event)
        return False


def span(name: str, /, **args):
    """Context manager tracing one phase: ``with span("prefill",
    bucket=128): ...``. Emits a Chrome complete event to the trace file
    (RBT_TRACE=1) and/or the flight ring (RBT_FLIGHT, default on);
    otherwise returns a shared no-op (no allocation beyond the env
    reads). ``name`` is positional-only so span attributes may freely use
    "name" as a key (e.g. reconcile spans labeling the object name)."""
    if not record_enabled():
        return _NULL
    return _Span(name, args)


def complete(name: str, duration_s: float, /, **args) -> None:
    """Emit a completed span for an interval measured elsewhere, ending
    now (``ph: "X"`` with ts backdated by the duration). Used for
    request-scoped phases whose start predates the code that knows their
    name — e.g. a request's queue wait, measured by the engine at
    admission time."""
    if not record_enabled():
        return
    dur = max(float(duration_s), 0.0) * 1e6
    event = {
        "name": name,
        "ph": "X",
        "ts": round(time.time() * 1e6 - dur, 1),
        "dur": round(dur, 1),
        "pid": trace_pid(),
        "tid": _tid(),
    }
    if args:
        event["args"] = args
    _emit(event)


def make_instant(name: str, /, **args) -> dict:
    """Build (without emitting) an instant event — the tail-sampling
    promoter appends one as the promotion marker."""
    event = {
        "name": name,
        "ph": "i",
        "s": "p",
        "ts": round(time.time() * 1e6, 1),
        "pid": trace_pid(),
        "tid": _tid(),
    }
    if args:
        event["args"] = args
    return event


def instant(name: str, /, **args) -> None:
    """Point-in-time marker (``ph: "i"``): checkpoint landed, preemption
    signal caught, profile started."""
    if not record_enabled():
        return
    _emit(make_instant(name, **args))


# ---------------------------------------------------------------------------
# Request scope (shared by the serve API and the gateway — the gateway
# must not import serve/api, which pulls the JAX engine stack).
# ---------------------------------------------------------------------------

# W3C trace context (https://www.w3.org/TR/trace-context/):
# version-traceid-parentid-flags, all lowercase hex.
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")
# Client-supplied ids flow into response headers, logs, and trace JSON:
# strip anything that could split a header or forge a log line.
_RID_UNSAFE_RE = re.compile(r"[^A-Za-z0-9._:/-]")


def request_scope(headers) -> Tuple[str, Optional[str]]:
    """(request_id, traceparent_out) for one HTTP request.

    X-Request-Id is accepted verbatim (sanitized); a W3C ``traceparent``
    is also honored — its trace-id becomes the request id when no
    explicit one came, and the response carries a child ``traceparent``
    (same trace-id, fresh parent-id) so an upstream tracer can stitch
    the hop. With neither header, an id is generated. The id rides the
    queue/prefill/decode trace spans (obs/trace.py) and the access log,
    so one Perfetto trace follows one request across the engine — and,
    through the gateway's forwarded headers, across pods."""
    rid = headers.get("X-Request-Id") if headers else None
    tp_out = None
    tp = (headers.get("traceparent", "") if headers else "").strip().lower()
    m = _TRACEPARENT_RE.match(tp)
    if m:
        tp_out = (f"{m.group(1)}-{m.group(2)}-"
                  f"{uuid.uuid4().hex[:16]}-{m.group(4)}")
        if not rid:
            rid = m.group(2)
    if rid:
        rid = _RID_UNSAFE_RE.sub("", str(rid))[:128]
    if not rid:
        rid = f"req-{uuid.uuid4().hex[:16]}"
    return rid, tp_out


def mint_traceparent() -> str:
    """A fresh root W3C traceparent (sampled flag set) — the gateway
    mints one when the client supplied none, so every upstream hop
    carries a stitchable trace context."""
    return f"00-{uuid.uuid4().hex}-{uuid.uuid4().hex[:16]}-01"
