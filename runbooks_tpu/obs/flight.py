"""Flight recorder: an always-on bounded ring of recent trace events.

``RBT_TRACE=1`` file tracing (obs/trace.py) is opt-in because an
unbounded JSONL stream is the wrong default for a long-lived server —
but when a request times out at 3 a.m. the spans that would explain it
were exactly the ones nobody was writing. This module keeps the last N
span/instant events **in memory, always**, independent of the file
switch: obs/trace.py tees every event it builds into :data:`RING`, so
the recent timeline (queue-wait → prefill → decode chunks → finish) is
reconstructible after the fact at near-zero steady-state cost (one
lock-guarded deque append per event; measured in the
``RBT_BENCH_FLIGHT=1`` bench axis, acceptance < 1% of a decode step).

Surfaces:

- ``GET /debug/flight[?request_id=]`` on the serve API **and** the
  gateway returns the ring (filtered to one request id when given) plus
  the process identity (host/pid/component) so ``rbt trace`` can merge
  rings from multiple pods into one clock-ordered timeline.
- **Tail sampling** (:func:`tail_sample`): requests that finish slow
  (``RBT_TRACE_TAIL_MS``), by deadline, or by error get their ring
  timeline promoted to ``trace.jsonl`` even with ``RBT_TRACE=0`` — the
  interesting traces survive without paying file I/O for the boring
  ones.
- Incident snapshots (obs/incident.py) embed the ring wholesale.

``RBT_FLIGHT=0`` disables the ring entirely (the disabled path is the
pre-flight-recorder no-op); ``RBT_FLIGHT_RING`` sizes it (default 4096
events).
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque
from typing import List, Optional

DEFAULT_CAPACITY = 4096


def recording() -> bool:
    """Read the switch per call, like trace_enabled(): tests and
    operators flip RBT_FLIGHT around individual runs. Default ON."""
    return os.environ.get("RBT_FLIGHT", "1") != "0"


def ring_capacity() -> int:
    """Ring size from RBT_FLIGHT_RING (events, default 4096)."""
    try:
        return max(16, int(os.environ.get("RBT_FLIGHT_RING",
                                          str(DEFAULT_CAPACITY))))
    except ValueError:
        return DEFAULT_CAPACITY


# Process identity stamped on /debug/flight responses and trace metadata
# events: which pod/tier a merged timeline's events came from.
_COMPONENT = [os.environ.get("RBT_COMPONENT", "proc")]


def set_component(name: str) -> None:
    """Name this process's tier ("serve", "gateway", "train",
    "controller") for flight/trace identity. Last caller wins — a
    process hosting both a trainer and an engine is still one pod."""
    _COMPONENT[0] = str(name)


def component() -> str:
    return _COMPONENT[0]


def identity() -> dict:
    """Who recorded these events: merged-timeline disambiguation for
    `rbt trace` and the Perfetto process_name metadata."""
    return {"host": socket.gethostname(), "pid": os.getpid(),
            "component": _COMPONENT[0]}


def _matches(event: dict, rid: str) -> bool:
    """Does this event belong to request `rid`? Spans carry either a
    single ``request_id`` or a ``request_ids`` list (batched decode
    chunks); multi-prompt bodies suffix per choice (`<rid>/0`), which a
    query for the base id should still find."""
    args = event.get("args")
    if not isinstance(args, dict):
        return False
    one = args.get("request_id")
    if isinstance(one, str) and (one == rid or one.startswith(rid + "/")):
        return True
    many = args.get("request_ids")
    if isinstance(many, (list, tuple)):
        for x in many:
            if isinstance(x, str) and (x == rid
                                       or x.startswith(rid + "/")):
                return True
    return False


class FlightRecorder:
    """Bounded, lock-guarded ring of recent trace events (dicts in the
    Chrome trace_event shape obs/trace.py builds). Thread-safe: the
    engine worker, HTTP handlers, and checkpoint threads all record
    concurrently; snapshot() is what /debug/flight serializes."""

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        cap = capacity if capacity is not None else ring_capacity()
        self._ring: deque = deque(maxlen=cap)  # guarded-by: _lock
        self.recorded = 0                      # guarded-by: _lock
        self.dropped = 0                       # guarded-by: _lock

    def record(self, event: dict) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(event)
            self.recorded += 1

    def snapshot(self, request_id: Optional[str] = None,
                 limit: Optional[int] = None) -> List[dict]:
        """Copy of the ring (oldest first), optionally filtered to one
        request id. The copy happens under the lock; filtering does not
        (events are append-only dicts once recorded)."""
        with self._lock:
            events = list(self._ring)
        if request_id:
            events = [e for e in events if _matches(e, request_id)]
        if limit is not None and limit >= 0:
            events = events[-limit:]
        return events

    def stats(self) -> dict:
        with self._lock:
            return {"events": len(self._ring),
                    "capacity": self._ring.maxlen,
                    "recorded": self.recorded,
                    "dropped": self.dropped}

    def resize(self, capacity: int) -> None:
        """Rebuild the ring at a new capacity, keeping the newest
        events (tests; RBT_FLIGHT_RING covers deployments)."""
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(16, int(capacity)))

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.recorded = 0
            self.dropped = 0


# The process-wide ring obs/trace.py tees into.
RING = FlightRecorder()


# ---------------------------------------------------------------------------
# Tail sampling
# ---------------------------------------------------------------------------

def tail_threshold_ms() -> Optional[float]:
    """RBT_TRACE_TAIL_MS: latency past which a finished request's ring
    timeline is promoted to trace.jsonl even with RBT_TRACE=0. Unset or
    malformed = no latency-based promotion (error/deadline promotion
    stays on whenever the ring records)."""
    raw = os.environ.get("RBT_TRACE_TAIL_MS", "")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def _tail_event_cap() -> int:
    """Max events one promotion writes (newest kept). Bounds the file
    I/O a single interesting request can charge the engine thread."""
    try:
        return max(16, int(os.environ.get("RBT_TRACE_TAIL_EVENTS",
                                          "512")))
    except ValueError:
        return 512


class _PromotionBudget:
    """Promotions-per-second limiter for tail sampling. Promotion runs
    ON the engine worker thread between decode chunks; a deadline storm
    (every slot expiring in one pass) or the crash handler dooming a
    whole batch would otherwise write O(slots x ring) JSON lines while
    healthy requests wait. Classification (the counter) is never
    limited — only the file writes are."""

    def __init__(self):
        self._lock = threading.Lock()
        self._window_start = 0.0  # guarded-by: _lock
        self._spent = 0           # guarded-by: _lock

    @staticmethod
    def _per_second() -> int:
        try:
            return max(1, int(os.environ.get("RBT_TRACE_TAIL_PER_S",
                                             "10")))
        except ValueError:
            return 10

    def admit(self) -> bool:
        now = time.monotonic()
        with self._lock:
            if now - self._window_start >= 1.0:
                self._window_start = now
                self._spent = 0
            if self._spent >= self._per_second():
                return False
            self._spent += 1
            return True


_PROMOTIONS = _PromotionBudget()


def tail_sample(request_id: str, duration_s: float, finish_reason: str,
                error: bool = False) -> bool:
    """Terminal hook per request (the engine calls it from
    ``_observe_request_done``; the serve worker's crash handler calls it
    with ``error=True``): promote the request's ring timeline to the
    trace file when the request was *interesting* — errored, finished by
    deadline, or slower than ``RBT_TRACE_TAIL_MS``. With ``RBT_TRACE=1``
    the events are already in the file, so promotion is skipped (only
    the counter records the classification). Returns True when events
    were promoted."""
    if not request_id or not recording():
        return False
    reason = None
    if error:
        reason = "error"
    elif finish_reason == "deadline":
        reason = "deadline"
    else:
        threshold = tail_threshold_ms()
        if threshold is not None and duration_s * 1000.0 >= threshold:
            reason = "slow"
    if reason is None:
        return False
    from runbooks_tpu.obs import metrics as obs_metrics
    from runbooks_tpu.obs import trace as obs_trace

    obs_metrics.REGISTRY.inc(
        "serve_tail_samples_total", reason=reason,
        help_text="Requests whose flight-ring timeline was promoted to "
                  "trace.jsonl (slow/deadline/error tail sampling).")
    if obs_trace.trace_enabled():
        return False  # already on disk via the live tracer
    # Promotion budget BEFORE the ring scan: a storm finishing a whole
    # batch "interesting" at once must not charge the engine thread an
    # O(ring) snapshot+filter per doomed request, let alone the file
    # I/O (each request's filter re-selects the batch's shared decode
    # spans — O(slots x ring) worst case). The classification counter
    # above still recorded; a budget token is occasionally spent on a
    # request whose events already wrapped out (empty snapshot), which
    # is the cheap side of that trade.
    if not _PROMOTIONS.admit():
        return False
    events = RING.snapshot(request_id=request_id)
    if not events:
        return False
    for event in events[-_tail_event_cap():]:
        obs_trace.write_event(event)
    obs_trace.write_event(obs_trace.make_instant(
        "tail_sample", reason=reason, request_id=request_id,
        duration_ms=round(duration_s * 1000.0, 1),
        finish_reason=finish_reason))
    return True
