"""Incident snapshots: the system captures its own evidence.

When ``SLOViolated`` fires, the engine crash handler dooms in-flight
requests, or the trainer aborts on ``max_bad_steps``, the state an
operator needs (``/debug/memory``, ``/debug/programs``, the recent span
timeline, queue depths, the metrics exposition) is gone before anyone
can curl it. :func:`capture` bundles all of it — the flight ring
(obs/flight.py), the full Prometheus exposition, the device memory /
live-array census, the compiled-program census, and the
unexpected-compile ring — into one timestamped JSON file under
``{artifacts}/incidents/``, written atomically (temp + ``os.replace``)
so a reader can never observe a torn bundle.

Captures are **debounced** (per-reason, ``RBT_INCIDENT_DEBOUNCE_S``,
default 60 s) and **rate-limited** (a global floor between any two
bundles) because the failure modes that fire them come in storms — a
crash-looping engine must leave one bundle per storm, not a bundle per
loop. Old bundles are pruned past ``RBT_INCIDENT_KEEP`` (default 20).

Fired automatically by the serve worker's crash handler, the trainer's
``max_bad_steps`` abort, and — via ``POST /debug/incident`` against
each replica — by the controller on an ``SLOViolated`` onset
(controller/server.py). ``rbt incidents`` lists and fetches bundles;
the Server's ``.status.lastIncident`` points at the latest one.

capture() must never raise: it runs inside crash handlers, so every
sub-collection degrades to an error note instead of propagating.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from runbooks_tpu.obs import flight
from runbooks_tpu.obs import metrics as obs_metrics

DEFAULT_DEBOUNCE_S = 60.0
# Global floor between any two bundles, whatever their reasons: a storm
# that rotates reasons must still not write faster than this.
MIN_INTERVAL_S = 5.0
DEFAULT_KEEP = 20

# Filename-safe reason slug (reasons flow in from HTTP bodies).
_SLUG_UNSAFE = str.maketrans(
    {c: "-" for c in "/\\ \t\n\r:\"'<>|?*"})


def _debounce_s() -> float:
    try:
        return float(os.environ.get("RBT_INCIDENT_DEBOUNCE_S",
                                    str(DEFAULT_DEBOUNCE_S)))
    except ValueError:
        return DEFAULT_DEBOUNCE_S


def _keep() -> int:
    try:
        return max(1, int(os.environ.get("RBT_INCIDENT_KEEP",
                                         str(DEFAULT_KEEP))))
    except ValueError:
        return DEFAULT_KEEP


def incidents_dir(artifacts: Optional[str] = None) -> str:
    from runbooks_tpu.utils import contract

    base = artifacts if artifacts is not None else contract.artifacts_dir()
    return os.path.join(base, "incidents")


class IncidentManager:
    """Debounce/rate-limit book + the capture implementation. One
    process-wide instance (:data:`MANAGER`); tests reset() it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._last_by_reason: Dict[str, float] = {}  # guarded-by: _lock
        self._last_any: float = 0.0                  # guarded-by: _lock
        self._last_path: Optional[str] = None        # guarded-by: _lock
        self._last_wall: Optional[float] = None      # guarded-by: _lock

    def reset(self) -> None:
        with self._lock:
            self._last_by_reason.clear()
            self._last_any = 0.0
            self._last_path = None
            self._last_wall = None

    def last_age(self) -> Optional[float]:
        """Seconds since this process's last captured bundle, or None —
        the serve_incident_age_seconds gauge (and `rbt top`'s lastinc
        cell) read it at scrape time."""
        with self._lock:
            if self._last_wall is None:
                return None
            return max(0.0, time.time() - self._last_wall)

    def last_path(self) -> Optional[str]:
        with self._lock:
            return self._last_path

    def _admit(self, reason: str) -> bool:
        """One debounce/rate-limit decision, atomically: a storm of
        concurrent captures (crash handler + HTTP + controller POST)
        must elect exactly one writer."""
        now = time.monotonic()
        debounce = _debounce_s()
        with self._lock:
            last = self._last_by_reason.get(reason)
            if last is not None and now - last < debounce:
                return False
            if self._last_any and now - self._last_any < MIN_INTERVAL_S:
                # Cross-reason storm floor, applied to EVERY reason: a
                # storm that rotates reasons (flapping SLO objectives)
                # must not write faster than this even once each
                # per-reason debounce window expires.
                return False
            self._last_by_reason[reason] = now
            self._last_any = now
            return True

    def capture(self, reason: str, *,
                artifacts: Optional[str] = None,
                component: Optional[str] = None,
                memory_groups: Optional[dict] = None,
                extra: Optional[Dict[str, Any]] = None,
                registry: Optional[obs_metrics.Registry] = None,
                ) -> Optional[str]:
        """Write one incident bundle; returns its path, or None when the
        capture was debounced/rate-limited. Never raises."""
        reason = (str(reason) or "unknown").translate(_SLUG_UNSAFE)[:64]
        if not self._admit(reason):
            return None
        try:
            return self._capture_admitted(reason, artifacts, component,
                                          memory_groups, extra, registry)
        except Exception as exc:  # noqa: BLE001 — runs in crash handlers
            print(f"incident: capture({reason}) failed: {exc!r}",
                  flush=True)
            return None

    def _capture_admitted(self, reason, artifacts, component,
                          memory_groups, extra, registry) -> Optional[str]:
        from runbooks_tpu.obs import device as obs_device

        reg = registry if registry is not None else obs_metrics.REGISTRY
        # Count BEFORE rendering the exposition below, so the bundle's
        # own metrics snapshot already carries this capture (and counts
        # admitted attempts even if a later section fails).
        reg.inc("serve_incidents_total", reason=reason,
                help_text="Incident bundles captured, by trigger reason.")
        wall = time.time()
        bundle: Dict[str, Any] = {
            "reason": reason,
            "time": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                  time.gmtime(wall)),
            "unix_time": round(wall, 3),
            **flight.identity(),
        }
        if component:
            bundle["component"] = component
        if extra:
            bundle["extra"] = extra
        # Every section degrades independently: a half-broken process is
        # exactly when a bundle is most needed.
        try:
            bundle["flight"] = {"stats": flight.RING.stats(),
                                "events": flight.RING.snapshot()}
        except Exception as exc:  # noqa: BLE001
            bundle["flight"] = {"error": repr(exc)}
        try:
            bundle["metrics"] = reg.render()
        except Exception as exc:  # noqa: BLE001
            bundle["metrics"] = f"render failed: {exc!r}"
        try:
            bundle["memory"] = obs_device.memory_snapshot(memory_groups)
        except Exception as exc:  # noqa: BLE001
            bundle["memory"] = {"error": repr(exc)}
        try:
            bundle["programs"] = obs_device.PROGRAMS.census()
        except Exception as exc:  # noqa: BLE001
            bundle["programs"] = [{"error": repr(exc)}]
        sentinel = obs_device.SENTINEL
        try:
            bundle["compiles"] = {
                "total": sentinel.total,
                "unexpected": sentinel.unexpected,
                "compile_seconds": round(sentinel.compile_seconds, 3),
                "steady": sentinel.steady_components(),
                "last_unexpected": sentinel.recent_unexpected(),
            }
        except Exception as exc:  # noqa: BLE001
            bundle["compiles"] = {"error": repr(exc)}

        out_dir = incidents_dir(artifacts)
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(wall))
        name = f"{stamp}-{reason}.json"
        path = os.path.join(out_dir, name)
        os.makedirs(out_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        os.replace(tmp, path)
        with self._lock:
            self._last_path = path
            self._last_wall = wall
        print(f"incident: captured {reason} -> {path}", flush=True)
        self._prune(out_dir)
        return path

    @staticmethod
    def _prune(out_dir: str) -> None:
        try:
            names = sorted(n for n in os.listdir(out_dir)
                           if n.endswith(".json"))
            for doomed in names[:-_keep()] if len(names) > _keep() else []:
                os.remove(os.path.join(out_dir, doomed))
        except OSError:
            pass  # pruning is hygiene, never worth failing a capture


MANAGER = IncidentManager()


def capture(reason: str, **kwargs) -> Optional[str]:
    """Module-level convenience over :data:`MANAGER`."""
    return MANAGER.capture(reason, **kwargs)


def list_incidents(artifacts: Optional[str] = None) -> List[dict]:
    """Bundle metadata (name/reason/time/size), newest first — what
    ``GET /debug/incidents`` and ``rbt incidents`` render."""
    out_dir = incidents_dir(artifacts)
    out: List[dict] = []
    try:
        names = sorted(os.listdir(out_dir), reverse=True)
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(out_dir, name)
        entry = {"name": name, "path": path}
        try:
            entry["size_bytes"] = os.path.getsize(path)
        except OSError:
            continue
        stem = name[:-len(".json")]
        stamp, _, reason = stem.partition("-")
        entry["reason"] = reason or "unknown"
        entry["time"] = stamp
        out.append(entry)
    return out


def read_incident(name: str,
                  artifacts: Optional[str] = None) -> Optional[dict]:
    """Load one bundle by its listing name. The name is validated
    against the directory listing (no path traversal from HTTP input)."""
    for entry in list_incidents(artifacts):
        if entry["name"] == name:
            try:
                with open(entry["path"]) as f:
                    return json.load(f)
            except (OSError, json.JSONDecodeError):
                return None
    return None
