"""Unified observability subsystem: metrics, trace spans, goodput, profiling.

One telemetry surface shared by the trainer, the serve engine/API, the
controller, and the benches (reference analog: controller-runtime's metrics
endpoint + config/prometheus/monitor.yaml — but extended with histograms,
Chrome-trace spans, goodput accounting, and on-demand XLA profiling, which
the reference has none of; SURVEY.md §5.1). Per-phase timing and goodput
accounting are what TPU-scale tuning lives on (arXiv:2011.03641,
arXiv:1909.09756): every perf PR after this one is judged against these
numbers.

- ``obs.metrics``  — process-wide Prometheus registry (counters, gauges,
  fixed-bucket histograms) with spec-correct text exposition.
- ``obs.trace``    — RBT_TRACE=1 JSONL trace spans (Chrome ``trace_event``
  compatible; loads in Perfetto / chrome://tracing).
- ``obs.goodput``  — productive-step-time ÷ wall-clock accounting,
  restart/restore-aware (pairs with docs/fault-tolerance.md resume).
- ``obs.profile``  — on-demand ``jax.profiler`` capture (serve API
  ``POST /debug/profile``; trainer ``RBT_PROFILE_AT_STEP``).
- ``obs.device``   — device-level: recompilation sentinel
  (``xla_unexpected_compiles_total``), HBM/live-array accounting,
  roofline (compute- vs bandwidth-bound) attribution per program.
- ``obs.history``  — bounded fleet time-series rings (two-resolution,
  staleness-aware, snapshot-persisted) behind windowed SLO burn-rate
  alerting, the controller's ``GET /metrics/history``, and ``rbt dash``.

See docs/observability.md for the metric catalog and how-tos.
"""

from runbooks_tpu.obs.metrics import (  # noqa: F401
    CONTENT_TYPE,
    REGISTRY,
    Registry,
    serve_metrics,
)
from runbooks_tpu.obs.trace import span, trace_enabled  # noqa: F401
from runbooks_tpu.obs.goodput import GoodputTracker  # noqa: F401
