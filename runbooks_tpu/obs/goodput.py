"""Goodput accounting: productive step time ÷ accountable wall clock.

"Goodput" per the TPU-scale training literature (arXiv:2011.03641,
arXiv:1909.09756): the fraction of wall-clock the job spends computing
steps that advance training, as opposed to waiting on input, writing
checkpoints, or paying restart overhead. The trainer feeds one tracker per
run; the ratio and its component breakdown land in ``metrics.json``, the
per-log-step JSON line, and the process registry.

Restart-awareness (the PR-4 resume path): restore and recompile time after
a preemption are *excluded* from the accountable window — they are
restart overhead, reported separately (``restore_s``/``compile_s``), so a
fault-injected resume reports the same steady-state goodput as an
uninterrupted run instead of a ratio silently dragged down by however long
the restore happened to take. Fleet-level "goodput including restarts" is
recoverable from the same snapshot: ``productive_s / wall_s``.
"""

from __future__ import annotations

import time
from typing import Dict


class GoodputTracker:
    """Accumulates per-step phase timings; all methods are cheap (float
    adds), safe to call once per training step."""

    def __init__(self):
        self._t0 = time.perf_counter()
        self.productive_s = 0.0     # step compute (dispatch + device sync)
        self.data_wait_s = 0.0      # blocked on the input pipeline
        self.ckpt_s = 0.0           # blocking checkpoint time
        self.excluded_s = 0.0       # restart overhead (restore + compile)
        self.excluded: Dict[str, float] = {}
        self.steps = 0

    def exclude(self, seconds: float, kind: str) -> None:
        """Remove restart overhead (``restore``, ``compile``) from the
        accountable window; tracked per kind for the breakdown."""
        if seconds and seconds > 0:
            self.excluded_s += seconds
            self.excluded[kind] = self.excluded.get(kind, 0.0) + seconds

    def step(self, step_s: float, data_wait_s: float = 0.0,
             ckpt_s: float = 0.0) -> None:
        self.productive_s += max(step_s, 0.0)
        self.data_wait_s += max(data_wait_s, 0.0)
        self.ckpt_s += max(ckpt_s, 0.0)
        self.steps += 1

    def wall_s(self) -> float:
        return time.perf_counter() - self._t0

    def ratio(self) -> float:
        """Productive fraction of the accountable window (wall minus
        restart overhead). Clamped to [0, 1]: phase timings measured
        around adjacent host calls can overlap the window edges by
        microseconds."""
        accountable = self.wall_s() - self.excluded_s
        if accountable <= 0:
            return 0.0
        return min(self.productive_s / accountable, 1.0)

    def snapshot(self) -> Dict[str, float]:
        """The breakdown written to metrics.json: every accounted bucket
        plus the raw wall clock, so both goodput definitions (steady-state
        and including restarts) are recomputable downstream."""
        wall = self.wall_s()
        return {
            "goodput": round(self.ratio(), 4),
            "productive_s": round(self.productive_s, 3),
            "data_wait_s": round(self.data_wait_s, 3),
            "ckpt_s": round(self.ckpt_s, 3),
            "restore_s": round(self.excluded.get("restore", 0.0), 3),
            "compile_s": round(self.excluded.get("compile", 0.0), 3),
            "wall_s": round(wall, 3),
            "steps": self.steps,
        }
