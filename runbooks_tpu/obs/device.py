"""Device-level observability: what happens below the dispatch boundary.

PR-5/6 built the host- and fleet-side telemetry planes; this module covers
the three device-side blind spots that dominate at-scale failures
(arXiv:2011.03641 §"compilation", arXiv:1909.09756 §startup — PAPERS.md):

- **Recompilation sentinel** (:data:`SENTINEL`): a process-wide compile
  tracker fed by ``jax.monitoring`` duration events. Every backend compile
  counts into ``xla_compilations_total`` / ``xla_compile_seconds``; once a
  component declares itself *steady* (the serve engine after warmup, the
  trainer after its first step), any further compile outside an
  :meth:`CompileSentinel.expected` block is a serve-time stall — it fires a
  loud log line, ``xla_unexpected_compiles_total``, and a trace instant.
  The engine's whole compile discipline ("no recompiles at serve time",
  serve/engine.py) stops being a comment and becomes a measured counter.

- **HBM / memory accounting**: per-device ``memory_stats()`` gauges
  (``device_memory_*``) plus a ``jax.live_arrays()`` census that attributes
  bytes to caller-named groups (weights / KV cache / optimizer state /
  other). On CPU ``memory_stats()`` is absent — the census alone still
  answers "what is holding the bytes".

- **Roofline attribution**: per-compiled-program ``cost_analysis()`` FLOPs
  and HBM bytes (captured from the *lowering*, no second backend compile),
  rolled into arithmetic intensity and a compute- vs bandwidth-bound
  classification against the chip's peak FLOP/s and HBM bandwidth
  (utils/hw.py). The engine's "decode is HBM-bound" claim becomes the
  ``xla_program_bandwidth_bound`` gauge; analytic MFU cross-checks the
  wall-clock MFU the trainer/bench report.

Everything degrades gracefully off-TPU; see docs/observability.md
("Device-level metrics") for the catalog and PromQL.
"""

from __future__ import annotations

import contextlib
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from runbooks_tpu.obs import metrics as obs_metrics
from runbooks_tpu.obs import trace as obs_trace

# The jax.monitoring event one backend (XLA) compile emits; its value is
# the compile wall time in seconds.
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# Compile times run from ~10 ms (tiny CPU programs) to minutes (pod-scale
# train steps); the default latency buckets top out at 30 s.
_COMPILE_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                    10.0, 30.0, 60.0, 120.0, 300.0)

# Nominal peaks for classification when the chip is unknown (CPU tests,
# new TPU generations): roofline *classification* must still work — the
# ridge point (peak_flops / bandwidth) is what decides compute- vs
# bandwidth-bound, and these keep it in a realistic accelerator regime
# (ridge = 10 FLOPs/byte).
NOMINAL_PEAK_FLOPS = 1e12
NOMINAL_HBM_BPS = 100e9


# ---------------------------------------------------------------------------
# Recompilation sentinel
# ---------------------------------------------------------------------------

class CompileSentinel:
    """Process-wide compiled-program tracker + post-warmup compile alarm.

    ``install()`` hooks ``jax.monitoring``; every backend compile then
    counts into the registry. Components call ``mark_steady(name)`` when
    their compile phase is over (warmup done / first step folded); from
    then on a compile outside an ``expected()`` block increments
    ``xla_unexpected_compiles_total``, prints a loud line, and emits a
    trace instant — on a serving path that compile just stalled every
    in-flight request for its duration (measured ~27 s cold on the v5e
    relay; serve/engine.py).

    ``expected()`` is thread-local: JAX compiles on the thread that traced
    the call, so the engine worker's intentional background prefix warms
    (serve/api.py ``_warm_one``) and the trainer's checkpoint machinery
    wrap themselves without masking compiles from other threads.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._installed = False                 # guarded-by: _lock
        self._degraded: Optional[str] = None    # guarded-by: _lock
        # component -> number of live claimants. Counted, not boolean:
        # two engines in one process both mark "serve"; the first one
        # stopping must not blind the sentinel for the survivor.
        self._steady: Dict[str, int] = {}       # guarded-by: _lock
        self._local = threading.local()
        self.total = 0                          # guarded-by: _lock
        self.unexpected = 0                     # guarded-by: _lock
        self.compile_seconds = 0.0              # guarded-by: _lock
        # Ring of the most recent unexpected-compile records (operators
        # read it via /debug/programs; tests assert on it).
        self.last_unexpected: List[dict] = []   # guarded-by: _lock

    # -- lifecycle ------------------------------------------------------

    def install(self) -> bool:
        """Idempotently hook jax.monitoring. Returns True when the
        monitoring feed is live; False when this jax build has no usable
        monitoring API (the sentinel then still serves the census and
        steady bookkeeping, it just cannot observe compiles)."""
        with self._lock:
            if self._installed:
                return self._degraded is None
            self._installed = True
            # Zero-init both counters: a PromQL increase()/rate() alert
            # needs the series to exist BEFORE the first onset, and the
            # healthy state (zero unexpected compiles) must be a visible
            # 0, not an absent series.
            reg = obs_metrics.REGISTRY
            reg.inc("xla_compilations_total", 0.0,
                    help_text="Backend (XLA) compiles in this process.")
            reg.inc("xla_unexpected_compiles_total", 0.0,
                    help_text="Compiles after a component marked steady — "
                              "each one stalled live work for its "
                              "duration.")
            try:
                import jax.monitoring

                jax.monitoring.register_event_duration_secs_listener(
                    self._on_duration)
            except Exception as exc:  # noqa: BLE001 — degrade, don't crash
                self._degraded = repr(exc)
                print(f"device-obs: jax.monitoring unavailable ({exc!r}); "
                      "compile sentinel degraded to census-only",
                      flush=True)
                return False
            return True

    def mark_steady(self, component: str) -> None:
        """Declare `component`'s compile phase over: compiles from here on
        are stalls unless wrapped in expected(). Each mark pairs with one
        clear_steady (refcounted per component)."""
        with self._lock:
            self._steady[component] = self._steady.get(component, 0) + 1

    def clear_steady(self, component: Optional[str] = None) -> None:
        """Withdraw one steadiness claim (run ended / engine stopped).
        None force-clears every component (tests)."""
        with self._lock:
            if component is None:
                self._steady.clear()
            elif component in self._steady:
                self._steady[component] -= 1
                if self._steady[component] <= 0:
                    del self._steady[component]

    def steady_components(self) -> List[str]:
        with self._lock:
            return sorted(self._steady)

    def recent_unexpected(self) -> List[dict]:
        """Snapshot of the last-unexpected ring. The live list mutates
        under the lock on whichever thread compiles; callers (the
        /debug/programs handler serializing during a compile storm) must
        not iterate the shared object."""
        with self._lock:
            return [dict(r) for r in self.last_unexpected]

    @contextlib.contextmanager
    def expected(self):
        """Mark compiles on THIS thread as intentional (warmup sweeps,
        background prefix warms, checkpoint plumbing)."""
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        try:
            yield
        finally:
            self._local.depth = depth

    # -- event feed -----------------------------------------------------

    def _on_duration(self, name: str, value: float, **kw) -> None:
        if name != COMPILE_EVENT:
            return
        reg = obs_metrics.REGISTRY
        with self._lock:
            self.total += 1
            self.compile_seconds += float(value)
            steady = sorted(self._steady)
        reg.inc("xla_compilations_total",
                help_text="Backend (XLA) compiles in this process.")
        reg.observe("xla_compile_seconds", float(value),
                    buckets=_COMPILE_BUCKETS,
                    help_text="Backend compile wall time per program.")
        if not steady or getattr(self._local, "depth", 0):
            return
        with self._lock:
            self.unexpected += 1
            record = {"seconds": round(float(value), 3),
                      "steady": steady, "time": time.time()}
            self.last_unexpected.append(record)
            del self.last_unexpected[:-16]
        reg.inc("xla_unexpected_compiles_total",
                help_text="Compiles after a component marked steady — "
                          "each one stalled live work for its duration.")
        print(f"device-obs: UNEXPECTED XLA COMPILE ({value:.2f}s) after "
              f"steady mark ({','.join(steady)}) — a compile here stalls "
              "every in-flight request/step for its duration; see "
              "docs/troubleshooting.md (xla_unexpected_compiles_total)",
              flush=True)
        obs_trace.instant("unexpected_compile",
                          seconds=round(float(value), 3),
                          steady=",".join(steady))


SENTINEL = CompileSentinel()


# ---------------------------------------------------------------------------
# Compiled-program census + roofline costs
# ---------------------------------------------------------------------------

class ProgramTracker:
    """Census of the jitted entry points each component runs, with their
    live compiled-variant counts (``fn._cache_size()``) and per-shape
    roofline costs. The registry view is the ``xla_programs`` /
    ``xla_program_*`` gauge families; /debug/programs renders the same
    data as a table."""

    def __init__(self):
        self._lock = threading.Lock()
        # (component, name) ->
        #   {"fn_ref": weakref-to-jitted-fn | None, "costs": {sig: cost}}
        self._programs: Dict[Tuple[str, str], dict] = {}  # guarded-by: _lock
        # (registry id, component) -> program names last exported there,
        # so set_gauges can DROP series whose program died/re-registered
        # instead of leaving a dead model's numbers on the exposition.
        self._exported: Dict[Tuple[int, Optional[str]], set] = {}  # guarded-by: _lock

    @staticmethod
    def _make_ref(fn: Any):
        if fn is None:
            return None
        try:
            # WEAK reference on purpose: a jitted fn's closure pins its
            # owner (the engine's decode fns capture the engine — params
            # and KV pool included). A strong ref here would keep a
            # discarded engine's HBM alive until process exit.
            return weakref.ref(fn)
        except TypeError:
            return lambda: fn

    def register(self, component: str, name: str, fn: Any) -> None:
        """(Re-)register a jitted entry point. Registration RESETS the
        recorded costs: a rebuilt engine / fresh run may carry a
        different model config behind the same program name, and serving
        the previous model's FLOPs for it would silently falsify the
        roofline gauges. The owner re-records at its warmup."""
        with self._lock:
            self._programs[(component, name)] = {
                "fn_ref": self._make_ref(fn), "costs": {}}

    def record_cost(self, component: str, name: str, shape_sig: str,
                    cost: Optional[dict]) -> None:
        if cost is None:
            return
        with self._lock:
            entry = self._programs.setdefault(
                (component, name), {"fn_ref": None, "costs": {}})
            entry["costs"][shape_sig] = dict(cost)

    def has_cost(self, component: str, name: str, shape_sig: str) -> bool:
        with self._lock:
            entry = self._programs.get((component, name))
            return bool(entry and shape_sig in entry["costs"])

    def census(self, component: Optional[str] = None) -> List[dict]:
        out = []
        doomed = []
        with self._lock:
            items = sorted(self._programs.items())
        for (comp, name), entry in items:
            fn = entry["fn_ref"]() if entry["fn_ref"] is not None else None
            if entry["fn_ref"] is not None and fn is None:
                # The owning engine/run was garbage-collected: its
                # programs are gone, so the census row is too.
                doomed.append((comp, name))
                continue
            if component is not None and comp != component:
                continue
            variants = None
            try:
                if fn is not None and hasattr(fn, "_cache_size"):
                    variants = int(fn._cache_size())
            except Exception:  # noqa: BLE001 — census must not crash
                variants = None
            out.append({"component": comp, "name": name,
                        "programs": variants,
                        "costs": {k: dict(v)
                                  for k, v in entry["costs"].items()}})
        if doomed:
            with self._lock:
                for key in doomed:
                    entry = self._programs.get(key)
                    if entry is not None and entry["fn_ref"] is not None \
                            and entry["fn_ref"]() is None:
                        del self._programs[key]
        return out

    def set_gauges(self, registry: Optional[obs_metrics.Registry] = None,
                   component: Optional[str] = None) -> None:
        """Mirror the census into the registry (call at scrape time).

        Each program's series are dropped before being re-set, and
        programs gone from the census (engine rebuilt / garbage-
        collected) have their series dropped entirely — a dead model's
        FLOPs must not keep rendering as live gauges."""
        reg = registry if registry is not None else obs_metrics.REGISTRY
        census = self.census(component)
        live = {(e["component"], e["name"]) for e in census}
        key = (id(reg), component)
        with self._lock:
            gone = self._exported.get(key, set()) - live
            self._exported[key] = live
        for comp, name in gone:
            reg.drop_series(component=comp, program=name)
        for entry in census:
            labels = {"component": entry["component"],
                      "program": entry["name"]}
            # Clear stale values first: a re-registered program with no
            # recorded costs yet must not show its predecessor's numbers.
            reg.drop_series(**labels)
            if entry["programs"] is not None:
                reg.set_gauge("xla_programs", entry["programs"],
                              help_text="Live compiled variants per jitted "
                                        "entry point.", **labels)
            costs = entry["costs"]
            if not costs:
                continue
            # One gauge per program: the largest shape is the one that
            # bounds memory/time (warmup walks shapes smallest-last only
            # for prefill rows; max-flops is the stable choice).
            cost = max(costs.values(), key=lambda c: c.get("flops", 0.0))
            reg.set_gauge("xla_program_flops", cost.get("flops", 0.0),
                          help_text="Analytic FLOPs per invocation "
                                    "(cost_analysis).", **labels)
            reg.set_gauge("xla_program_hbm_bytes",
                          cost.get("hbm_bytes", 0.0),
                          help_text="Analytic bytes accessed per "
                                    "invocation (cost_analysis).", **labels)
            if cost.get("arithmetic_intensity") is not None:
                reg.set_gauge("xla_program_arithmetic_intensity",
                              cost["arithmetic_intensity"],
                              help_text="FLOPs per byte accessed.",
                              **labels)
            if cost.get("bound"):
                reg.set_gauge("xla_program_bandwidth_bound",
                              int(cost["bound"] == "bandwidth"),
                              help_text="1 when the program sits left of "
                                        "the roofline ridge (HBM-bound).",
                              **labels)


PROGRAMS = ProgramTracker()


def cost_analysis_of(fn, *args, **kwargs) -> Optional[dict]:
    """FLOPs / bytes-accessed for one jitted call at these arg shapes,
    from the *lowering's* cost analysis — tracing only, no second backend
    compile (donated buffers are safe: nothing executes). Returns None
    when the backend offers no analysis (some plugin backends)."""
    try:
        lowered = fn.lower(*args, **kwargs)
        analysis = lowered.cost_analysis()
    except Exception:  # noqa: BLE001 — optional telemetry, never fatal
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    flops = float(analysis.get("flops", 0.0) or 0.0)
    hbm = float(analysis.get("bytes accessed", 0.0) or 0.0)
    return {"flops": flops, "hbm_bytes": hbm}


def classify_roofline(flops: float, hbm_bytes: float,
                      peak_flops: Optional[float] = None,
                      hbm_bytes_per_sec: Optional[float] = None) -> dict:
    """Roofline classification of one program: arithmetic intensity
    (FLOPs/byte) against the ridge point (peak FLOP/s ÷ HBM bandwidth).
    Left of the ridge the program cannot saturate the MXU no matter how
    good the schedule — it is **bandwidth**-bound; right of it, compute-
    bound. Peaks default to the current device (nominal fallbacks keep
    classification meaningful on CPU)."""
    if peak_flops is None or hbm_bytes_per_sec is None:
        d_peak, d_bw = device_peaks()
        peak_flops = peak_flops if peak_flops is not None else d_peak
        hbm_bytes_per_sec = (hbm_bytes_per_sec
                            if hbm_bytes_per_sec is not None else d_bw)
    ai = flops / hbm_bytes if hbm_bytes > 0 else float("inf")
    ridge = peak_flops / hbm_bytes_per_sec if hbm_bytes_per_sec else 0.0
    bound = "bandwidth" if ai < ridge else "compute"
    # Best achievable time: max of the compute and the memory roofline.
    t_compute = flops / peak_flops if peak_flops else 0.0
    t_memory = (hbm_bytes / hbm_bytes_per_sec
                if hbm_bytes_per_sec else 0.0)
    return {"arithmetic_intensity": round(ai, 3),
            "ridge": round(ridge, 3),
            "bound": bound,
            "min_seconds": max(t_compute, t_memory)}


def device_peaks() -> Tuple[float, float]:
    """(peak FLOP/s, HBM bytes/s) across ALL local devices, with nominal
    per-chip fallbacks so roofline classification still works on CPU/
    unknown chips. Whole-process totals on purpose: cost_analysis FLOPs
    cover the whole (SPMD) module, and the trainer's wall-clock MFU
    normalizes by chip peak × device count (train/trainer.py) — analytic
    MFU must use the same convention or the cross-check can never agree
    on a multi-chip mesh. The ridge (peak ÷ bandwidth) is per-chip
    either way, since both totals scale by the device count."""
    import jax

    from runbooks_tpu.utils.hw import chip_hbm_bandwidth, chip_peak_flops

    devices = jax.devices()
    peak = chip_peak_flops(devices[0]) or NOMINAL_PEAK_FLOPS
    bw = chip_hbm_bandwidth(devices[0]) or NOMINAL_HBM_BPS
    return peak * len(devices), bw * len(devices)


def program_cost(component: str, name: str, shape_sig: str, fn,
                 *args, **kwargs) -> Optional[dict]:
    """Capture-and-record one program shape's roofline cost (idempotent
    per shape signature — re-warms skip the re-trace). Returns the cost
    dict (with classification folded in) or None."""
    if PROGRAMS.has_cost(component, name, shape_sig):
        return None
    cost = cost_analysis_of(fn, *args, **kwargs)
    if cost is None:
        return None
    cost.update(classify_roofline(cost["flops"], cost["hbm_bytes"]))
    PROGRAMS.record_cost(component, name, shape_sig, cost)
    return cost


# ---------------------------------------------------------------------------
# HBM / memory accounting
# ---------------------------------------------------------------------------

def device_memory_stats() -> List[dict]:
    """Per-device allocator stats. TPU/GPU backends report bytes in use /
    peak / limit; CPU's ``memory_stats()`` returns None — the entry then
    carries only identity, and callers fall back to the live-array census
    (the documented CPU degradation path)."""
    import jax

    out: List[dict] = []
    for d in jax.devices():
        entry: dict = {"device": str(getattr(d, "id", "?")),
                       "kind": getattr(d, "device_kind", ""),
                       "platform": getattr(d, "platform", "")}
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — not all backends implement it
            stats = None
        if stats:
            in_use = stats.get("bytes_in_use")
            limit = (stats.get("bytes_limit")
                     or stats.get("bytes_reservable_limit"))
            peak = stats.get("peak_bytes_in_use")
            if in_use is not None:
                entry["bytes_in_use"] = int(in_use)
            if peak is not None:
                entry["peak_bytes_in_use"] = int(peak)
            if limit:
                entry["bytes_limit"] = int(limit)
                if in_use is not None:
                    entry["headroom_bytes"] = int(limit) - int(in_use)
        out.append(entry)
    return out


def set_memory_gauges(registry: Optional[obs_metrics.Registry] = None
                      ) -> List[dict]:
    """Mirror device_memory_stats() into ``device_memory_*`` gauges
    (labeled per device) and return the entries. Devices without stats
    set nothing — an absent series IS the CPU-degradation signal."""
    reg = registry if registry is not None else obs_metrics.REGISTRY
    entries = device_memory_stats()
    for e in entries:
        if "bytes_in_use" not in e:
            continue
        labels = {"device": e["device"]}
        reg.set_gauge("device_memory_bytes_in_use", e["bytes_in_use"],
                      help_text="Allocator bytes currently in use "
                                "(memory_stats).", **labels)
        if "peak_bytes_in_use" in e:
            reg.set_gauge("device_memory_peak_bytes",
                          e["peak_bytes_in_use"],
                          help_text="Allocator high-water mark.", **labels)
        if "bytes_limit" in e:
            reg.set_gauge("device_memory_bytes_limit", e["bytes_limit"],
                          help_text="Allocator byte limit (HBM capacity "
                                    "share).", **labels)
            reg.set_gauge("device_memory_headroom_bytes",
                          e.get("headroom_bytes", 0),
                          help_text="bytes_limit - bytes_in_use.", **labels)
    return entries


def shard_local_nbytes(arr) -> int:
    """Per-device bytes one device holds of ``arr`` under its sharding.
    Pure metadata (``sharding.shard_shape`` — no device sync, no
    transfer): a [H, D] weight sharded 2-way over its head axis reports
    half its logical ``nbytes``; replicated and single-device arrays
    report the full amount. Falls back to logical bytes when the
    sharding doesn't expose shard shapes (committed host arrays etc.)."""
    try:
        shape = arr.sharding.shard_shape(tuple(arr.shape))
        out = int(getattr(arr.dtype, "itemsize", 1))
        for d in shape:
            out *= int(d)
        return out
    except Exception:  # noqa: BLE001 — metadata probe only
        return int(getattr(arr, "nbytes", 0))


def _tree_array_ids(tree: Any) -> set:
    """ids of the jax.Array leaves of an arbitrary pytree (QuantizedArray,
    KVCache etc. are registered pytrees, so tree.leaves walks them)."""
    import jax

    ids = set()
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array):
            ids.add(id(leaf))
    return ids


def live_array_census(groups: Optional[Dict[str, Any]] = None) -> dict:
    """Attribute every live jax.Array's bytes to caller-named groups.

    ``groups`` maps a name ("weights", "kv_cache", "optimizer", …) to a
    pytree whose leaves should be charged to it; anything live that
    belongs to no group lands in ``other``. Bytes are logical
    (``nbytes``); a group's number is exact, the categories + ``other``
    sum to ``total_bytes`` by construction. Deleted (donated-away)
    arrays are skipped — they hold no memory.

    ``by_category_per_device`` / ``total_per_device_bytes`` carry the
    same attribution in PER-DEVICE bytes (shard_local_nbytes): under a
    serving mesh a sharded weight or KV pool costs each chip only its
    shard, and per-chip HBM — not the logical total — is what fits or
    OOMs. On one device (or fully replicated) the two views agree."""
    import jax

    group_ids = {name: _tree_array_ids(tree)
                 for name, tree in (groups or {}).items()}
    by_group = {name: 0 for name in group_ids}
    by_group_local = {name: 0 for name in group_ids}
    by_group_counts = {name: 0 for name in group_ids}
    total = 0
    total_local = 0
    count = 0
    for arr in jax.live_arrays():
        try:
            if arr.is_deleted():
                continue
            nbytes = int(arr.nbytes)
        except Exception:  # noqa: BLE001 — racing a deletion
            continue
        local = shard_local_nbytes(arr)
        total += nbytes
        total_local += local
        count += 1
        aid = id(arr)
        for name, ids in group_ids.items():
            if aid in ids:
                by_group[name] += nbytes
                by_group_local[name] += local
                by_group_counts[name] += 1
                break
    categorized = sum(by_group.values())
    by_group["other"] = total - categorized
    by_group_local["other"] = total_local - sum(by_group_local.values())
    by_group_counts["other"] = count - sum(by_group_counts.values())
    return {"total_bytes": total, "arrays": count,
            "total_per_device_bytes": total_local,
            "by_category": by_group,
            "by_category_per_device": by_group_local,
            "array_counts": by_group_counts}


def memory_snapshot(groups: Optional[Dict[str, Any]] = None) -> dict:
    """One self-contained memory picture: device allocator stats + the
    live-array attribution census. This is what GET /debug/memory returns
    and what /debug/profile bundles beside the XLA trace."""
    return {"devices": device_memory_stats(),
            "live_arrays": live_array_census(groups)}
