"""On-demand TPU/XLA profiler capture (``jax.profiler`` trace).

Two triggers, both writing XProf/TensorBoard-loadable traces under
``{artifacts}/profiles/``:

- Serve API: ``POST /debug/profile?seconds=N`` captures N seconds of live
  traffic (serve/api.py wires it; returns the capture directory).
- Trainer: ``RBT_PROFILE_AT_STEP=n[:k]`` captures k steps (default 1)
  starting at step n — an env-only knob, so an operator can profile a
  misbehaving run by editing the Job env without touching the validated
  spec. (The spec-level ``profile_start``/``profile_stop`` window from the
  TrainJobConfig still works; this is the on-demand path.)

The net-new capability vs the reference, which has no profiling hooks at
all (SURVEY.md §5.1): answering "is this run input-bound or compute-bound"
from a trace instead of a debugger.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Tuple


class ProfilerBusy(RuntimeError):
    """A capture is already in flight (jax.profiler supports one trace at
    a time per process); serve/api.py maps this to HTTP 409."""


class Profiler:
    """Thread-safe single-capture guard over jax.profiler start/stop."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active_dir: Optional[str] = None  # guarded-by: _lock

    @property
    def active_dir(self) -> Optional[str]:
        with self._lock:
            return self._active_dir

    def start(self, log_dir: str) -> str:
        import jax

        with self._lock:
            if self._active_dir is not None:
                raise ProfilerBusy(
                    f"a profile capture is already writing to "
                    f"{self._active_dir}")
            os.makedirs(log_dir, exist_ok=True)
            jax.profiler.start_trace(log_dir)
            self._active_dir = log_dir
        from runbooks_tpu.obs import trace as obs_trace

        obs_trace.instant("profile.start", dir=log_dir)
        return log_dir

    def stop(self) -> Optional[str]:
        import jax

        with self._lock:
            if self._active_dir is None:
                return None
            try:
                jax.profiler.stop_trace()
            finally:
                log_dir, self._active_dir = self._active_dir, None
        from runbooks_tpu.obs import trace as obs_trace

        obs_trace.instant("profile.stop", dir=log_dir)
        # Self-contained bundle: snapshot the device memory state
        # (memory_stats() + live-array census) beside the XLA trace, so
        # "what was resident while this trace ran" travels with the
        # capture instead of needing a live process to ask.
        try:
            import json

            from runbooks_tpu.obs import device as obs_device

            with open(os.path.join(log_dir, "memory.json"), "w") as f:
                json.dump(obs_device.memory_snapshot(), f, indent=2)
        except Exception as exc:  # noqa: BLE001 — the trace still stands
            print(f"profile: memory snapshot failed: {exc!r}", flush=True)
        return log_dir

    def capture(self, log_dir: str, seconds: float) -> str:
        """Blocking timed capture: start, sleep, stop. Call off the event
        loop (the serve API runs it in an executor)."""
        self.start(log_dir)
        try:
            time.sleep(max(seconds, 0.0))
        finally:
            self.stop()
        return log_dir


PROFILER = Profiler()


def profiles_dir(artifacts: Optional[str] = None) -> str:
    from runbooks_tpu.utils import contract

    return os.path.join(artifacts or contract.artifacts_dir(), "profiles")


def capture_dir(artifacts: Optional[str] = None,
                tag: Optional[str] = None) -> str:
    """A fresh capture directory: profiles/<utc-stamp>[-tag]."""
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    name = f"{stamp}-{tag}" if tag else stamp
    return os.path.join(profiles_dir(artifacts), name)


def parse_profile_at_step(
        spec: Optional[str] = None) -> Optional[Tuple[int, int]]:
    """``RBT_PROFILE_AT_STEP=n[:k]`` -> (start_step, num_steps). k defaults
    to 1. Malformed values raise at parse time (before training state
    exists), like RBT_FAULT_INJECT."""
    if spec is None:
        spec = os.environ.get("RBT_PROFILE_AT_STEP", "")
    if not spec:
        return None
    step, _, count = spec.partition(":")
    try:
        n = int(step)
        k = int(count) if count else 1
    except ValueError:
        raise ValueError(
            f"RBT_PROFILE_AT_STEP={spec!r}: expected n or n:k "
            "(capture k steps starting at step n)") from None
    if n < 0 or k < 1:
        raise ValueError(
            f"RBT_PROFILE_AT_STEP={spec!r}: step must be >= 0, count >= 1")
    return n, k
