"""Fleet time-series history: bounded in-memory rings over scraped metrics.

The telemetry plane before this module was memoryless: the fleet scraper
(controller/fleet.py) kept only the LATEST sample per replica, so every
windowed question — "what did TTFT p99 do over the last 15 minutes",
"is the error budget burning fast or slow", "is queue-wait p90 high
*sustained* or just this instant" — either needed an external Prometheus
or got approximated with in-process sustain clocks that died with the
controller. This module is the missing memory:

- **Rings.** Every mirrored series (``serve_*``/``train_*``/``xla_*``/
  ``device_*``/``gateway_*``/``flight_*`` plus the scraper's own
  ``fleet_*`` gauges) gets a bounded ring of ``(t, value)`` points —
  histograms keep their full cumulative bucket snapshot per point, so
  windowed quantiles are EXACT bucket deltas (the PromQL
  ``histogram_quantile(rate(..._bucket[W]))`` equivalent), not decaying
  estimates. Appends are O(1) (``collections.deque``).
- **Two resolutions.** A raw ring at scrape cadence (default 10 s,
  15 min retention) answers the dev-loop questions; a rollup ring
  (default 60 s, 6 h retention) carries the slow burn-rate windows.
  The rollup point is the first raw sample at/after each 60 s grid
  boundary — exact for cumulative series (counters, histogram
  snapshots), a 1-in-N sample for gauges (docs/observability.md).
- **Staleness.** A replica that vanishes (scale-in, crash, node loss)
  has its series *marked stale*, not silently deleted: window queries
  exclude stale series (a dead pod's last distribution must not bias a
  cross-replica p90 mid-scale-in — the autoscaler bug class), and the
  retain pass prunes them once their newest point ages out of raw
  retention.
- **Snapshots.** ``save``/``load`` persist the rings as one JSON file
  (atomic tmp+rename) so burn-rate and sustain state survive controller
  restarts and leader failover; a corrupt/partial snapshot logs loudly
  and cold-starts — it can never crash the manager.

Consumers: the burn-rate SLO evaluator (controller/burnrate.py), the
autoscaler's windowed queue-wait p90 (controller/server.py), the
controller's ``GET /metrics/history`` endpoint (obs/metrics.py), and
``rbt dash`` (cli/main.py). docs/observability.md § "Fleet history".
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]
SeriesKey = Tuple[str, LabelKey]

# Scalar point: (t, value). Histogram point: (t, count, sum, cumulative)
# where `cumulative` are the finite-bound bucket counts exactly as the
# exposition carries them (bounds live on the series, not the point).

DEFAULT_RAW_STEP_S = 10.0
DEFAULT_RAW_RETENTION_S = 900.0
DEFAULT_ROLLUP_STEP_S = 60.0
DEFAULT_ROLLUP_RETENTION_S = 21600.0
DEFAULT_MAX_SERIES = 4096

# /metrics/history response bounds: points per series per response and
# series names per request — the endpoint must stay scrape-sized, never
# a bulk-export API.
MAX_QUERY_POINTS = 720
MAX_QUERY_SERIES = 16
MAX_INDEX_SERIES = 2000


def _labelkey(labels) -> LabelKey:
    if isinstance(labels, tuple):
        return labels
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def default_snapshot_path() -> str:
    """Where the controller persists the history between restarts and
    across leader failover: RBT_HISTORY_SNAPSHOT, or
    ``{artifacts}/fleet_history.json`` (the shared artifacts mount — the
    next leader reads the old leader's snapshot)."""
    explicit = os.environ.get("RBT_HISTORY_SNAPSHOT")
    if explicit:
        return explicit
    from runbooks_tpu.utils.contract import artifacts_dir

    return os.path.join(artifacts_dir(), "fleet_history.json")


def fraction_at_or_below(bounds: Sequence[float], deltas: Sequence[float],
                         count: float, threshold: float) -> float:
    """Estimated number of observations <= ``threshold`` in a windowed
    (delta) histogram, linear-interpolating inside the containing bucket
    like PromQL's histogram_quantile. Observations in +Inf (above the
    top finite bound) count as ABOVE any finite threshold."""
    acc = 0.0
    lo = 0.0
    for bound, c in zip(bounds, deltas):
        if threshold >= bound:
            acc += c
            lo = bound
            continue
        if bound > lo and threshold > lo:
            acc += c * (threshold - lo) / (bound - lo)
        break
    return min(acc, count)


class _WindowHist:
    """A merged windowed histogram delta: what happened inside [now-W, now]."""

    __slots__ = ("bounds", "deltas", "count", "sum", "span_s")

    def __init__(self, bounds, deltas, count, sum_, span_s):
        self.bounds = bounds
        self.deltas = deltas
        self.count = count
        self.sum = sum_
        self.span_s = span_s

    def quantile(self, q: float) -> float:
        from runbooks_tpu.obs.metrics import _Histogram

        hist = _Histogram(self.bounds)
        hist.counts = [max(0.0, d) for d in self.deltas]
        hist.count = self.count
        hist.sum = self.sum
        return hist.quantile(q)

    def fraction_above(self, threshold: float) -> float:
        """Fraction of the window's observations above ``threshold``
        (0.0 when the window saw no traffic)."""
        if self.count <= 0:
            return 0.0
        below = fraction_at_or_below(self.bounds, self.deltas, self.count,
                                     threshold)
        return max(0.0, (self.count - below) / self.count)


class _Series:
    """One (name, labels) ring pair. Not thread-safe on its own — every
    access goes through FleetHistory's lock."""

    __slots__ = ("name", "type", "labels", "bounds", "raw", "rollup",
                 "stale_since", "next_rollup_t")

    def __init__(self, name: str, type_: str, labels: LabelKey,
                 raw_maxlen: int, rollup_maxlen: int):
        self.name = name
        self.type = type_
        self.labels = labels
        self.bounds: Optional[Tuple[float, ...]] = None
        self.raw = deque(maxlen=raw_maxlen)
        self.rollup = deque(maxlen=rollup_maxlen)
        self.stale_since: Optional[float] = None
        self.next_rollup_t: Optional[float] = None


class FleetHistory:
    """Thread-safe store of bounded per-series time rings.

    Written by the fleet scraper on every scrape tick (and by the Server
    reconciler for the burn-rate line); read by the SLO/burn evaluator,
    the autoscaler, and the /metrics/history endpoint."""

    def __init__(self, raw_step_s: float = DEFAULT_RAW_STEP_S,
                 raw_retention_s: float = DEFAULT_RAW_RETENTION_S,
                 rollup_step_s: float = DEFAULT_ROLLUP_STEP_S,
                 rollup_retention_s: float = DEFAULT_ROLLUP_RETENTION_S,
                 max_series: int = DEFAULT_MAX_SERIES):
        self.raw_step_s = float(raw_step_s)
        self.raw_retention_s = float(raw_retention_s)
        self.rollup_step_s = float(rollup_step_s)
        self.rollup_retention_s = float(rollup_retention_s)
        self.max_series = int(max_series)
        self._raw_maxlen = max(2, int(raw_retention_s / max(raw_step_s,
                                                            1e-9)) + 3)
        self._rollup_maxlen = max(2, int(
            rollup_retention_s / max(rollup_step_s, 1e-9)) + 3)
        self._lock = threading.RLock()
        self._series: Dict[SeriesKey, _Series] = {}   # guarded-by: _lock
        self._dropped_series = 0                      # guarded-by: _lock
        self._warned_cap = False                      # guarded-by: _lock
        # Scrape-path memo: (name, parsed-labelkey, extra-labelkey) ->
        # merged LabelKey, so per-tick ingestion never re-sorts label
        # dicts that were sorted last tick (RBT_BENCH_HISTORY).
        self._lkey_cache: Dict[tuple, LabelKey] = {}  # guarded-by: _lock

    # -- write side ----------------------------------------------------

    def _series_for(self, name: str, labels: LabelKey,  # guarded-by: _lock
                    type_: str) -> Optional[_Series]:
        key = (name, labels)
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= self.max_series:
                self._dropped_series += 1
                if not self._warned_cap:
                    self._warned_cap = True
                    print(f"fleet-history: series cap ({self.max_series}) "
                          "reached; new series are dropped (raise "
                          "FleetHistory(max_series=) or reduce the fleet's "
                          "label cardinality)", flush=True)
                return None
            s = self._series[key] = _Series(name, type_, labels,
                                            self._raw_maxlen,
                                            self._rollup_maxlen)
        s.type = type_
        # A fresh point un-stales the series (a replica that came back).
        s.stale_since = None
        return s

    def _append(self, s: _Series, t: float, point: tuple) -> None:  # guarded-by: _lock
        s.raw.append(point)
        if s.next_rollup_t is None or t >= s.next_rollup_t:
            s.rollup.append(point)
            # Next rollup lands on the grid boundary after t, so uneven
            # scrape cadences still produce ~one rollup point per bucket.
            s.next_rollup_t = (math.floor(t / self.rollup_step_s) + 1) \
                * self.rollup_step_s

    def append_scalar(self, name: str, labels, t: float, value: float,
                      type_: str = "gauge") -> None:
        lkey = _labelkey(labels)
        with self._lock:
            s = self._series_for(name, lkey, type_)
            if s is not None:
                self._append(s, t, (t, float(value)))

    def _append_hist_locked(self, name, lkey, t,  # guarded-by: _lock
                            bounds, cumulative, count, sum_) -> None:
        s = self._series_for(name, lkey, "histogram")
        if s is None:
            return
        # No per-element float() pass: the scrape path appends one
        # snapshot per series per tick and the delta math is int/float
        # agnostic — conversions here were measurable in the
        # RBT_BENCH_HISTORY microbench.
        bounds = tuple(bounds)
        if s.bounds is not None and s.bounds != bounds:
            # Bucket layout changed (redeploy with different buckets):
            # old points can't delta against new ones.
            s.raw.clear()
            s.rollup.clear()
            s.next_rollup_t = None
        s.bounds = bounds
        self._append(s, t, (t, count, float(sum_), tuple(cumulative)))

    def append_histogram(self, name: str, labels, t: float,
                         bounds: Sequence[float],
                         cumulative: Sequence[float], count: float,
                         sum_: float) -> None:
        lkey = _labelkey(labels)
        with self._lock:
            self._append_hist_locked(name, lkey, t, bounds, cumulative,
                                     count, sum_)

    def ingest(self, families, extra: Dict[str, str], t: float,
               prefixes) -> None:
        """Bulk scrape-path ingestion: one replica's parsed exposition
        (obs/metrics.ParsedFamily dict) appended under a single lock
        acquisition, with merged label keys memoized across ticks —
        this is the whole per-tick history tax on the scraper
        (bounded < 1% of scrape wall by RBT_BENCH_HISTORY=1)."""
        extra_key = tuple(sorted(extra.items()))
        with self._lock:
            cache = self._lkey_cache
            if len(cache) > 4 * self.max_series:
                cache.clear()
            for fam in families.values():
                if not fam.name.startswith(prefixes):
                    continue
                if fam.type == "histogram":
                    for lkey, hist in fam.histograms.items():
                        ck = (fam.name, lkey, extra_key)
                        mk = cache.get(ck)
                        if mk is None:
                            mk = cache[ck] = tuple(sorted(
                                {**dict(lkey), **extra}.items()))
                        self._append_hist_locked(
                            fam.name, mk, t, hist.bounds,
                            hist.cumulative, hist.count, hist.sum)
                else:
                    for lkey, value in fam.samples.items():
                        ck = (fam.name, lkey, extra_key)
                        mk = cache.get(ck)
                        if mk is None:
                            mk = cache[ck] = tuple(sorted(
                                {**dict(lkey), **extra}.items()))
                        s = self._series_for(fam.name, mk, fam.type)
                        if s is not None:
                            self._append(s, t, (t, float(value)))

    def mark_stale(self, t: Optional[float] = None, **labels) -> int:
        """Mark every series whose labelset includes all given pairs as
        stale (e.g. ``mark_stale(replica=pod)`` when a replica vanishes).
        Stale series are excluded from window queries and pruned once
        their newest point ages out of raw retention. Returns the number
        of series marked."""
        t = time.time() if t is None else t
        match = {(k, str(v)) for k, v in labels.items()}
        n = 0
        with self._lock:
            for s in self._series.values():
                if s.stale_since is None and match <= set(s.labels):
                    s.stale_since = t
                    n += 1
        return n

    def prune(self, now: Optional[float] = None) -> int:
        """Drop stale series whose newest point is older than raw
        retention (the scraper's retain pass). Live series age out via
        their ring maxlen; only stale ones need explicit deletion."""
        now = time.time() if now is None else now
        doomed: List[SeriesKey] = []
        with self._lock:
            for key, s in self._series.items():
                if s.stale_since is None:
                    continue
                newest = s.raw[-1][0] if s.raw else (
                    s.rollup[-1][0] if s.rollup else None)
                if newest is None or now - newest > self.raw_retention_s:
                    doomed.append(key)
            for key in doomed:
                del self._series[key]
        return len(doomed)

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self._lkey_cache.clear()
            self._dropped_series = 0
            self._warned_cap = False

    def stats(self) -> Dict[str, int]:
        with self._lock:
            points = sum(len(s.raw) + len(s.rollup)
                         for s in self._series.values())
            stale = sum(1 for s in self._series.values()
                        if s.stale_since is not None)
            return {"series": len(self._series), "points": points,
                    "stale": stale, "dropped": self._dropped_series}

    # -- window queries (burn rates, autoscaler) -----------------------

    def _matching(self, name: str, sel: Dict[str, str],  # guarded-by: _lock
                  include_stale: bool = False) -> List[_Series]:
        match = {(k, str(v)) for k, v in sel.items()}
        return [s for (n, _), s in self._series.items()
                if n == name and match <= set(s.labels)
                and (include_stale or s.stale_since is None)]

    @staticmethod
    def _latest(s: _Series) -> Optional[tuple]:
        if s.raw:
            return s.raw[-1]
        if s.rollup:
            return s.rollup[-1]
        return None

    def _baseline(self, s: _Series, cut: float, window_s: float,
                  partial: bool) -> Optional[tuple]:
        """The newest point at or before ``cut`` — the raw ring first,
        then the rollup. A ring whose span *almost* reaches the cut (its
        oldest point within one step of it, capped at half the window so
        a sliver of history can never claim to answer a much longer
        window) yields its oldest point, so a window exactly as long as
        the retention is still computable. ``partial=True`` falls all
        the way back to the oldest point held (the budget accountant's
        'over available history' mode)."""
        for ring, step in ((s.raw, self.raw_step_s),
                           (s.rollup, self.rollup_step_s)):
            for point in reversed(ring):
                if point[0] <= cut:
                    return point
            if ring and ring[0][0] <= cut + min(step, window_s / 2.0):
                return ring[0]
        if partial:
            if s.rollup and (not s.raw or s.rollup[0][0] <= s.raw[0][0]):
                return s.rollup[0]
            if s.raw:
                return s.raw[0]
        return None

    def window_histogram(self, name: str, window_s: float,
                         now: Optional[float] = None,
                         partial: bool = False,
                         sel: Optional[Dict[str, str]] = None,
                         ) -> Optional[_WindowHist]:
        """The merged cross-replica histogram DELTA over the trailing
        window — what the fleet actually observed inside [now-W, now] —
        or None when no matching non-stale series can provide a baseline
        that old. A counter reset (replica restart) makes the latest
        snapshot the whole delta for that series."""
        now = time.time() if now is None else now
        cut = now - window_s
        sel = sel or {}
        merged_bounds = None
        deltas: List[float] = []
        count = 0.0
        sum_ = 0.0
        span = 0.0
        found = False
        with self._lock:
            for s in self._matching(name, sel):
                if s.type != "histogram" or s.bounds is None:
                    continue
                latest = self._latest(s)
                base = self._baseline(s, cut, window_s, partial)
                if latest is None or base is None:
                    continue
                if merged_bounds is None:
                    merged_bounds = s.bounds
                    deltas = [0.0] * len(s.bounds)
                elif s.bounds != merged_bounds:
                    continue  # mismatched layouts can't merge
                lt, lcount, lsum, lcum = latest
                bt, bcount, bsum, bcum = base
                if lcount < bcount:
                    # Counter reset (replica restart): the latest
                    # snapshot IS the observable delta.
                    bcount, bsum, bcum = 0.0, 0.0, (0.0,) * len(lcum)
                elif lt <= bt:
                    # One point, older than the cut (a silent replica):
                    # nothing new was observed inside the window.
                    bcount, bsum, bcum = lcount, lsum, lcum
                prev = 0.0
                bprev = 0.0
                for i in range(len(merged_bounds)):
                    dc = max(0.0, (lcum[i] - prev) - (bcum[i] - bprev))
                    deltas[i] += dc
                    prev, bprev = lcum[i], bcum[i]
                count += max(0.0, lcount - bcount)
                sum_ += max(0.0, lsum - bsum)
                span = max(span, lt - bt)
                found = True
        if not found:
            return None
        return _WindowHist(merged_bounds, deltas, count, sum_, span)

    def window_quantile(self, name: str, q: float, window_s: float,
                        now: Optional[float] = None,
                        sel: Optional[Dict[str, str]] = None,
                        ) -> Optional[float]:
        """Cross-replica q-quantile of observations inside the trailing
        window (None when the window isn't computable or saw nothing)."""
        wh = self.window_histogram(name, window_s, now=now, sel=sel)
        if wh is None or wh.count <= 0:
            return None
        return wh.quantile(q)

    def window_increase(self, name: str, window_s: float,
                        now: Optional[float] = None,
                        partial: bool = False,
                        sel: Optional[Dict[str, str]] = None,
                        ) -> Optional[float]:
        """Summed counter increase over the trailing window across
        matching non-stale series (PromQL ``increase()``), reset-aware.
        None when no series can provide a baseline."""
        now = time.time() if now is None else now
        cut = now - window_s
        sel = sel or {}
        total = None
        with self._lock:
            for s in self._matching(name, sel):
                if s.type == "histogram":
                    continue
                latest = self._latest(s)
                base = self._baseline(s, cut, window_s, partial)
                if latest is None or base is None:
                    continue
                lv = latest[1]
                bv = base[1] if latest[0] > base[0] else lv
                inc = lv if lv < bv else lv - bv   # reset -> whole value
                total = inc if total is None else total + inc
        return total

    # -- grid queries (the /metrics/history + rbt dash read path) ------

    def _grid_series(self, s: _Series, step: float, n: int, now: float,
                     q: float) -> Tuple[List[Optional[tuple]],
                                        Optional[tuple]]:
        """One series resampled onto the right-aligned grid of ``n``
        cells ending at ``now``: cell i covers
        (now-(n-i)*step, now-(n-1-i)*step]. Value per cell is the last
        point that landed in it (None for empty cells). Also returns the
        newest point BEFORE the grid, so the first populated cell's
        delta (histograms, counter rates) baselines against real
        history instead of rendering the cumulative-since-start value."""
        ring = s.rollup if step >= self.rollup_step_s else s.raw
        cells: List[Optional[tuple]] = [None] * n
        start = now - n * step
        pre: Optional[tuple] = None
        for point in ring:
            idx = int((point[0] - start) / step) if step > 0 else -1
            if 0 <= idx < n:
                cells[idx] = point
            elif idx < 0:
                pre = point  # ring is time-ordered: keeps the newest
        return cells, pre

    def query(self, name: str, since_s: float, step_s: float,
              now: Optional[float] = None, q: float = 0.5,
              agg: str = "sum", sel: Optional[Dict[str, str]] = None,
              max_points: int = MAX_QUERY_POINTS) -> dict:
        """One merged series resampled onto a fixed grid, JSON-shaped:

        ``{"name", "type", "step", "points": [[t, v|null], ...],
           "series": <labelsets merged>, "stale_excluded": k}``

        Values per grid cell: gauges aggregate across series (``agg`` =
        sum|avg|max), counters become per-second rates (reset-clamped),
        histograms become the q-quantile of the cell-over-cell bucket
        delta. ``null`` marks cells with no data (staleness gaps render
        as gaps, not zeros). A ``since``/``step`` pair asking for more
        than ``max_points`` cells WIDENS the step to cover the full
        window (the caller reads the effective step back from the
        response) — never a silent truncation of the window."""
        now = time.time() if now is None else now
        step = max(float(step_s), 1e-3, float(since_s) / int(max_points))
        n = max(1, int(float(since_s) / step))
        sel = sel or {}
        with self._lock:
            series = self._matching(name, sel)
            stale_excluded = len(self._matching(name, sel,
                                                include_stale=True)) \
                - len(series)
            type_ = series[0].type if series else "untyped"
            grids = [(s,) + self._grid_series(s, step, n, now, q)
                     for s in series]
            points: List[list] = []
            for i in range(n):
                t_cell = now - (n - 1 - i) * step
                vals: List[float] = []
                for s, cells, pre in grids:
                    point = cells[i]
                    if point is None:
                        continue
                    if s.type == "histogram":
                        prev = next((cells[j] for j in range(i - 1, -1, -1)
                                     if cells[j] is not None), pre)
                        v = self._hist_cell_value(s, point, prev, q)
                    elif s.type == "counter":
                        prev = next((cells[j] for j in range(i - 1, -1, -1)
                                     if cells[j] is not None), pre)
                        v = self._rate_cell_value(point, prev)
                    else:
                        v = point[1]
                    if v is not None:
                        vals.append(v)
                if not vals:
                    points.append([round(t_cell, 3), None])
                elif agg == "avg":
                    points.append([round(t_cell, 3),
                                   sum(vals) / len(vals)])
                elif agg == "max":
                    points.append([round(t_cell, 3), max(vals)])
                else:
                    points.append([round(t_cell, 3), sum(vals)])
        return {"name": name, "type": type_, "step": step,
                "points": points, "series": len(grids),
                "stale_excluded": stale_excluded}

    @staticmethod
    def _hist_cell_value(s: _Series, point: tuple, prev: Optional[tuple],
                         q: float) -> Optional[float]:
        t, count, sum_, cum = point
        if prev is not None and prev[1] <= count:
            bcount, bcum = prev[1], prev[3]
        else:
            bcount, bcum = 0.0, (0.0,) * len(cum)
        dcount = count - bcount
        if dcount <= 0 or s.bounds is None:
            return None
        deltas = []
        p = bp = 0.0
        for i in range(len(s.bounds)):
            deltas.append(max(0.0, (cum[i] - p) - (bcum[i] - bp)))
            p, bp = cum[i], bcum[i]
        return _WindowHist(s.bounds, deltas, dcount, 0.0, 0.0).quantile(q)

    @staticmethod
    def _rate_cell_value(point: tuple, prev: Optional[tuple],
                         ) -> Optional[float]:
        if prev is None:
            return None
        t, v = point
        pt, pv = prev[0], prev[1]
        if t <= pt:
            return None
        return max(0.0, v - (pv if v >= pv else 0.0)) / (t - pt)

    def index(self) -> dict:
        """Bounded series listing + ring config (the no-params
        /metrics/history response; `rbt dash` reads the config to pick
        its default step/window)."""
        with self._lock:
            entries = []
            for (name, _), s in sorted(self._series.items())[
                    :MAX_INDEX_SERIES]:
                newest = self._latest(s)
                entries.append({
                    "name": name, "type": s.type,
                    "labels": dict(s.labels),
                    "stale": s.stale_since is not None,
                    "points": len(s.raw) + len(s.rollup),
                    "newest": round(newest[0], 3) if newest else None,
                })
            stats = {"series": len(self._series),
                     "dropped": self._dropped_series}
        return {
            "config": {"raw_step_s": self.raw_step_s,
                       "raw_retention_s": self.raw_retention_s,
                       "rollup_step_s": self.rollup_step_s,
                       "rollup_retention_s": self.rollup_retention_s,
                       "max_series": self.max_series},
            "stats": stats,
            "series": entries,
        }

    _QUERY_PARAMS = ("series", "since", "step", "q", "agg")

    def http_query(self, params: Dict[str, List[str]],
                   now: Optional[float] = None) -> dict:
        """The GET /metrics/history contract: ``params`` is a parsed
        query string (parse_qs shape). Without ``series`` returns the
        bounded index; with it, merged grid series per requested name.
        Unknown params are label selectors (``name=srv&namespace=default``).
        Raises ValueError on malformed numbers (the handler's 400)."""

        def first(key, default=None):
            vals = params.get(key)
            return vals[0] if vals else default

        names = [n for n in (first("series") or "").split(",") if n]
        if not names:
            return self.index()
        if len(names) > MAX_QUERY_SERIES:
            raise ValueError(
                f"series: at most {MAX_QUERY_SERIES} names per request")
        since = float(first("since", self.raw_retention_s))
        since = min(max(since, 0.0), self.rollup_retention_s)
        step = float(first("step", self.raw_step_s))
        q = float(first("q", 0.5))
        if not 0.0 < q < 1.0:
            raise ValueError("q: must be in (0, 1)")
        agg = first("agg", "sum")
        if agg not in ("sum", "avg", "max"):
            raise ValueError("agg: expected sum|avg|max")
        sel = {k: v[0] for k, v in params.items()
               if k not in self._QUERY_PARAMS and v}
        now = time.time() if now is None else now
        return {
            "now": round(now, 3), "since": since, "step": step,
            "series": [self.query(name, since, step, now=now, q=q,
                                  agg=agg, sel=sel) for name in names],
        }

    # -- snapshot persistence ------------------------------------------

    def to_snapshot(self) -> dict:
        with self._lock:
            series = []
            for (name, _), s in self._series.items():
                series.append({
                    "name": name, "type": s.type,
                    "labels": list(s.labels),
                    "bounds": list(s.bounds) if s.bounds else None,
                    "stale_since": s.stale_since,
                    "next_rollup_t": s.next_rollup_t,
                    "raw": [list(p) for p in s.raw],
                    "rollup": [list(p) for p in s.rollup],
                })
        return {"version": 1, "saved_at": time.time(),
                "config": {"raw_step_s": self.raw_step_s,
                           "rollup_step_s": self.rollup_step_s},
                "series": series}

    def load_snapshot(self, snap: dict) -> int:
        """Restore rings from a snapshot dict. Raises on malformed input
        (callers treat any exception as 'corrupt'); returns the number
        of series restored. Points older than the rollup retention are
        dropped; everything else survives verbatim, stale markers
        included."""
        if snap.get("version") != 1:
            raise ValueError(f"unknown snapshot version "
                             f"{snap.get('version')!r}")
        cutoff = time.time() - self.rollup_retention_s
        restored = 0
        with self._lock:
            self._series.clear()
            for entry in snap["series"]:
                name = entry["name"]
                lkey = tuple((str(k), str(v)) for k, v in entry["labels"])
                s = _Series(name, entry["type"], lkey, self._raw_maxlen,
                            self._rollup_maxlen)
                if entry.get("bounds"):
                    s.bounds = tuple(float(b) for b in entry["bounds"])
                s.stale_since = entry.get("stale_since")
                s.next_rollup_t = entry.get("next_rollup_t")
                for ring_name in ("raw", "rollup"):
                    ring = getattr(s, ring_name)
                    for p in entry[ring_name]:
                        t = float(p[0])
                        if t < cutoff:
                            continue
                        if len(p) == 2:
                            ring.append((t, float(p[1])))
                        else:
                            ring.append((t, float(p[1]), float(p[2]),
                                         tuple(float(c) for c in p[3])))
                self._series[(name, lkey)] = s
                restored += 1
        return restored

    def save(self, path: str) -> bool:
        """Atomic snapshot write (tmp + os.replace): a crash mid-write
        leaves the previous snapshot intact, never a truncated JSON the
        next start would choke on. Never raises — persistence is a
        nicety; the scrape loop must outlive a full disk."""
        tmp = f"{path}.tmp"
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(self.to_snapshot(), f)
            os.replace(tmp, path)
            return True
        except OSError as e:
            print(f"fleet-history: snapshot save to {path} failed "
                  f"(continuing without persistence): {e}", flush=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False

    def load(self, path: str) -> str:
        """Restore from ``path``. Returns "restored", "cold" (no file),
        or "corrupt" (unreadable/partial — logged LOUDLY, rings reset,
        never raises: a bad snapshot must not crash the manager)."""
        if not os.path.exists(path):
            return "cold"
        try:
            with open(path) as f:
                snap = json.load(f)
            n = self.load_snapshot(snap)
        except Exception as e:  # noqa: BLE001 — any corruption -> cold start
            self.reset()
            print(f"fleet-history: SNAPSHOT CORRUPT at {path} ({e!r}); "
                  "cold-starting with empty history — burn-rate windows "
                  "re-warm from live scrapes", flush=True)
            return "corrupt"
        print(f"fleet-history: restored {n} series from {path}",
              flush=True)
        return "restored"


# The process-wide history: the manager's scraper writes, the Server
# reconciler's burn-rate/autoscale evaluation and the /metrics/history
# endpoint read (same pattern as the shared FLEET state and REGISTRY).
HISTORY = FleetHistory()
