"""Process-wide Prometheus-format metrics registry.

Promoted from the controller's private minimal registry
(controller/metrics.py, which now re-exports this module) into the one
registry every layer shares: counters, gauges, and fixed-bucket histograms
with correct text-format exposition (``# HELP``/``# TYPE`` lines, spec
label escaping, ``_bucket``/``_sum``/``_count`` series with cumulative
``le`` buckets). No third-party deps — the exposition format is stable and
small, and the serving path must not grow a client-library import.

Conventions (enforced by tests/test_obs.py's exposition lint):
- counters end in ``_total``; gauges and histograms do not
- histogram families expose ``<name>_bucket{le=...}``, ``<name>_sum``,
  ``<name>_count``; the ``+Inf`` bucket equals ``_count``
- every ``# TYPE`` precedes its family's samples
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

# The Prometheus text exposition content type. Bare "text/plain" makes some
# scrapers fall back to heuristic parsing; version + charset is what the
# official client libraries send.
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Default histogram buckets: latency-shaped (seconds), spanning sub-ms
# engine dispatches to multi-second cold compiles. 14 buckets keeps each
# labelset's exposition small; per-metric overrides via observe(buckets=).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0)

LabelKey = Tuple[Tuple[str, str], ...]
MetricKey = Tuple[str, LabelKey]

# The canonical metric catalog: every family this codebase registers at
# runtime, by name -> type. tests/test_fleet.py enforces BOTH directions
# against the docs/observability.md table (a metric added here without a
# doc row fails, and a doc row for a metric that no longer exists fails),
# so the catalog cannot silently rot as metrics are added.
CATALOG: Dict[str, str] = {
    # controller
    "controller_reconcile_total": "counter",
    "controller_reconcile_errors_total": "counter",
    "controller_reconcile_seconds": "histogram",
    "controller_apiserver_errors_total": "counter",
    "controller_slice_restarts_total": "counter",
    "controller_slo_violations_total": "counter",
    "controller_autoscale_actions_total": "counter",
    "controller_fleet_scrape_seconds": "histogram",
    # burn-rate SLO layer (controller/burnrate.py, obs/history.py)
    "controller_slo_burn_rate": "gauge",
    "controller_slo_error_budget_remaining_pct": "gauge",
    # fleet scraper (per-replica labels {kind, name, replica}; the serve_*
    # and train_* families below also appear with these labels on the
    # controller's exposition, mirrored at scrape time)
    "fleet_scrape_up": "gauge",
    "fleet_scrape_age_seconds": "gauge",
    "fleet_tokens_per_sec": "gauge",
    "fleet_slo_violated": "gauge",
    # telemetry-plane self-observability + history rings
    "fleet_scrape_errors_total": "counter",
    "fleet_scrape_duration_seconds": "histogram",
    "fleet_history_series": "gauge",
    "fleet_history_points": "gauge",
    # serve
    "serve_requests_total": "counter",
    "serve_requests_failed_total": "counter",
    "serve_requests_rejected_total": "counter",
    "serve_tokens_generated_total": "counter",
    "serve_decode_steps_total": "counter",
    "serve_deadline_expired_total": "counter",
    "serve_prefix_tokens_reused_total": "counter",
    "serve_active_slots": "gauge",
    "serve_queue_depth": "gauge",
    "serve_queue_limit": "gauge",
    "serve_draining": "gauge",
    "serve_queue_wait_seconds": "histogram",
    "serve_ttft_seconds": "histogram",
    "serve_inter_token_seconds": "histogram",
    "serve_request_duration_seconds": "histogram",
    "serve_prefill_dispatch_seconds": "histogram",
    "serve_decode_dispatch_seconds": "histogram",
    # Speculative decoding (serve/engine.py verify path,
    # docs/speculative-decoding.md): exported only when speculative is
    # on ("off" engines register none of these)
    "serve_spec_drafted_total": "counter",
    "serve_spec_accepted_total": "counter",
    "serve_spec_accept_len": "histogram",
    "serve_verify_dispatch_seconds": "histogram",
    # trainer
    "train_step_seconds": "histogram",
    "train_data_wait_seconds": "histogram",
    "train_checkpoint_seconds": "histogram",
    "train_goodput_ratio": "gauge",
    "train_step": "gauge",
    "train_loss": "gauge",
    "train_analytic_mfu": "gauge",
    # device-level (obs/device.py): compile sentinel, program census,
    # roofline attribution, HBM accounting
    "xla_compilations_total": "counter",
    "xla_unexpected_compiles_total": "counter",
    "xla_compile_seconds": "histogram",
    "xla_programs": "gauge",
    "xla_program_flops": "gauge",
    "xla_program_hbm_bytes": "gauge",
    "xla_program_arithmetic_intensity": "gauge",
    "xla_program_bandwidth_bound": "gauge",
    "device_memory_bytes_in_use": "gauge",
    "device_memory_peak_bytes": "gauge",
    "device_memory_bytes_limit": "gauge",
    "device_memory_headroom_bytes": "gauge",
    # KV-cache occupancy + prefix reuse (paged-KV design baseline)
    "serve_slots_total": "gauge",
    "serve_kv_cache_tokens": "gauge",
    "serve_kv_cache_capacity_tokens": "gauge",
    "serve_kv_occupancy_ratio": "gauge",
    # KV pool HBM bytes: aggregate (logical) and per-device (the shard
    # each chip holds under a serving mesh; equal unsharded)
    "serve_kv_pool_bytes": "gauge",
    "serve_kv_pool_bytes_per_device": "gauge",
    "serve_prefix_lookups_total": "counter",
    "serve_prefix_hits_total": "counter",
    # Paged KV pool (serve/paging.py, docs/paged-kv.md): exported only
    # when the engine runs paged
    "serve_kv_pages_free": "gauge",
    "serve_kv_pages_used": "gauge",
    "serve_kv_pages_shared": "gauge",
    "serve_prefix_pages_reused_total": "counter",
    # Host-RAM KV swap tier + QoS preemption (serve/paging.py,
    # docs/paged-kv.md "Host tier and preemption"): swap families are
    # exported only when kv_host_pages > 0; the preemption counters are
    # unconditional (0 on engines without preemption)
    "serve_kv_host_pages_used": "gauge",
    "serve_kv_host_pages_free": "gauge",
    "serve_kv_swap_out_pages_total": "counter",
    "serve_kv_swap_in_pages_total": "counter",
    "serve_kv_swap_dropped_pages_total": "counter",
    "serve_kv_swap_seconds": "histogram",
    "serve_preemptions_total": "counter",
    "serve_preempted_resumed_total": "counter",
    # Multi-tenant LoRA adapter pool (serve/lora_pool.py,
    # docs/multi-tenant-lora.md): exported only by pooled engines
    "serve_adapter_loads_total": "counter",
    "serve_adapter_evictions_total": "counter",
    "serve_adapter_hits_total": "counter",
    "serve_adapter_requests_total": "counter",
    "serve_adapters_resident": "gauge",
    # Grammar-constrained structured output (serve/grammar.py,
    # docs/structured-output.md): exported only when grammar is on
    "serve_grammar_requests_total": "counter",
    "serve_grammar_cache_hits_total": "counter",
    "serve_grammar_cache_misses_total": "counter",
    "serve_grammar_draft_truncations_total": "counter",
    "serve_grammar_mask_build_seconds": "histogram",
    # Serving gateway (serve/gateway.py, docs/serving-dataplane.md):
    # the multi-replica routing data plane
    "gateway_requests_total": "counter",
    "gateway_route_decisions_total": "counter",
    "gateway_retries_total": "counter",
    "gateway_affinity_requests_total": "counter",
    "gateway_affinity_hits_total": "counter",
    "gateway_shed_passthrough_total": "counter",
    "gateway_proxy_latency_seconds": "histogram",
    "gateway_replicas_healthy": "gauge",
    "gateway_shadow_blocks": "gauge",
    # Flight recorder + distributed tracing + incident snapshots
    # (obs/flight.py, obs/incident.py, docs/observability.md)
    "flight_ring_events": "gauge",
    "serve_tail_samples_total": "counter",
    "serve_incidents_total": "counter",
    "serve_incident_age_seconds": "gauge",
    "gateway_trace_spans_total": "counter",
    # process
    "process_uptime_seconds": "gauge",
}


def escape_label_value(value: str) -> str:
    """Escape a label value per the Prometheus text format: backslash,
    double-quote, and line-feed must be escaped or the line is unparseable
    (and a hostile value could inject fake samples)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help(text: str) -> str:
    """# HELP lines escape backslash and line-feed only (spec)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(name: str, labels: LabelKey, value) -> str:
    if labels:
        inner = ",".join(f'{k}="{escape_label_value(v)}"'
                         for k, v in labels)
        return f"{name}{{{inner}}} {value}"
    return f"{name} {value}"


def _key(name: str, labels: Dict[str, str]) -> MetricKey:
    return (name, tuple(sorted((k, str(v)) for k, v in labels.items())))


class _Histogram:
    """One histogram labelset: cumulative bucket counts + sum + count."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Sequence[float]):
        self.bounds = tuple(bounds)
        self.counts = [0] * len(self.bounds)   # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.bounds, value)
        if i < len(self.counts):
            self.counts[i] += 1
        # values above the top bound land only in +Inf (== count)
        self.sum += value
        self.count += 1

    def cumulative(self) -> List[int]:
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile from the buckets (linear interpolation
        inside the containing bucket, like PromQL's histogram_quantile).
        Returns the top finite bound when the quantile lands in +Inf."""
        if self.count == 0:
            return float("nan")
        rank = q * self.count
        acc = 0
        lo = 0.0
        for bound, c in zip(self.bounds, self.counts):
            if acc + c >= rank and c > 0:
                frac = (rank - acc) / c
                return lo + (bound - lo) * min(max(frac, 0.0), 1.0)
            acc += c
            lo = bound
        return self.bounds[-1] if self.bounds else float("nan")


class Registry:
    """Thread-safe metrics registry rendering Prometheus text format.

    ``inc`` accumulates counters; ``set_counter`` mirrors an externally
    maintained monotonic count (e.g. the serve engine's own totals) as an
    absolute value at scrape time; ``set_gauge`` sets gauges; ``observe``
    records into a fixed-bucket histogram. ``help_text`` registered on
    first use (or via ``describe``) renders as ``# HELP``.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[MetricKey, float] = defaultdict(float)  # guarded-by: _lock
        self._gauges: Dict[MetricKey, object] = {}                   # guarded-by: _lock
        self._hists: Dict[MetricKey, _Histogram] = {}                # guarded-by: _lock
        self._help: Dict[str, str] = {}                              # guarded-by: _lock
        self.started = time.time()

    # -- write side ----------------------------------------------------

    def describe(self, name: str, help_text: str) -> None:
        with self._lock:
            self._help[name] = help_text

    def inc(self, name: str, value: float = 1.0, /, *,
            help_text: Optional[str] = None, **labels: str) -> None:
        key = _key(name, labels)
        with self._lock:
            self._counters[key] += value
            if help_text:
                self._help.setdefault(name, help_text)

    def set_counter(self, name: str, value: float, /, *,
                    help_text: Optional[str] = None, **labels: str) -> None:
        """Absolute-value counter (for mirroring a count the source object
        maintains itself — e.g. engine.steps — at scrape time)."""
        key = _key(name, labels)
        with self._lock:
            self._counters[key] = float(value)
            if help_text:
                self._help.setdefault(name, help_text)

    def set_gauge(self, name: str, value, /, *,
                  help_text: Optional[str] = None, **labels: str) -> None:
        key = _key(name, labels)
        with self._lock:
            self._gauges[key] = value
            if help_text:
                self._help.setdefault(name, help_text)

    def observe(self, name: str, value: float, /, *,
                buckets: Optional[Sequence[float]] = None,
                help_text: Optional[str] = None, **labels: str) -> None:
        key = _key(name, labels)
        with self._lock:
            hist = self._hists.get(key)
            if hist is None:
                hist = self._hists[key] = _Histogram(
                    buckets if buckets is not None else DEFAULT_BUCKETS)
            hist.observe(float(value))
            if help_text:
                self._help.setdefault(name, help_text)

    def set_histogram(self, name: str, bounds: Sequence[float],
                      cumulative: Sequence[int], count: int, sum_: float,
                      /, *, help_text: Optional[str] = None,
                      **labels: str) -> None:
        """Mirror an externally scraped histogram labelset as absolute
        state (the fleet scraper re-exposing a replica's distribution).
        `cumulative` are the finite-bound bucket counts exactly as the
        exposition carries them; `count` is the +Inf/_count value."""
        hist = _Histogram(bounds)
        acc = 0
        for i, c in enumerate(cumulative):
            hist.counts[i] = int(c) - acc
            acc = int(c)
        hist.sum = float(sum_)
        hist.count = int(count)
        with self._lock:
            self._hists[_key(name, labels)] = hist
            if help_text:
                self._help.setdefault(name, help_text)

    def drop_series(self, **labels: str) -> int:
        """Remove every series whose labelset includes ALL the given
        label pairs (e.g. ``drop_series(replica=pod)`` when a scraped
        replica disappears — its mirrored absolute values would otherwise
        read as live forever). Returns the number of series dropped."""
        match = {(k, str(v)) for k, v in labels.items()}
        dropped = 0
        with self._lock:
            for store in (self._counters, self._gauges, self._hists):
                doomed = [k for k in store if match <= set(k[1])]
                for k in doomed:
                    del store[k]
                dropped += len(doomed)
        return dropped

    # -- read side -----------------------------------------------------

    def quantile(self, name: str, q: float, /, **labels: str) -> float:
        with self._lock:
            hist = self._hists.get(_key(name, labels))
            return hist.quantile(q) if hist is not None else float("nan")

    def counter_value(self, name: str, /, **labels: str) -> float:
        with self._lock:
            return self._counters.get(_key(name, labels), 0.0)

    def histogram_stats(self, name: str, /,
                        **labels: str) -> Optional[Tuple[int, float]]:
        """(count, sum) of one histogram labelset, or None — the mean
        dispatch time a roofline's analytic MFU divides by."""
        with self._lock:
            hist = self._hists.get(_key(name, labels))
            return (hist.count, hist.sum) if hist is not None else None

    def render(self) -> str:
        """Prometheus text format, grouped per family: ``# HELP`` and
        ``# TYPE`` precede every family's samples (required by the spec —
        fixing the old renderer, whose interleaved sorted dump had no type
        lines at all)."""
        lines: List[str] = []
        with self._lock:
            families: Dict[str, List[Tuple[str, LabelKey, object]]] = {}
            types: Dict[str, str] = {}
            for (name, labels), value in sorted(self._counters.items()):
                families.setdefault(name, []).append((name, labels, value))
                types[name] = "counter"
            for (name, labels), value in sorted(self._gauges.items()):
                families.setdefault(name, []).append((name, labels, value))
                types[name] = "gauge"
            uptime = time.time() - self.started
            families.setdefault("process_uptime_seconds", []).append(
                ("process_uptime_seconds", (), uptime))
            types["process_uptime_seconds"] = "gauge"
            self._help.setdefault("process_uptime_seconds",
                                  "Seconds since this registry was created.")
            for name in sorted(families):
                if name in self._help:
                    lines.append(
                        f"# HELP {name} {escape_help(self._help[name])}")
                lines.append(f"# TYPE {name} {types[name]}")
                for sample_name, labels, value in families[name]:
                    lines.append(_fmt(sample_name, labels, value))
            hist_names = sorted({name for name, _ in self._hists})
            for name in hist_names:
                if name in self._help:
                    lines.append(
                        f"# HELP {name} {escape_help(self._help[name])}")
                lines.append(f"# TYPE {name} histogram")
                for (hname, labels), hist in sorted(self._hists.items()):
                    if hname != name:
                        continue
                    cum = hist.cumulative()
                    for bound, c in zip(hist.bounds, cum):
                        bl = labels + (("le", f"{bound:g}"),)
                        lines.append(_fmt(f"{name}_bucket", bl, c))
                    lines.append(_fmt(f"{name}_bucket",
                                      labels + (("le", "+Inf"),),
                                      hist.count))
                    lines.append(_fmt(f"{name}_sum", labels,
                                      round(hist.sum, 9)))
                    lines.append(_fmt(f"{name}_count", labels, hist.count))
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop all series (tests; a process never needs this)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


# The process-wide registry: controller, serve API, trainer, and benches all
# record here, so one /metrics scrape sees every layer living in the process.
REGISTRY = Registry()


def serve_metrics(port: int, registry: Optional[Registry] = None,
                  history=None) -> HTTPServer:
    """Serve GET /metrics on a background thread (controller-manager's
    metrics endpoint; reference: controller-runtime --metrics-bind-address).
    port=0 binds an ephemeral port (tests); read it back from
    ``httpd.server_address``.

    With ``history`` (an obs/history.py FleetHistory — the controller
    passes the process-wide HISTORY) the endpoint also answers
    ``GET /metrics/history[?series=&since=&step=&q=&agg=&<label>=...]``:
    bounded JSON time series resampled from the fleet rings — the data
    plane behind ``rbt dash`` (docs/observability.md "Fleet history")."""
    import json as _json
    from urllib.parse import parse_qs, urlparse

    reg = registry if registry is not None else REGISTRY

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, body: bytes, content_type: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            parsed = urlparse(self.path)
            if parsed.path == "/metrics":
                self._send(200, reg.render().encode("utf-8"), CONTENT_TYPE)
            elif parsed.path == "/metrics/history" and history is not None:
                try:
                    payload = history.http_query(parse_qs(parsed.query))
                except ValueError as e:
                    self._send(400, _json.dumps(
                        {"error": str(e)}).encode("utf-8"),
                        "application/json")
                    return
                self._send(200, _json.dumps(payload).encode("utf-8"),
                           "application/json")
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *args):
            return

    httpd = HTTPServer(("0.0.0.0", port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


# ---------------------------------------------------------------------------
# Exposition parsing (the scrape side of the text format this module
# renders). The fleet scraper uses it to re-expose each replica's series
# from the controller; `rbt top` uses it to turn any /metrics body into a
# table. Stdlib-only for the same reason the renderer is.
# ---------------------------------------------------------------------------

import re as _re

_SAMPLE_RE = _re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_RE = _re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(value: str) -> str:
    return (value.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


class ParsedHistogram:
    """One histogram labelset as scraped: finite-bound cumulative counts
    + count (+Inf) + sum, with the same quantile estimate the live
    _Histogram computes."""

    __slots__ = ("bounds", "cumulative", "count", "sum")

    def __init__(self):
        self.bounds: List[float] = []
        self.cumulative: List[int] = []
        self.count = 0
        self.sum = 0.0

    def quantile(self, q: float) -> float:
        hist = _Histogram(self.bounds)
        acc = 0
        for i, c in enumerate(self.cumulative):
            hist.counts[i] = int(c) - acc
            acc = int(c)
        hist.sum = self.sum
        hist.count = self.count
        return hist.quantile(q)

    def merged(self, other: "ParsedHistogram") -> "ParsedHistogram":
        """Sum with another labelset over the SAME bounds (cross-replica
        aggregation); mismatched bounds keep self (can't merge buckets)."""
        if other.bounds != self.bounds:
            return self
        out = ParsedHistogram()
        out.bounds = list(self.bounds)
        out.cumulative = [a + b for a, b in
                          zip(self.cumulative, other.cumulative)]
        out.count = self.count + other.count
        out.sum = self.sum + other.sum
        return out


class ParsedFamily:
    """One metric family from a scraped exposition."""

    __slots__ = ("name", "type", "samples", "histograms")

    def __init__(self, name: str, type_: str = "untyped"):
        self.name = name
        self.type = type_
        # counter/gauge: labelset -> value
        self.samples: Dict[LabelKey, float] = {}
        # histogram: labelset (without `le`) -> ParsedHistogram
        self.histograms: Dict[LabelKey, ParsedHistogram] = {}

    def value(self, default: float = 0.0, **labels: str) -> float:
        return self.samples.get(
            tuple(sorted((k, str(v)) for k, v in labels.items())), default)

    def total(self) -> float:
        """Sum across labelsets (cross-replica aggregation of a counter
        or additive gauge)."""
        return sum(self.samples.values())

    def merged_histogram(self) -> Optional[ParsedHistogram]:
        """All labelsets merged into one distribution (same-bounds only)."""
        out = None
        for hist in self.histograms.values():
            out = hist if out is None else out.merged(hist)
        return out


def parse_exposition(text: str) -> Dict[str, ParsedFamily]:
    """Parse a Prometheus text exposition (the format ``render`` emits,
    including histograms) into families. Unknown/malformed lines are
    skipped — a scrape must degrade, not crash the scraper."""
    families: Dict[str, ParsedFamily] = {}
    types: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
                families.setdefault(parts[2], ParsedFamily(
                    parts[2], parts[3])).type = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, label_blob, raw = m.group(1), m.group(2), m.group(3)
        try:
            value = float(raw)
        except ValueError:
            continue
        labels = {k: _unescape_label(v)
                  for k, v in _LABEL_RE.findall(label_blob or "")}
        # Histogram series fold into their base family.
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            cand = name[: -len(suffix)] if name.endswith(suffix) else None
            if cand and types.get(cand) == "histogram":
                base = cand
                break
        if base is not None:
            fam = families.setdefault(base, ParsedFamily(base, "histogram"))
            le = labels.pop("le", None)
            lkey = tuple(sorted(labels.items()))
            hist = fam.histograms.setdefault(lkey, ParsedHistogram())
            if name.endswith("_bucket"):
                if le == "+Inf":
                    hist.count = int(value)
                elif le is not None:
                    hist.bounds.append(float(le))
                    hist.cumulative.append(int(value))
            elif name.endswith("_sum"):
                hist.sum = value
            elif name.endswith("_count"):
                hist.count = int(value)
            continue
        fam = families.setdefault(
            name, ParsedFamily(name, types.get(name, "untyped")))
        fam.samples[tuple(sorted(labels.items()))] = value
    return families
