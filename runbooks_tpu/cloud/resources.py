"""TPU resource mapping + multi-host pod-slice fan-out.

This replaces the reference's GPU resources layer (reference:
internal/resources/resources.go Apply + gpu_info.go nvidia.com/gpu & GKE
accelerator node selectors) with the TPU-native equivalent, including the one
capability the reference never had (SURVEY.md §2a): **multi-host fan-out** —
a topology that spans hosts becomes an indexed Job (one pod per TPU VM host)
plus a headless Service for stable DNS, with the env JAX needs to form the
slice (`jax.distributed.initialize` coordinator at pod index 0,
megascale-style worker ids from the completion index).

Topology math (GKE conventions):
- v5e (tpu-v5-lite-podslice, ct5lp machines): topology "AxB", 4 chips per
  host once the slice has >= 4 chips (1x1/2x2 are single-host partial).
- v5p (tpu-v5p-slice): topology "AxBxC", 4 chips per host.
- v4  (tpu-v4-podslice): topology "AxBxC", 4 chips per host.
- v6e (tpu-v6e-slice): topology "AxB", 4 chips per host.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional

JAX_COORDINATOR_PORT = 8476

TPU_TYPES: Dict[str, Dict] = {
    "v5e": {"accelerator": "tpu-v5-lite-podslice", "dims": 2,
            "chips_per_host": 4},
    "v5p": {"accelerator": "tpu-v5p-slice", "dims": 3, "chips_per_host": 4},
    "v4": {"accelerator": "tpu-v4-podslice", "dims": 3, "chips_per_host": 4},
    "v6e": {"accelerator": "tpu-v6e-slice", "dims": 2, "chips_per_host": 4},
}


@dataclasses.dataclass(frozen=True)
class TPUSlice:
    type: str               # v5e | v5p | v4 | v6e
    topology: str           # "2x4" / "2x2x2"
    chips: int
    hosts: int
    chips_per_host: int
    accelerator: str        # GKE node-selector value

    @property
    def multi_host(self) -> bool:
        return self.hosts > 1


def parse_tpu(tpu: dict) -> TPUSlice:
    """Validate + resolve a spec.resources.tpu {type, topology} block."""
    tpu_type = tpu.get("type", "")
    info = TPU_TYPES.get(tpu_type)
    if info is None:
        raise ValueError(
            f"unknown tpu type {tpu_type!r}; known: {sorted(TPU_TYPES)}")
    topology = tpu.get("topology", "")
    try:
        dims = [int(d) for d in topology.split("x")]
    except ValueError:
        raise ValueError(f"invalid tpu topology {topology!r}")
    if len(dims) != info["dims"] or any(d < 1 for d in dims):
        raise ValueError(
            f"tpu type {tpu_type} needs a {info['dims']}-dimensional "
            f"topology (e.g. {'2x2' if info['dims'] == 2 else '2x2x2'}), "
            f"got {topology!r}")
    chips = math.prod(dims)
    chips_per_host = min(info["chips_per_host"], chips)
    hosts = max(1, chips // info["chips_per_host"])
    return TPUSlice(type=tpu_type, topology=topology, chips=chips,
                    hosts=hosts, chips_per_host=chips_per_host,
                    accelerator=info["accelerator"])


def apply_cpu_resources(pod_spec: dict, container_name: str,
                        resources: dict) -> None:
    """cpu/memory/disk requests+limits on the named container (reference:
    internal/resources/resources.go Apply)."""
    for container in pod_spec.get("containers", []):
        if container.get("name") != container_name:
            continue
        res = container.setdefault("resources", {})
        requests = res.setdefault("requests", {})
        limits = res.setdefault("limits", {})
        requests["cpu"] = str(resources.get("cpu", 2))
        requests["memory"] = f"{resources.get('memory', 10)}Gi"
        requests["ephemeral-storage"] = f"{resources.get('disk', 10)}Gi"
        limits["memory"] = requests["memory"]
        limits["ephemeral-storage"] = requests["ephemeral-storage"]


def apply_tpu_resources(pod_spec: dict, container_name: str,
                        slice_: TPUSlice, spot: bool = False) -> None:
    """google.com/tpu requests + topology node selectors (+ spot toleration
    to trigger node auto-provisioning, like the reference's GKE spot flow —
    reference: internal/resources/resources.go:52-60)."""
    selectors = pod_spec.setdefault("nodeSelector", {})
    selectors["cloud.google.com/gke-tpu-accelerator"] = slice_.accelerator
    selectors["cloud.google.com/gke-tpu-topology"] = slice_.topology
    if spot:
        selectors["cloud.google.com/gke-spot"] = "true"
        pod_spec.setdefault("tolerations", []).append({
            "key": "cloud.google.com/gke-spot",
            "operator": "Equal",
            "value": "true",
            "effect": "NoSchedule",
        })
    for container in pod_spec.get("containers", []):
        if container.get("name") != container_name:
            continue
        res = container.setdefault("resources", {})
        res.setdefault("requests", {})["google.com/tpu"] = \
            str(slice_.chips_per_host)
        res.setdefault("limits", {})["google.com/tpu"] = \
            str(slice_.chips_per_host)


def distributed_env(job_name: str, service_name: str, namespace: str,
                    slice_: TPUSlice) -> List[dict]:
    """Env for jax.distributed slice formation on indexed-Job pods: the
    coordinator is pod index 0 via the headless service; worker identity
    comes from the completion index (SURVEY.md §5.8 — the reference has no
    trainer rendezvous at all; this is the XLA-collectives-over-ICI answer)."""
    coordinator = (f"{job_name}-0.{service_name}.{namespace}"
                   f".svc.cluster.local:{JAX_COORDINATOR_PORT}")
    return [
        {"name": "JAX_COORDINATOR_ADDRESS", "value": coordinator},
        {"name": "JAX_NUM_PROCESSES", "value": str(slice_.hosts)},
        {"name": "JAX_PROCESS_ID", "valueFrom": {"fieldRef": {
            "fieldPath":
                "metadata.annotations['batch.kubernetes.io/job-completion-index']"
        }}},
        {"name": "TPU_WORKER_ID", "valueFrom": {"fieldRef": {
            "fieldPath":
                "metadata.annotations['batch.kubernetes.io/job-completion-index']"
        }}},
        {"name": "TPU_WORKER_HOSTNAMES", "value": ",".join(
            f"{job_name}-{i}.{service_name}.{namespace}.svc.cluster.local"
            for i in range(slice_.hosts))},
    ]


def headless_service(job_name: str, namespace: str) -> dict:
    """Stable per-pod DNS for the slice (clusterIP: None + job-name
    selector)."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": job_name, "namespace": namespace},
        "spec": {
            "clusterIP": "None",
            "selector": {"job-name": job_name},
            "ports": [{"name": "jax-coordinator",
                       "port": JAX_COORDINATOR_PORT}],
        },
    }


def multislice_env(num_slices: int, slice_id: int, coordinator: str
                   ) -> List[dict]:
    """MEGASCALE env for multi-slice training over DCN: each slice is its own
    ICI domain; XLA's DCN collectives stitch slices together. Coordinator is
    slice 0's host 0."""
    return [
        {"name": "MEGASCALE_COORDINATOR_ADDRESS", "value": coordinator},
        {"name": "MEGASCALE_NUM_SLICES", "value": str(num_slices)},
        {"name": "MEGASCALE_SLICE_ID", "value": str(slice_id)},
    ]


def multislice_jobs(job: dict, slice_: TPUSlice,
                    num_slices: int) -> List[dict]:
    """Expand one workload Job into num_slices jobs ({name}-slice-{i}), each
    fanned out across its hosts, all joined over DCN via MEGASCALE env.
    Returns the flat list of objects to create (jobs + headless services).
    The reference has no multi-node story at all (SURVEY.md §2a); this is
    the v4/v5 multislice topology first-class."""
    import copy

    base_name = job["metadata"]["name"]
    namespace = job["metadata"].get("namespace", "default")
    coordinator = (f"{base_name}-slice-0-0.{base_name}-slice-0."
                   f"{namespace}.svc.cluster.local:{JAX_COORDINATOR_PORT}")
    out: List[dict] = []
    for i in range(num_slices):
        j = copy.deepcopy(job)
        j["metadata"]["name"] = f"{base_name}-slice-{i}"
        j["metadata"].setdefault("labels", {})["slice"] = str(i)
        svc = fan_out_job(j, slice_)
        env = multislice_env(num_slices, i, coordinator)
        for container in j["spec"]["template"]["spec"].get("containers", []):
            existing = {e["name"] for e in container.setdefault("env", [])}
            container["env"].extend(e for e in env
                                    if e["name"] not in existing)
        out.append(j)
        if svc is not None:
            out.append(svc)
    return out


def fan_out_job(job: dict, slice_: TPUSlice) -> Optional[dict]:
    """Turn a single-pod Job into a multi-host indexed Job; returns the
    headless Service to create alongside (None when single-host).

    All-hosts-or-nothing: parallelism == completions == hosts, Indexed
    completion mode, subdomain for stable DNS, and the jax.distributed env
    on every container.
    """
    if not slice_.multi_host:
        return None
    name = job["metadata"]["name"]
    namespace = job["metadata"].get("namespace", "default")
    spec = job["spec"]
    spec["completions"] = slice_.hosts
    spec["parallelism"] = slice_.hosts
    spec["completionMode"] = "Indexed"
    pod_spec = spec["template"]["spec"]
    pod_spec["subdomain"] = name
    # One host dies => whole slice restarts (slice-consistent restart):
    # backoffLimit stays 0 for multi-host — a lost host crashes the peers'
    # jax.distributed processes too, so per-pod retries cannot reform the
    # slice; the reconciler recreates the whole Job instead and the
    # trainer resumes step-exactly (docs/fault-tolerance.md).
    spec["backoffLimit"] = spec.get("backoffLimit", 0)
    pod_spec.setdefault("restartPolicy", "Never")
    env = distributed_env(name, name, namespace, slice_)
    for container in pod_spec.get("containers", []):
        container.setdefault("env", [])
        existing = {e["name"] for e in container["env"]}
        container["env"].extend(
            e for e in env if e["name"] not in existing)
    return headless_service(name, namespace)
