"""Local cloud: hostPath buckets + local registry — the dev/CI substitute.

Plays the role of the reference's kind cloud (reference: internal/cloud/
kind.go — hostPath /bucket with a tar:// scheme hack, registry discovered
from the in-cluster Service): the whole operator loop (build -> store ->
mount -> serve) runs on a laptop/CI with zero cloud dependencies. Identity
binding is a no-op.
"""

from __future__ import annotations

import dataclasses

from runbooks_tpu.api.types import Resource
from runbooks_tpu.cloud.base import (
    UPLOAD_OBJECT,
    BucketMount,
    CommonConfig,
    StorageBuildContext,
    image_name,
    image_tag_for,
    object_bucket_path,
    parse_bucket_url,
)


@dataclasses.dataclass
class LocalCloud:
    config: CommonConfig
    name: str = "local"

    def __post_init__(self):
        if not self.config.artifact_bucket_url:
            self.config.artifact_bucket_url = "file:///bucket"
        if not self.config.registry_url:
            self.config.registry_url = "localhost:5000"

    # -- URLs ----------------------------------------------------------

    def object_artifact_url(self, obj: Resource) -> str:
        scheme, bucket = parse_bucket_url(self.config.artifact_bucket_url)
        return (f"{scheme}://{bucket}/"
                f"{object_bucket_path(self.config.cluster_name, obj)}")

    def object_built_image_url(self, obj: Resource) -> str:
        return image_name(self.config, obj, image_tag_for(obj))

    # -- pod mutation --------------------------------------------------

    def mount_bucket(self, pod_metadata: dict, pod_spec: dict, obj: Resource,
                     mount: BucketMount) -> None:
        _, bucket = parse_bucket_url(self.config.artifact_bucket_url)
        host_root = "/" + bucket.lstrip("/")
        prefix = object_bucket_path(self.config.cluster_name, obj)
        vol_name = f"artifacts-{mount.content_subdir}".replace("/", "-")
        vols = pod_spec.setdefault("volumes", [])
        if not any(v["name"] == vol_name for v in vols):
            vols.append({
                "name": vol_name,
                "hostPath": {
                    "path": f"{host_root}/{prefix}/{mount.bucket_subdir}",
                    "type": "DirectoryOrCreate",
                },
            })
        for container in pod_spec.get("containers", []):
            mounts = container.setdefault("volumeMounts", [])
            mounts.append({
                "name": vol_name,
                "mountPath": f"/content/{mount.content_subdir}",
                "readOnly": mount.read_only,
            })

    def storage_build_context(self, obj: Resource) -> StorageBuildContext:
        """kaniko cannot fetch file:// buckets: mount the object's hostPath
        artifact prefix at /bucket and read the tarball through the mount
        (reference: build_reconciler.go:442-468, the kind-cloud tar://
        hostPath arrangement)."""
        _, rest = parse_bucket_url(self.object_artifact_url(obj))
        return StorageBuildContext(
            context_url=f"tar:///bucket/{UPLOAD_OBJECT}",
            volumes=[{
                "name": "bucket",
                "hostPath": {"path": "/" + rest.lstrip("/"),
                             "type": "Directory"},
            }],
            mounts=[{"name": "bucket", "mountPath": "/bucket",
                     "readOnly": True}],
        )

    # -- identity ------------------------------------------------------

    def associate_principal(self, sa: dict) -> None:  # no-op locally
        return None

    def get_principal(self, sa: dict) -> tuple[str, bool]:
        return "", True
