"""Cloud abstraction: artifact buckets, image registries, identity, mounts.

Interface parity with the reference's cloud layer (reference:
internal/cloud/cloud.go Cloud interface: Name/AutoConfigure/
ObjectBuiltImageURL/ObjectArtifactURL/AssociatePrincipal/GetPrincipal/
MountBucket; naming scheme internal/cloud/common.go) — with the bucket-path
md5 scheme preserved because it is load-bearing for the "bucket as source of
truth" restore design (reference: docs/design.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Protocol

from runbooks_tpu.api.types import Resource


@dataclasses.dataclass
class BucketMount:
    bucket_subdir: str      # path inside the object's artifact prefix
    content_subdir: str     # mount point under /content
    read_only: bool = True


# Where clients PUT build tarballs within an object's artifact prefix
# (reference: internal/controller/build_reconciler.go:29).
UPLOAD_OBJECT = "uploads/latest.tar.gz"


@dataclasses.dataclass
class StorageBuildContext:
    """How a kaniko pod reads an uploaded build tarball on this cloud:
    the --context URL plus any pod volumes/mounts the URL depends on
    (reference: storageBuildJob per-cloud variants,
    build_reconciler.go:405-533)."""
    context_url: str
    volumes: list = dataclasses.field(default_factory=list)
    mounts: list = dataclasses.field(default_factory=list)


class Cloud(Protocol):
    name: str

    def object_artifact_url(self, obj: Resource) -> str: ...

    def object_built_image_url(self, obj: Resource) -> str: ...

    def mount_bucket(self, pod_metadata: dict, pod_spec: dict, obj: Resource,
                     mount: BucketMount) -> None: ...

    def storage_build_context(self, obj: Resource) -> StorageBuildContext: ...

    def associate_principal(self, sa: dict) -> None: ...

    def get_principal(self, sa: dict) -> tuple[str, bool]: ...


def default_storage_build_context(cloud, obj: Resource) -> StorageBuildContext:
    """For buckets kaniko fetches natively (gs://, s3://): context is the
    bucket URL of the uploaded tarball, no extra mounts."""
    url = cloud.object_artifact_url(obj)
    return StorageBuildContext(context_url=f"{url}/{UPLOAD_OBJECT}")


@dataclasses.dataclass
class CommonConfig:
    cluster_name: str = "default"
    artifact_bucket_url: str = ""     # e.g. gs://bucket or file:///data/bucket
    registry_url: str = ""            # e.g. us-docker.pkg.dev/p/repo
    principal: str = ""               # e.g. substratus@proj.iam.gserviceaccount.com

    @classmethod
    def from_env(cls) -> "CommonConfig":
        return cls(
            cluster_name=os.environ.get("CLUSTER_NAME", "default"),
            artifact_bucket_url=os.environ.get("ARTIFACT_BUCKET_URL", ""),
            registry_url=os.environ.get("REGISTRY_URL", ""),
            principal=os.environ.get("PRINCIPAL", ""),
        )


def object_bucket_path(cluster: str, obj: Resource) -> str:
    """Deterministic artifact prefix: md5 over the object's logical path, so
    re-created clusters/objects find their prior artifacts (reference:
    internal/cloud/common.go:45-66 and docs/design.md:80-137)."""
    logical = (f"clusters/{cluster}/namespaces/{obj.namespace}/"
               f"{obj.kind.lower()}s/{obj.name}")
    return hashlib.md5(logical.encode()).hexdigest()


def image_name(cfg: CommonConfig, obj: Resource, tag: str) -> str:
    """{registry}/{cluster}-{kind}-{ns}-{name}:{tag} (reference:
    internal/cloud/common.go:18-43)."""
    return (f"{cfg.registry_url}/{cfg.cluster_name}-{obj.kind.lower()}-"
            f"{obj.namespace}-{obj.name}:{tag}")


def image_tag_for(obj: Resource) -> str:
    """Tag = git ref when building from git, upload md5 when building from an
    upload, 'latest' otherwise."""
    git = obj.build_git
    if git:
        return git.get("tag") or git.get("branch") or "main"
    upload = obj.build_upload
    if upload and upload.get("md5checksum"):
        return upload["md5checksum"]
    return "latest"


def parse_bucket_url(url: str) -> tuple[str, str]:
    """'scheme://bucket[/path]' -> (scheme, 'bucket[/path]')."""
    if "://" not in url:
        raise ValueError(f"invalid bucket url {url!r}")
    scheme, rest = url.split("://", 1)
    return scheme, rest.rstrip("/")
