"""GCP cloud: GCS-FUSE bucket mounts + Artifact Registry + workload identity.

Reference behavior mirrored (reference: internal/cloud/gcp.go): artifact
buckets mount through the GKE GCS FUSE CSI driver (pod annotations
``gke-gcsfuse/*`` + a csi volume), images go to Artifact Registry, and
Kubernetes ServiceAccounts bind to the GCP principal via the
``iam.gke.io/gcp-service-account`` annotation (the IAM policy half happens in
the SCI service — runbooks_tpu.sci).
"""

from __future__ import annotations

import dataclasses

from runbooks_tpu.api.types import Resource
from runbooks_tpu.cloud.base import (
    BucketMount,
    CommonConfig,
    StorageBuildContext,
    default_storage_build_context,
    image_name,
    image_tag_for,
    object_bucket_path,
    parse_bucket_url,
)

WI_ANNOTATION = "iam.gke.io/gcp-service-account"
# The artifact FSGroup the workload containers run with so gcsfuse-written
# files stay group-writable (reference: model_controller.go FSGroup 3003).
ARTIFACT_FS_GROUP = 3003


@dataclasses.dataclass
class GCPConfig:
    common: CommonConfig
    project_id: str = ""
    cluster_location: str = ""


@dataclasses.dataclass
class GCPCloud:
    config: GCPConfig
    name: str = "gcp"

    # -- URLs ----------------------------------------------------------

    def object_artifact_url(self, obj: Resource) -> str:
        scheme, bucket = parse_bucket_url(
            self.config.common.artifact_bucket_url)
        assert scheme == "gs", f"expected gs:// bucket, got {scheme}"
        return (f"gs://{bucket}/"
                f"{object_bucket_path(self.config.common.cluster_name, obj)}")

    def object_built_image_url(self, obj: Resource) -> str:
        return image_name(self.config.common, obj, image_tag_for(obj))

    # -- pod mutation --------------------------------------------------

    def mount_bucket(self, pod_metadata: dict, pod_spec: dict, obj: Resource,
                     mount: BucketMount) -> None:
        annotations = pod_metadata.setdefault("annotations", {})
        annotations["gke-gcsfuse/volumes"] = "true"
        annotations.setdefault("gke-gcsfuse/cpu-limit", "2")
        annotations.setdefault("gke-gcsfuse/memory-limit", "800Mi")
        annotations.setdefault("gke-gcsfuse/ephemeral-storage-limit", "20Gi")

        _, bucket = parse_bucket_url(self.config.common.artifact_bucket_url)
        bucket_name = bucket.split("/", 1)[0]
        prefix = object_bucket_path(self.config.common.cluster_name, obj)
        vol_name = f"gcs-{mount.content_subdir}".replace("/", "-")
        vols = pod_spec.setdefault("volumes", [])
        if not any(v["name"] == vol_name for v in vols):
            vols.append({
                "name": vol_name,
                "csi": {
                    "driver": "gcsfuse.csi.storage.gke.io",
                    "readOnly": mount.read_only,
                    "volumeAttributes": {
                        "bucketName": bucket_name,
                        "mountOptions":
                            f"implicit-dirs,uid=0,gid={ARTIFACT_FS_GROUP}",
                    },
                },
            })
        pod_spec.setdefault("securityContext", {})["fsGroup"] = \
            ARTIFACT_FS_GROUP
        for container in pod_spec.get("containers", []):
            container.setdefault("volumeMounts", []).append({
                "name": vol_name,
                "mountPath": f"/content/{mount.content_subdir}",
                # SubPath selects the object's prefix inside the bucket.
                "subPath": f"{prefix}/{mount.bucket_subdir}",
                "readOnly": mount.read_only,
            })

    def storage_build_context(self, obj: Resource) -> StorageBuildContext:
        return default_storage_build_context(self, obj)

    # -- identity ------------------------------------------------------

    def associate_principal(self, sa: dict) -> None:
        sa.setdefault("metadata", {}).setdefault("annotations", {})[
            WI_ANNOTATION] = self.config.common.principal

    def get_principal(self, sa: dict) -> tuple[str, bool]:
        principal = self.config.common.principal
        bound = (sa.get("metadata", {}).get("annotations", {})
                 .get(WI_ANNOTATION) == principal)
        return principal, bound
