"""GCE metadata-server probe for cloud auto-detection.

When CLOUD is unset, the controller probes the GCE metadata server to decide
whether it is running on Google Cloud and, if so, auto-configures project /
cluster identity from metadata attributes (reference:
internal/cloud/cloud.go:48-85 `New()` OnGCE probe and
internal/cloud/gcp.go:28-71 `AutoConfigure`).

The probe host is overridable via GCE_METADATA_HOST (the same escape hatch
the Google client libraries use), which is also how tests point it at a
local HTTP fake.
"""

from __future__ import annotations

import os
import urllib.error
import urllib.request

_FLAVOR = ("Metadata-Flavor", "Google")


def _base_url() -> str:
    host = os.environ.get("GCE_METADATA_HOST", "metadata.google.internal")
    return f"http://{host}/computeMetadata/v1"


def fetch(path: str, timeout: float = 1.0) -> str:
    """GET a metadata path (e.g. 'project/project-id'); raises on failure."""
    req = urllib.request.Request(f"{_base_url()}/{path.lstrip('/')}")
    req.add_header(*_FLAVOR)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode().strip()


def _bounded(fn, timeout: float):
    """Run fn on a worker thread with a hard deadline and return its result
    (None on timeout/error). urlopen's timeout does NOT bound the DNS
    lookup, so every metadata call goes through here — an off-GCP box with
    a slow resolver must not stall controller startup."""
    import threading

    result = {}

    def runner():
        try:
            result["v"] = fn()
        except (urllib.error.URLError, OSError, ValueError):
            pass

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    t.join(timeout=timeout)
    return result.get("v")


def on_gce(timeout: float = 1.0) -> bool:
    """True when the GCE metadata server answers with the Google flavor
    header (the OnGCE probe; reference cloud.go:52-57)."""

    def probe():
        req = urllib.request.Request(_base_url() + "/")
        req.add_header(*_FLAVOR)
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.headers.get("Metadata-Flavor") == "Google"

    return bool(_bounded(probe, timeout + 0.5))


def auto_configure() -> dict:
    """Metadata attributes a GKE node exposes that we need for GCPConfig
    (reference gcp.go:28-71): project id, cluster name, cluster location.
    Missing attributes come back as ''."""
    out = {}
    for key, path in (
        ("project_id", "project/project-id"),
        ("cluster_name", "instance/attributes/cluster-name"),
        ("cluster_location", "instance/attributes/cluster-location"),
    ):
        out[key] = _bounded(lambda p=path: fetch(p), timeout=1.5) or ""
    return out
