"""GCE metadata-server probe for cloud auto-detection.

When CLOUD is unset, the controller probes the GCE metadata server to decide
whether it is running on Google Cloud and, if so, auto-configures project /
cluster identity from metadata attributes (reference:
internal/cloud/cloud.go:48-85 `New()` OnGCE probe and
internal/cloud/gcp.go:28-71 `AutoConfigure`).

The probe host is overridable via GCE_METADATA_HOST (the same escape hatch
the Google client libraries use), which is also how tests point it at a
local HTTP fake.
"""

from __future__ import annotations

import os
import urllib.error
import urllib.request

_FLAVOR = ("Metadata-Flavor", "Google")


def _hosts() -> list:
    override = os.environ.get("GCE_METADATA_HOST")
    if override:
        return [override]
    # Try the DNS name AND the literal address, like the Go metadata
    # client: a transient DNS hiccup at pod start must not make a GKE
    # controller look off-cloud.
    return ["metadata.google.internal", "169.254.169.254"]


def _base_url(host: str) -> str:
    return f"http://{host}/computeMetadata/v1"


# Sentinel: the metadata server answered 404 — the attribute does not
# exist (e.g. GKE instance attributes on a plain GCE VM). Distinct from
# "no host reachable", which is a connectivity failure worth crash-looping
# over.
_ABSENT = object()


def _fetch_raw(path: str, timeout: float = 1.0):
    """Try each metadata host with its OWN bounded window (the _bounded
    deadline must cover a hanging DNS lookup on host 1 without starving
    the literal-IP fallback). Returns the value, _ABSENT on 404, or None
    when no host answered."""
    for host in _hosts():
        def one(h=host):
            req = urllib.request.Request(
                f"{_base_url(h)}/{path.lstrip('/')}")
            req.add_header(*_FLAVOR)
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return resp.read().decode().strip()
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    return _ABSENT
                raise

        value = _bounded(one, timeout + 0.5)
        if value is not None:
            return value
    return None


def fetch(path: str, timeout: float = 1.0) -> str:
    """GET a metadata path (e.g. 'project/project-id'); raises on failure
    (OSError: unreachable; LookupError: server answered but path absent)."""
    value = _fetch_raw(path, timeout)
    if value is None:
        raise OSError(f"GCE metadata server unreachable fetching {path}")
    if value is _ABSENT:
        raise LookupError(f"GCE metadata attribute absent: {path}")
    return value


def _bounded(fn, timeout: float):
    """Run fn on a worker thread with a hard deadline and return its result
    (None on timeout/error). urlopen's timeout does NOT bound the DNS
    lookup, so every metadata call goes through here — an off-GCP box with
    a slow resolver must not stall controller startup."""
    import threading

    result = {}

    def runner():
        try:
            result["v"] = fn()
        except (urllib.error.URLError, OSError, ValueError):
            pass

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    t.join(timeout=timeout)
    return result.get("v")


def on_gce(timeout: float = 1.0, attempts: int = 3) -> bool:
    """True when the GCE metadata server answers with the Google flavor
    header (the OnGCE probe; reference cloud.go:52-57). Probes both the
    DNS name and the literal 169.254.169.254, with retries — a single-shot
    1s probe failing on a transient hiccup must not misclassify the
    environment (r4 advisor, medium)."""

    def probe_host(host):
        def probe():
            req = urllib.request.Request(_base_url(host) + "/")
            req.add_header(*_FLAVOR)
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.headers.get("Metadata-Flavor") == "Google"

        return _bounded(probe, timeout + 0.5)

    for attempt in range(attempts):
        for host in _hosts():
            if probe_host(host):
                return True
        if attempt < attempts - 1:
            import time

            time.sleep(0.2 * (attempt + 1))
    return False


def maintenance_event(timeout: float = 1.0) -> Optional[str]:
    """The instance's pending maintenance event, or None when nothing is
    pending (or we are not on GCE / the server is unreachable).

    GCE flips ``instance/maintenance-event`` from NONE to
    TERMINATE_ON_HOST_MAINTENANCE / MIGRATE_ON_HOST_MAINTENANCE ahead of
    host maintenance; TPU VMs surface upcoming preemptions the same way.
    The trainer polls this (train/trainer.py maintenance_poll_s) and treats
    a pending event like SIGTERM: emergency checkpoint + clean exit, so the
    work since the last periodic checkpoint survives the event
    (docs/fault-tolerance.md)."""
    value = _fetch_raw("instance/maintenance-event", timeout)
    if value is None or value is _ABSENT or value in ("", "NONE"):
        return None
    return str(value)


def auto_configure(needed=("project_id", "cluster_name",
                           "cluster_location")) -> dict:
    """Metadata attributes a GKE node exposes that we need for GCPConfig
    (reference gcp.go:28-71): project id, cluster name, cluster location.
    Fetches ONLY the `needed` keys — the caller passes what its env did
    not provide, so an off-GCE CLOUD=gcp deployment missing just the
    optional cluster name never touches the project-id path.

    project_id (when needed) is required: unreachable-or-absent raises
    RuntimeError, mirroring the reference's AutoConfigure error returns —
    a not-yet-ready metadata server must crash-loop the controller until
    it answers, not let it proceed with empty project identity (r4
    advisor). The GKE-only instance attributes (cluster-name/-location)
    come back as '' when unreachable or 404: a plain GCE VM / off-GCE box
    with env-provided identity is not an error."""
    paths = {
        "project_id": "project/project-id",
        "cluster_name": "instance/attributes/cluster-name",
        "cluster_location": "instance/attributes/cluster-location",
    }
    out = {k: "" for k in paths}
    for key in needed:
        value = _fetch_raw(paths[key], timeout=1.0)
        if key == "project_id" and (
                value is None or value is _ABSENT or not value):
            raise RuntimeError(
                "failed to get project id from the GCE metadata server "
                f"({paths[key]}); set PROJECT_ID or fix node metadata")
        out[key] = "" if (value is None or value is _ABSENT) else value
    return out
