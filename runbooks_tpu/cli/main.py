"""`rbt` — the runbooks-tpu dev CLI (reference analog: cmd/sub, internal/cli).

Round-1 stub: subcommands land with the orchestration layer (apply/run/
serve/get/delete/notebook).
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    sys.stderr.write(
        "rbt: CLI subcommands (apply/run/serve/get/delete/notebook) are "
        "under construction in this round.\n"
    )
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
