"""`rbt` — the runbooks-tpu dev CLI.

Command parity with the reference's `sub` CLI (reference: cmd/sub/main.go,
internal/cli/root.go — apply, run, get, delete, serve, notebook), built on
the same client primitives (SSA apply, upload handshake, watch-based
readiness). Where the reference runs a bubbletea TUI, rbt prints live
condition updates; port-forwarding shells out to kubectl (the reference
shells out to kubectl for cp the same way — internal/client/cp/kubectl.go).

Manifest discovery mirrors internal/tui/manifests.go: a path, file, or URL
yields YAML docs; non-runbooks kinds are skipped; kinds are applied in
dependency-friendly order (Dataset, Model, Server, Notebook).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional

import yaml

from runbooks_tpu.api.types import API_VERSION, KINDS
from runbooks_tpu.k8s import objects as ko

KIND_ORDER = {"Dataset": 0, "Model": 1, "Server": 2, "Notebook": 3}


def use_tui(args) -> bool:
    """Full-screen TUI when attached to a terminal (reference: every `sub`
    command runs a bubbletea program); --plain or RBT_NO_TUI=1 opts out,
    and pipes/CI fall back to the plain printed flow automatically."""
    if getattr(args, "plain", False) or os.environ.get("RBT_NO_TUI") == "1":
        return False
    return sys.stdout.isatty() and sys.stdin.isatty()


def run_flow(flow) -> int:
    """Run a TUI flow to completion; exit code from its final error."""
    from runbooks_tpu.tui.core import Program

    Program(flow).run()
    if flow.final_error is not None:
        # The alt-screen teardown erased the last frame; restate the error.
        print(f"Error: {flow.final_error}", file=sys.stderr)
        return 1
    return 0


def context_dir(filename: str) -> str:
    """Build-context directory for -f: the directory itself when -f is a
    directory, else the file's directory."""
    if os.path.isdir(filename):
        return filename
    return os.path.dirname(os.path.abspath(filename)) or "."


def make_client(args):
    if os.environ.get("RBT_FAKE"):
        # Hermetic/demo mode: a process-local fake cluster (useful with
        # STANDALONE controller or for dry-runs/tests).
        from runbooks_tpu.k8s.fake import FakeCluster

        return FakeCluster()
    from runbooks_tpu.k8s.client import K8sClient, KubeConfig

    cfg = (KubeConfig.from_kubeconfig(args.kubeconfig)
           if getattr(args, "kubeconfig", None) else KubeConfig.auto())
    return K8sClient(cfg)


# ---------------------------------------------------------------------------
# Manifests
# ---------------------------------------------------------------------------

def load_manifests(path: str, namespace: str) -> List[dict]:
    docs: List[dict] = []
    if re.match(r"^https?://", path):
        with urllib.request.urlopen(path, timeout=30) as resp:
            docs = list(yaml.safe_load_all(resp.read()))
    elif os.path.isdir(path):
        for fname in sorted(os.listdir(path)):
            if fname.endswith((".yaml", ".yml")):
                with open(os.path.join(path, fname)) as f:
                    docs.extend(yaml.safe_load_all(f))
    else:
        with open(path) as f:
            docs = list(yaml.safe_load_all(f))
    out = []
    for doc in docs:
        if not isinstance(doc, dict) or doc.get("kind") not in KINDS:
            continue
        if doc.get("apiVersion") != API_VERSION:
            continue
        doc.setdefault("metadata", {}).setdefault("namespace", namespace)
        out.append(doc)
    out.sort(key=lambda d: KIND_ORDER.get(d["kind"], 9))
    return out


def parse_scope(scope: str) -> tuple[Optional[str], Optional[str]]:
    """'models' / 'models/m1' / '' -> (Kind, name)."""
    if not scope:
        return None, None
    part, _, name = scope.partition("/")
    singular = part.rstrip("s").lower()
    for kind in KINDS:
        if kind.lower() == singular:
            return kind, name or None
    raise SystemExit(f"unknown kind {part!r}; expected one of "
                     f"{[k.lower() + 's' for k in KINDS]}")


# ---------------------------------------------------------------------------
# Output helpers
# ---------------------------------------------------------------------------

def print_table(rows: List[List[str]], header: List[str]) -> None:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    def fmt(row):
        return "  ".join(str(c).ljust(w) for c, w in zip(row, widths))
    print(fmt(header))
    for row in rows:
        print(fmt(row))


def telemetry_summary(obj: dict) -> str:
    """Compact one-cell rendering of .status.telemetry (fleet scraper;
    docs/observability.md): live load for Servers, training progress for
    Models."""
    t = ko.deep_get(obj, "status", "telemetry", default=None)
    if not isinstance(t, dict) or not t:
        return ""
    parts = []
    if "step" in t:
        parts.append(f"step={t['step']}")
        if "loss" in t:
            parts.append(f"loss={t['loss']}")
        if "goodput" in t:
            parts.append(f"goodput={t['goodput']}")
    else:
        if "activeSlots" in t:
            parts.append(f"slots={t['activeSlots']}")
        if "queueDepth" in t:
            parts.append(f"queue={t['queueDepth']}")
        if "queueWaitP90Ms" in t:
            parts.append(f"qw90={t['queueWaitP90Ms']}ms")
        if "ttftP99Ms" in t:
            parts.append(f"ttft99={t['ttftP99Ms']}ms")
        if "tokensPerSec" in t:
            parts.append(f"tok/s={t['tokensPerSec']}")
        if "burnRate" in t:
            parts.append(f"burn={t['burnRate']}x")
        if ko.deep_get(obj, "spec", "slo", default=None):
            # Error-budget remaining (controller/burnrate.py): present
            # once the fleet history is warm enough to account the
            # trailing budget window; "-" until then.
            budget = t.get("errorBudgetRemainingPct")
            parts.append(f"budget={budget:g}%"
                         if isinstance(budget, (int, float))
                         else "budget=-")
    if "replicasUp" in t and "replicas" in t:
        parts.append(f"up={t['replicasUp']}/{t['replicas']}")
    # Last-incident age from .status.lastIncident (controller-side
    # SLO-onset captures; docs/observability.md "Incident snapshots").
    inc = ko.deep_get(obj, "status", "lastIncident", default=None)
    if isinstance(inc, dict) and inc.get("unixTime"):
        age = max(0.0, time.time() - float(inc["unixTime"]))
        parts.append(f"lastinc={age:.0f}s")
    return " ".join(parts)


def condition_summary(obj: dict) -> str:
    conds = ko.deep_get(obj, "status", "conditions", default=[]) or []
    parts = []
    for c in conds:
        mark = "+" if c.get("status") == "True" else "-"
        parts.append(f"{mark}{c.get('type')}")
    return ",".join(parts)


def wait_ready(client, obj: dict, timeout_s: float, quiet=False) -> bool:
    kind, ns, name = ko.kind(obj), ko.namespace(obj), ko.name(obj)
    deadline = time.monotonic() + timeout_s
    last = ""
    while time.monotonic() < deadline:
        cur = client.get(API_VERSION, kind, ns, name)
        if cur is None:
            time.sleep(0.5)
            continue
        summary = condition_summary(cur)
        if summary != last and not quiet:
            print(f"  {kind}/{name}: {summary or 'pending'}")
            last = summary
        if ko.deep_get(cur, "status", "ready"):
            if not quiet:
                print(f"  {kind}/{name}: ready")
            return True
        time.sleep(0.5)
    return False


# ---------------------------------------------------------------------------
# Commands
# ---------------------------------------------------------------------------

def cmd_apply(args) -> int:
    client = make_client(args)
    if use_tui(args):
        from runbooks_tpu.tui.flows import ApplyFlow

        return run_flow(ApplyFlow(
            client, args.filename, args.namespace,
            build_dir=args.build, wait=args.wait,
            timeout_s=args.timeout))
    manifests = load_manifests(args.filename, args.namespace)
    if not manifests:
        print(f"no runbooks-tpu manifests found in {args.filename}",
              file=sys.stderr)
        return 1
    for obj in manifests:
        upload_dir = _upload_dir_for(obj, args)
        if upload_dir:
            from runbooks_tpu.utils.upload import upload_build_context

            print(f"{obj['kind']}/{ko.name(obj)}: uploading build context "
                  f"from {upload_dir}")
            upload_build_context(client, obj, upload_dir,
                                 progress=lambda m: print(f"  {m}"))
        else:
            client.apply(obj, "rbt-cli")
            print(f"{obj['kind']}/{ko.name(obj)} applied")
    if args.wait:
        ok = all(wait_ready(client, o, args.timeout) for o in manifests)
        return 0 if ok else 1
    return 0


def _upload_dir_for(obj: dict, args) -> Optional[str]:
    build = ko.deep_get(obj, "spec", "build", default={}) or {}
    if "upload" in build or getattr(args, "build", None):
        # `rbt run/apply --build DIR` or a spec that asks for an upload.
        return getattr(args, "build", None) or context_dir(args.filename)
    return None


def _collect_rows(client, kind_filter, name_filter, namespace):
    rows = []
    for kind in KINDS:
        if kind_filter and kind != kind_filter:
            continue
        for obj in client.list(API_VERSION, kind, namespace=namespace):
            if name_filter and ko.name(obj) != name_filter:
                continue
            ready = "True" if ko.deep_get(obj, "status", "ready") else "False"
            rows.append([f"{kind.lower()}s/{ko.name(obj)}",
                         ko.namespace(obj), ready, condition_summary(obj),
                         telemetry_summary(obj)])
    return rows


def cmd_get(args) -> int:
    client = make_client(args)
    kind_filter, name_filter = parse_scope(args.scope)
    if args.watch and use_tui(args):
        from runbooks_tpu.tui.flows import GetFlow

        return run_flow(GetFlow(client, args.namespace,
                                kind_filter or "", name_filter or ""))
    header = ["NAME", "NAMESPACE", "READY", "CONDITIONS", "TELEMETRY"]
    if not args.watch:
        rows = _collect_rows(client, kind_filter, name_filter,
                             args.namespace)
        if not rows:
            print("no resources found")
            return 0
        print_table(rows, header)
        return 0
    # Live watch view (the reference's `sub get` is a watch-based TUI table
    # — internal/tui/get.go); redraw on change, ctrl-c to exit.
    last = None
    try:
        while True:
            rows = _collect_rows(client, kind_filter, name_filter,
                                 args.namespace)
            snapshot = json.dumps(rows)
            if snapshot != last:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                print(time.strftime("%H:%M:%S"), "(watching — ctrl-c to exit)")
                print_table(rows or [["(none)", "", "", "", ""]], header)
                last = snapshot
            time.sleep(1.0)
    except KeyboardInterrupt:
        return 0


def cmd_delete(args) -> int:
    client = make_client(args)
    if args.filename:
        targets = [(d["kind"], ko.name(d))
                   for d in load_manifests(args.filename, args.namespace)]
    else:
        kind, name = parse_scope(args.scope)
        if not kind or not name:
            raise SystemExit("usage: rbt delete <kind>/<name> | -f FILE")
        targets = [(kind, name)]
    if use_tui(args):
        from runbooks_tpu.tui.flows import DeleteFlow

        return run_flow(DeleteFlow(client, targets, args.namespace))
    for kind, name in targets:
        ok = client.delete(API_VERSION, kind, args.namespace, name)
        print(f"{kind.lower()}s/{name} " + ("deleted" if ok else "not found"))
    return 0


def _auto_increment_name(client, kind: str, namespace: str,
                         base: str) -> str:
    """base -> base-N with N = max existing + 1 (reference:
    internal/tui/common.go name auto-increment)."""
    pattern = re.compile(re.escape(base) + r"-(\d+)$")
    top = 0
    for obj in client.list(API_VERSION, kind, namespace=namespace):
        m = pattern.match(ko.name(obj))
        if m:
            top = max(top, int(m.group(1)))
        elif ko.name(obj) == base:
            top = max(top, 0)
    return f"{base}-{top + 1}"


def cmd_run(args) -> int:
    """Create-with-upload batch flow (reference: internal/tui/run.go +
    `sub run`): package the CWD, create the object (auto-incremented name or
    --replace), wait until it completes."""
    client = make_client(args)
    if use_tui(args):
        from runbooks_tpu.tui.flows import RunFlow

        return run_flow(RunFlow(
            client, args.filename, args.namespace, build_dir=args.build,
            increment=args.increment, replace=args.replace,
            timeout_s=args.timeout))
    manifests = load_manifests(args.filename, args.namespace)
    if not manifests:
        print("no manifests found", file=sys.stderr)
        return 1
    rc = 0
    for obj in manifests:
        kind, ns, base = obj["kind"], ko.namespace(obj), ko.name(obj)
        if args.replace:
            client.delete(API_VERSION, kind, ns, base)
        elif args.increment:
            obj["metadata"]["name"] = _auto_increment_name(
                client, kind, ns, base)
        build = ko.deep_get(obj, "spec", "build", default={}) or {}
        if args.build or "upload" in build:
            from runbooks_tpu.utils.upload import upload_build_context

            build_dir = args.build or context_dir(args.filename)
            upload_build_context(client, obj, build_dir,
                                 progress=lambda m: print(f"  {m}"))
        else:
            # git builds (and no-build objects) apply as-is; the build
            # reconciler handles the rest server-side.
            client.apply(obj, "rbt-cli")
        print(f"{kind}/{ko.name(obj)} created")
        if not wait_ready(client, obj, args.timeout):
            print(f"{kind}/{ko.name(obj)} did not become ready",
                  file=sys.stderr)
            rc = 1
    return rc


def cmd_serve(args) -> int:
    """Wait for a Server to be ready, then port-forward localhost:PORT ->
    service 8080 (reference: internal/tui/serve.go)."""
    client = make_client(args)
    kind, name = parse_scope(args.scope)
    if kind != "Server" or not name:
        raise SystemExit("usage: rbt serve servers/<name>")
    if use_tui(args):
        from runbooks_tpu.tui.flows import ServeFlow

        return run_flow(ServeFlow(client, name, args.namespace,
                                  local_port=args.port,
                                  timeout_s=args.timeout))
    obj = client.get(API_VERSION, "Server", args.namespace, name)
    if obj is None:
        raise SystemExit(f"servers/{name} not found")
    if not wait_ready(client, obj, args.timeout):
        return 1
    pod = _server_run_pod(client, args.namespace, name)
    if pod is not None:
        from runbooks_tpu.controller.server import SERVE_PORT

        rc = _inprocess_port_forward(client, args.namespace, pod,
                                     args.port, SERVE_PORT)
        if rc is not None:
            return rc
    print(f"forwarding localhost:{args.port} -> service/{name}:80 "
          f"(ctrl-c to stop)")
    return _kubectl_port_forward(f"service/{name}", args.port, 80,
                                 args.namespace)


def cmd_notebook(args) -> int:
    """Apply/derive a Notebook, upload the workspace, wait, port-forward 8888,
    and sync files back (reference: internal/tui/notebook.go flow)."""
    client = make_client(args)
    if args.resume and args.build:
        raise SystemExit(
            "--resume reattaches without uploading; drop --build (apply the "
            "manifest again to rebuild)")
    if use_tui(args):
        from runbooks_tpu.tui.flows import NotebookFlow

        return run_flow(NotebookFlow(
            client, args.filename, args.namespace, build_dir=args.build,
            sync=args.sync, timeout_s=args.timeout, resume=args.resume))
    if args.resume:
        # Reattach to an existing notebook: no manifests, no upload — just
        # unsuspend if needed, then the shared wait/sync/port-forward tail
        # (reference: `sub notebook --resume <name>`).
        nb = client.get(API_VERSION, "Notebook", args.namespace, args.resume)
        if nb is None:
            raise SystemExit(f"notebooks/{args.resume} not found")
        if ko.deep_get(nb, "spec", "suspend"):
            client.apply({"apiVersion": API_VERSION, "kind": "Notebook",
                          "metadata": {"name": args.resume,
                                       "namespace": args.namespace},
                          "spec": {"suspend": False}}, "rbt-cli-suspend")
        return _notebook_attach(client, args, nb)
    manifests = load_manifests(args.filename, args.namespace)
    nb = next((m for m in manifests if m["kind"] == "Notebook"), None)
    if nb is None and manifests:
        # Derive a notebook from another object's spec (reference:
        # internal/client/notebook.go NotebookForObject).
        src = manifests[0]
        nb = {
            "apiVersion": API_VERSION, "kind": "Notebook",
            "metadata": {"name": ko.name(src),
                         "namespace": args.namespace},
            "spec": {k: v for k, v in src.get("spec", {}).items()
                     if k in ("image", "build", "env", "params", "resources",
                              "model", "dataset")},
        }
    if nb is None:
        raise SystemExit("no notebook (or derivable object) found")
    nb_build = ko.deep_get(nb, "spec", "build", default={}) or {}
    if args.build or "upload" in nb_build:
        from runbooks_tpu.utils.upload import upload_build_context

        build_dir = args.build or context_dir(args.filename)
        upload_build_context(client, nb, build_dir,
                             progress=lambda m: print(f"  {m}"))
    else:
        client.apply(nb, "rbt-cli")
    if nb["spec"].get("suspend"):
        nb["spec"]["suspend"] = False
        client.apply(nb, "rbt-cli")
    print(f"notebooks/{ko.name(nb)} applied; waiting for readiness…")
    return _notebook_attach(client, args, nb)


def _notebook_attach(client, args, nb: dict) -> int:
    """Shared notebook tail: wait ready, start file sync, port-forward
    8888 (used by both the fresh-apply and --resume paths)."""
    if not wait_ready(client, nb, args.timeout):
        return 1
    pod = f"{ko.name(nb)}-notebook"
    if args.sync:
        from runbooks_tpu.utils.sync import start_sync

        start_sync(pod, args.namespace, context_dir(args.filename))
    print("open http://localhost:8888?token=default")
    rc = _inprocess_port_forward(client, args.namespace, pod, 8888, 8888)
    if rc is not None:
        return rc
    return _kubectl_port_forward(f"pod/{pod}", 8888, 8888, args.namespace)


def _sse_chat_once(url: str, messages: List[dict], max_tokens: int,
                   temperature: float, out=None) -> str:
    """One streamed chat turn: POST /v1/chat/completions with stream:true,
    print deltas as they arrive, return the full assistant text."""
    out = out if out is not None else sys.stdout  # late-bound: tests capture
    req = urllib.request.Request(
        f"{url}/v1/chat/completions",
        data=json.dumps({"messages": messages, "max_tokens": max_tokens,
                         "temperature": temperature,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    text = []
    with urllib.request.urlopen(req, timeout=600) as resp:
        for raw in resp:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            # Tolerate schema drift from arbitrary --url endpoints
            # (usage-only chunks with empty choices, non-JSON keepalives):
            # skip what we can't read; only explicit error events abort.
            try:
                event = json.loads(payload)
            except json.JSONDecodeError:
                continue
            if "error" in event:
                raise RuntimeError(event["error"].get("message", "error"))
            choices = event.get("choices") or []
            if not choices:
                continue
            delta = choices[0].get("delta", {})
            piece = delta.get("content", "")
            if piece:
                text.append(piece)
                out.write(piece)
                out.flush()
    out.write("\n")
    return "".join(text)


def _resolve_server_url(args, usage: str):
    """(url, port-forwarder-or-None) for a Server-scoped command: --url is
    used directly; otherwise resolve the Server's running pod and open an
    in-process port-forward on an ephemeral local port. Callers stop() the
    returned forwarder when done."""
    if args.url:
        return args.url, None
    client = make_client(args)
    kind, name = parse_scope(args.scope)
    if kind != "Server" or not name:
        raise SystemExit(usage)
    obj = client.get(API_VERSION, "Server", args.namespace, name)
    if obj is None:
        raise SystemExit(f"servers/{name} not found")
    if not wait_ready(client, obj, args.timeout):
        raise SystemExit(1)
    pod = _server_run_pod(client, args.namespace, name)
    cfg = getattr(client, "config", None)
    if pod is None or cfg is None:
        raise SystemExit(
            "no running server pod reachable; use --url with an "
            "existing port-forward")
    from runbooks_tpu.controller.server import SERVE_PORT
    from runbooks_tpu.k8s.portforward import PortForwarder

    ready = threading.Event()
    bound = {}

    def on_ready(p):
        bound["port"] = p
        ready.set()

    pf = PortForwarder(cfg, args.namespace, pod, 0, SERVE_PORT,
                       on_ready=on_ready)
    threading.Thread(target=pf.serve, daemon=True).start()
    if not ready.wait(timeout=30):
        raise SystemExit("port-forward did not become ready")
    return f"http://127.0.0.1:{bound['port']}", pf


def cmd_chat(args) -> int:
    """Interactive streaming chat against a Server (reference analog:
    internal/tui/infer_chat.go — an unused skeleton there; functional
    here). Resolves the server's running pod and opens an in-process
    port-forward unless --url points somewhere directly."""
    url, pf = _resolve_server_url(
        args, "usage: rbt chat servers/<name> | --url URL")

    messages: List[dict] = []
    if args.system:
        messages.append({"role": "system", "content": args.system})
    try:
        while True:
            try:
                prompt = input("> ")
            except EOFError:
                break
            if not prompt.strip():
                continue
            if prompt.strip() in ("/quit", "/exit"):
                break
            messages.append({"role": "user", "content": prompt})
            try:
                reply = _sse_chat_once(url, messages, args.max_tokens,
                                       args.temperature)
            except (RuntimeError, OSError) as e:
                print(f"chat error: {e}", file=sys.stderr)
                messages.pop()
                continue
            messages.append({"role": "assistant", "content": reply})
    except KeyboardInterrupt:
        pass
    finally:
        if pf is not None:
            pf.stop()
    return 0


def cmd_profile(args) -> int:
    """Trigger an on-demand TPU/XLA profiler capture on a live Server
    (POST /debug/profile, docs/observability.md): traces N seconds of
    real traffic into the server's {artifacts}/profiles/ — viewable in
    XProf/TensorBoard from the artifact bucket. No restart, no spec
    change; the capture window is the only cost."""
    url, pf = _resolve_server_url(
        args, "usage: rbt profile servers/<name> [--seconds N] | --url URL")
    try:
        req = urllib.request.Request(
            f"{url}/debug/profile?seconds={args.seconds}", data=b"",
            headers={"Content-Type": "application/json"})
        print(f"profiling for {args.seconds}s ...", flush=True)
        try:
            with urllib.request.urlopen(
                    req, timeout=args.seconds + 60) as resp:
                body = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read().decode())["error"]["message"]
            except Exception:  # noqa: BLE001 — non-JSON error body
                msg = str(e)
            print(f"profile failed ({e.code}): {msg}", file=sys.stderr)
            return 1
        except OSError as e:
            print(f"profile request failed: {e}", file=sys.stderr)
            return 1
        print(f"profile written to {body.get('path')} (on the server's "
              "artifacts mount)")
        return 0
    finally:
        if pf is not None:
            pf.stop()


def _fetch_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))


def _fetch_flight(base_url: str, request_id: str) -> dict:
    """One /debug/flight query (serve replica or gateway)."""
    from urllib.parse import quote

    url = f"{base_url.rstrip('/')}/debug/flight"
    if request_id:
        url += f"?request_id={quote(request_id, safe='')}"
    return _fetch_json(url)


def _merged_timeline(sources: List[tuple]) -> List[tuple]:
    """[(label, flight-response)] -> [(ts_us, label, event)] sorted by
    wall-clock ts — one clock-ordered timeline across pods (hosts with
    skewed clocks show as interleaving artifacts, which is exactly what
    an operator needs to SEE rather than have hidden). Events identical
    by (ts, pid, tid, name, dur) dedupe to the first source that
    returned them — one process hosting several apps (tests, colocated
    tiers) shares one ring, and a replica reachable under two names
    must not double every row."""
    merged = []
    seen = set()
    for label, resp in sources:
        for event in resp.get("events", []):
            ts = event.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            key = (ts, event.get("pid"), event.get("tid"),
                   event.get("name"), event.get("dur"))
            if key in seen:
                continue
            seen.add(key)
            merged.append((float(ts), label, event))
    merged.sort(key=lambda x: x[0])
    return merged


def _format_timeline(merged: List[tuple]) -> List[List[str]]:
    """Rows for print_table: offset from the first event, source pod,
    span name, duration, compact args."""
    rows = []
    t0 = merged[0][0] if merged else 0.0
    for ts, label, event in merged:
        args = dict(event.get("args") or {})
        args.pop("request_id", None)
        args.pop("request_ids", None)
        detail = " ".join(f"{k}={v}" for k, v in sorted(args.items()))
        dur = event.get("dur")
        rows.append([
            f"+{(ts - t0) / 1000.0:.1f}ms", label, event.get("name", "?"),
            f"{dur / 1000.0:.1f}ms" if isinstance(dur, (int, float))
            else "-",
            detail[:60] or "-"])
    return rows


def cmd_trace(args) -> int:
    """Merged gateway→replica timeline for one request id: query the
    target's /debug/flight (obs/flight.py — the always-on span ring),
    follow the replica map a gateway returns, and print every pod's
    events for that id in one clock-ordered table
    (docs/observability.md)."""
    rid = args.request_id
    url, pf = _resolve_server_url(
        args, "usage: rbt trace <request-id> servers/<name> | --url URL")
    try:
        sources = []
        try:
            first = _fetch_flight(url, rid)
        except (OSError, ValueError) as e:
            print(f"trace: /debug/flight fetch failed: {e}",
                  file=sys.stderr)
            return 1
        label = f"{first.get('component', '?')}@{first.get('host', '?')}"
        sources.append((label, first))
        # A gateway's response lists its backends: fetch each replica's
        # ring too, so the timeline covers the whole path. The backend
        # map carries pod URLs, which are routable in-cluster (where
        # the gateway pod and CI smoke run) but NOT through a laptop's
        # port-forward to the gateway alone — unreachable replicas
        # degrade to a warning naming the per-replica fallback, never
        # fail the merge.
        unreachable = []
        for name, rurl in sorted((first.get("replicas") or {}).items()):
            try:
                resp = _fetch_flight(rurl, rid)
                sources.append(
                    (f"{resp.get('component', '?')}@"
                     f"{resp.get('host', '?')}/{name}", resp))
            except (OSError, ValueError) as e:
                unreachable.append(name)
                print(f"trace: replica {name} ({rurl}) unreachable "
                      f"({e}); timeline is partial", file=sys.stderr)
        if unreachable:
            print("trace: pod IPs are only routable in-cluster; for the "
                  "replica half of the timeline, port-forward a replica "
                  "and run `rbt trace <request-id> servers/<name>` (or "
                  "--url the replica directly)", file=sys.stderr)
        merged = _merged_timeline(sources)
        if not merged:
            print(f"no flight-recorder events for request id {rid!r} "
                  f"(ring window passed, or the id never served here)")
            return 1
        print(f"request {rid}: {len(merged)} events across "
              f"{len(sources)} pod(s)")
        print_table(_format_timeline(merged),
                    ["TIME", "POD", "EVENT", "DUR", "DETAIL"])
        return 0
    finally:
        if pf is not None:
            pf.stop()


def cmd_incidents(args) -> int:
    """List / fetch incident bundles (obs/incident.py) from a Server
    replica: `rbt incidents servers/<name>` tables the bundles under
    {artifacts}/incidents/; `--fetch NAME` downloads one bundle's full
    JSON locally for offline triage."""
    url, pf = _resolve_server_url(
        args, "usage: rbt incidents servers/<name> [--fetch NAME] "
              "| --url URL")
    try:
        base = url.rstrip("/")
        if args.fetch:
            from urllib.parse import quote

            try:
                bundle = _fetch_json(
                    f"{base}/debug/incidents?name="
                    f"{quote(args.fetch, safe='')}")
            except urllib.error.HTTPError as e:
                print(f"incidents: fetch failed ({e.code})",
                      file=sys.stderr)
                return 1
            except (OSError, ValueError) as e:
                print(f"incidents: fetch failed: {e}", file=sys.stderr)
                return 1
            out_path = args.out or args.fetch
            with open(out_path, "w") as f:
                json.dump(bundle, f, indent=1)
            print(f"wrote {out_path} (reason={bundle.get('reason')}, "
                  f"{len(bundle.get('flight', {}).get('events', []))} "
                  "flight events)")
            return 0
        try:
            listing = _fetch_json(f"{base}/debug/incidents")
        except (OSError, ValueError) as e:
            print(f"incidents: list failed: {e}", file=sys.stderr)
            return 1
        incidents = listing.get("incidents", [])
        if not incidents:
            print("no incident bundles captured")
            return 0
        rows = [[e.get("name", "?"), e.get("reason", "?"),
                 e.get("time", "?"), str(e.get("size_bytes", "?"))]
                for e in incidents]
        print_table(rows, ["BUNDLE", "REASON", "TIME (UTC)", "BYTES"])
        return 0
    finally:
        if pf is not None:
            pf.stop()


def _fetch_exposition(url: str) -> str:
    target = url if url.endswith("/metrics") else url.rstrip("/") + "/metrics"
    with urllib.request.urlopen(target, timeout=10) as resp:
        return resp.read().decode("utf-8", "replace")


def _metric_value(families, name: str, sel: dict, default=None):
    """First sample of `name` whose labelset includes `sel` (mirrored
    fleet series carry extra labels like namespace — subset match)."""
    fam = families.get(name)
    if fam is None:
        return default
    match = set(sel.items())
    for lkey, value in sorted(fam.samples.items()):
        if match <= set(lkey):
            return value
    return default


def _metric_sum(families, name: str, sel: dict, default=None):
    """Sum of every sample of `name` whose labelset includes `sel` (e.g.
    per-device HBM gauges summed across a replica's devices)."""
    fam = families.get(name)
    if fam is None:
        return default
    match = set(sel.items())
    vals = [v for lkey, v in fam.samples.items() if match <= set(lkey)]
    return sum(vals) if vals else default


def _top_hbm(families, sel: dict) -> str:
    """HBM% cell: bytes-in-use / limit across the replica's devices
    (device_memory_* gauges; '-' on CPU replicas, where memory_stats()
    is absent and the series never exists)."""
    in_use = _metric_sum(families, "device_memory_bytes_in_use", sel)
    limit = _metric_sum(families, "device_memory_bytes_limit", sel)
    if in_use is None or not limit:
        return "-"
    return f"{in_use / limit * 100:.0f}%"


def _top_slots(families, sel: dict) -> str:
    """Slot-utilization cell: active/total slots + KV occupancy. A paged
    engine (serve_kv_pages_* series present) renders page occupancy and
    the radix-shared share of the pool (`kv=N% shared=M%`,
    docs/paged-kv.md); dense engines keep the token-occupancy ratio."""
    active = _metric_value(families, "serve_active_slots", sel)
    total = _metric_value(families, "serve_slots_total", sel)
    if active is None or not total:
        return "-"
    cell = f"{active:.0f}/{total:.0f}"
    used = _metric_value(families, "serve_kv_pages_used", sel)
    free = _metric_value(families, "serve_kv_pages_free", sel)
    if used is not None and free is not None and used + free > 0:
        pool = used + free
        shared = _metric_value(families, "serve_kv_pages_shared",
                               sel) or 0
        cell += (f" kv={used / pool * 100:.0f}%"
                 f" shared={shared / pool * 100:.0f}%")
        return cell
    kv = _metric_value(families, "serve_kv_occupancy_ratio", sel)
    if kv is not None:
        cell += f" kv={kv * 100:.0f}%"
    return cell


def _metric_quantile_ms(families, name: str, q: float, sel: dict):
    """Quantile (ms) over the merged histogram labelsets matching `sel`."""
    fam = families.get(name)
    if fam is None:
        return None
    merged = None
    match = set(sel.items())
    for lkey, hist in sorted(fam.histograms.items()):
        if match <= set(lkey):
            merged = hist if merged is None else merged.merged(hist)
    if merged is None or not merged.count:
        return None
    return merged.quantile(q) * 1000.0


def _top_rows_from_metrics(text: str):
    """(header, rows) for `rbt top` from any /metrics body. A controller
    exposition (fleet_scrape_up present) yields one row per scraped
    replica; a single replica's own endpoint yields one local row."""
    from runbooks_tpu.obs.metrics import parse_exposition

    families = parse_exposition(text)
    header = ["WORKLOAD", "REPLICA", "UP", "AGE", "SLO", "HBM", "SLOTS",
              "DETAIL"]
    rows = []
    up_fam = families.get("fleet_scrape_up")
    if up_fam is not None and up_fam.samples:
        for lkey, up in sorted(up_fam.samples.items()):
            lbl = dict(lkey)
            kind = lbl.get("kind", "?")
            name = lbl.get("name", "?")
            # Namespace included: same-named Servers in two namespaces
            # must not blend each other's series in the subset match.
            sel = {"kind": kind, "name": name,
                   "namespace": lbl.get("namespace", "?"),
                   "replica": lbl.get("replica", "?")}
            age = _metric_value(families, "fleet_scrape_age_seconds", sel)
            slo = _metric_value(families, "fleet_slo_violated",
                                {"kind": kind, "name": name,
                                 "namespace": lbl.get("namespace", "?")})
            rows.append([
                f"{kind.lower()}s/{name}", sel["replica"],
                "yes" if up else "NO",
                f"{age:.0f}s" if age is not None else "-",
                ("VIOLATED" if slo else "ok") if slo is not None else "-",
                _top_hbm(families, sel),
                _top_slots(families, sel) if kind == "Server" else "-",
                _top_detail(families, kind, sel) or "-"])
        return header, rows
    # Direct replica endpoint (e.g. `rbt top servers/x` port-forward):
    # one row from the process's own unlabeled series.
    detail = _top_detail(families, "Server", {}) \
        or _top_detail(families, "Model", {})
    rows.append(["local", "-", "yes", "0s", "-", _top_hbm(families, {}),
                 _top_slots(families, {}), detail or "-"])
    return header, rows


def _top_gateway_detail(families, sel: dict) -> str:
    """DETAIL cell for a gateway replica (serve/gateway.py): routed
    volume, affinity hit rate, failover retries, healthy backends."""
    routed = _metric_sum(families, "gateway_requests_total", sel)
    if routed is None:
        return ""
    parts = [f"routed={routed:.0f}"]
    healthy = _metric_value(families, "gateway_replicas_healthy", sel)
    if healthy is not None:
        parts.append(f"backends={healthy:.0f}")
    aff_req = _metric_sum(families, "gateway_affinity_requests_total",
                          sel)
    aff_hit = _metric_sum(families, "gateway_affinity_hits_total", sel)
    if aff_req:
        parts.append(f"affinity={(aff_hit or 0) / aff_req * 100:.0f}%")
    retries = _metric_sum(families, "gateway_retries_total", sel)
    if retries:
        parts.append(f"retries={retries:.0f}")
    p90 = _metric_quantile_ms(families, "gateway_proxy_latency_seconds",
                              0.90, sel)
    if p90 is not None:
        parts.append(f"proxy90={p90:.1f}ms")
    return " ".join(parts)


def _top_detail(families, kind: str, sel: dict) -> str:
    parts = []
    if kind == "Server":
        gw = _top_gateway_detail(families, sel)
        if gw:
            # A gateway pod exports gateway_* instead of engine load; its
            # row reads routing stats where replicas read slots/queue.
            return gw
        slots = _metric_value(families, "serve_active_slots", sel)
        queue = _metric_value(families, "serve_queue_depth", sel)
        qw = _metric_quantile_ms(families, "serve_queue_wait_seconds",
                                 0.90, sel)
        ttft = _metric_quantile_ms(families, "serve_ttft_seconds",
                                   0.99, sel)
        tps = _metric_value(families, "fleet_tokens_per_sec", sel)
        if slots is not None:
            parts.append(f"slots={slots:.0f}")
        if queue is not None:
            parts.append(f"queue={queue:.0f}")
        if qw is not None:
            parts.append(f"qw90={qw:.1f}ms")
        if ttft is not None:
            parts.append(f"ttft99={ttft:.1f}ms")
        if tps is not None:
            parts.append(f"tok/s={tps:g}")
        # Speculative-decoding accept rate (docs/speculative-decoding.md):
        # the serve_spec_* families exist only when speculation is on, so
        # the cell appears exactly for speculative replicas.
        drafted = _metric_value(families, "serve_spec_drafted_total", sel)
        if drafted:
            accepted = _metric_value(families,
                                     "serve_spec_accepted_total", sel) or 0
            parts.append(f"acc={accepted / drafted * 100:.0f}%")
        # Multi-tenant LoRA pool (docs/multi-tenant-lora.md): resident
        # adapters + cumulative loads. The serve_adapter_* families exist
        # only on pooled engines, so the cell appears exactly there.
        resident = _metric_value(families, "serve_adapters_resident", sel)
        if resident is not None:
            loads = _metric_value(families, "serve_adapter_loads_total",
                                  sel)
            cell = f"adapters={resident:.0f}"
            if loads:
                cell += f"/{loads:.0f}ld"
            parts.append(cell)
        # Last-incident age (obs/incident.py): the series exists only
        # once the replica captured a bundle — absence means "never".
        inc_age = _metric_value(families, "serve_incident_age_seconds",
                                sel)
        if inc_age is not None:
            parts.append(f"lastinc={inc_age:.0f}s")
    else:
        step = _metric_value(families, "train_step", sel)
        loss = _metric_value(families, "train_loss", sel)
        goodput = _metric_value(families, "train_goodput_ratio", sel)
        if step is not None:
            parts.append(f"step={step:.0f}")
        if loss is not None:
            parts.append(f"loss={loss:.4g}")
        if goodput is not None:
            parts.append(f"goodput={goodput:g}")
    return " ".join(parts)


def _top_rows_from_crds(client, namespace, kind_filter, name_filter):
    """(header, rows) from CRD status alone (no /metrics reachable):
    .status.telemetry + the SLOViolated condition, as the controller's
    fleet layer last wrote them."""
    header = ["WORKLOAD", "READY", "SLO", "TELEMETRY"]
    rows = []
    for kind in ("Server", "Model"):
        if kind_filter and kind != kind_filter:
            continue
        for obj in client.list(API_VERSION, kind, namespace=namespace):
            if name_filter and ko.name(obj) != name_filter:
                continue
            slo_c = ko.get_condition(obj, "SLOViolated")
            slo = ("-" if slo_c is None else
                   "VIOLATED" if slo_c.get("status") == "True" else "ok")
            rows.append([
                f"{kind.lower()}s/{ko.name(obj)}",
                "True" if ko.deep_get(obj, "status", "ready") else "False",
                slo, telemetry_summary(obj) or "-"])
    return header, rows


def cmd_top(args) -> int:
    """Live per-replica fleet load + SLO view (docs/observability.md).
    Sources, in order: --url (any /metrics endpoint — the controller's
    for the whole fleet), servers/<name> (port-forward to one replica,
    same plumbing as `rbt chat`), or the CRD .status.telemetry the
    controller aggregates."""
    pf = None
    client = None
    url = args.url
    kind_filter = name_filter = None
    if not url and args.scope:
        url, pf = _resolve_server_url(
            args, "usage: rbt top [servers/<name>] [--url URL]")
    elif not url:
        client = make_client(args)
    try:
        while True:
            if url:
                try:
                    header, rows = _top_rows_from_metrics(
                        _fetch_exposition(url))
                except OSError as e:
                    if args.once:
                        print(f"top: metrics fetch failed: {e}",
                              file=sys.stderr)
                        return 1
                    header, rows = ["WORKLOAD"], []
            else:
                header, rows = _top_rows_from_crds(
                    client, args.namespace, kind_filter, name_filter)
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                print(time.strftime("%H:%M:%S"),
                      "fleet top (ctrl-c to exit)")
            print_table(rows or [["(none)"] + [""] * (len(header) - 1)],
                        header)
            if args.once:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        if pf is not None:
            pf.stop()


# ---------------------------------------------------------------------------
# rbt dash — terminal dashboard over the controller's /metrics/history
# ---------------------------------------------------------------------------

_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(values: List[Optional[float]], width: int = 48) -> str:
    """Unicode sparkline over the last `width` points; None (no data in
    that grid cell — staleness gaps, pre-warm cells) renders as '·' so
    gaps stay visible instead of interpolating away."""
    pts = values[-width:]
    nums = [v for v in pts if v is not None]
    if not nums:
        return ""
    lo, hi = min(nums), max(nums)
    out = []
    for v in pts:
        if v is None:
            out.append("·")
        elif hi <= lo:
            out.append(_SPARK_BLOCKS[3])
        else:
            idx = round((v - lo) / (hi - lo) * (len(_SPARK_BLOCKS) - 1))
            out.append(_SPARK_BLOCKS[max(0, min(idx, 7))])
    return "".join(out)


def _fetch_history_series(url: str, names: List[str], sel: dict,
                          since: float, step: float, q=None, agg=None):
    """GET /metrics/history for `names`; {name: series-entry} or {} when
    the endpoint has nothing for them."""
    from urllib.parse import urlencode

    params = {"series": ",".join(names), "since": since, "step": step}
    if q is not None:
        params["q"] = q
    if agg is not None:
        params["agg"] = agg
    params.update(sel)
    body = _fetch_json(url.rstrip("/") + "/metrics/history?"
                       + urlencode(params))
    return {s["name"]: s for s in body.get("series", [])}


# (label, series, q, agg, scale, unit). None series = computed panel.
_DASH_PANELS = (
    ("ttft p99", "serve_ttft_seconds", 0.99, None, 1000.0, "ms"),
    ("queue-wait p90", "serve_queue_wait_seconds", 0.90, None, 1000.0,
     "ms"),
    ("tokens/sec", "fleet_tokens_per_sec", None, "sum", 1.0, "tok/s"),
    ("kv occupancy", "serve_kv_occupancy_ratio", None, "avg", 100.0, "%"),
    ("hbm headroom", "device_memory_headroom_bytes", None, "sum",
     1.0 / 2**30, "GiB"),
    ("error rate", None, None, None, 1.0, "%"),
    ("replicas up", "fleet_scrape_up", None, "sum", 1.0, ""),
    ("burn rate 5m", "controller_slo_burn_rate", None, "max", 1.0, "x"),
)


def _dash_panel_values(url: str, sel: dict, since: float,
                       step: float) -> List[tuple]:
    """[(label, unit, values)] per panel — values aligned to the history
    grid, scaled to display units."""
    out = []
    for label, series, q, agg, scale, unit in _DASH_PANELS:
        if series is None:
            # error rate %: failed-rate / request-rate, pointwise over
            # the same grid (both counters arrive as per-second rates).
            fetched = _fetch_history_series(
                url, ["serve_requests_total",
                      "serve_requests_failed_total"], sel, since, step)
            total = (fetched.get("serve_requests_total")
                     or {}).get("points", [])
            failed = (fetched.get("serve_requests_failed_total")
                      or {}).get("points", [])
            fmap = {t: v for t, v in failed}
            values = [None if v is None or not v
                      else min(100.0, (fmap.get(t) or 0.0) / v * 100.0)
                      for t, v in total]
        else:
            fsel = dict(sel)
            if series == "controller_slo_burn_rate":
                fsel["window"] = "5m"
            elif series == "fleet_scrape_up":
                # Serving replicas only: gateway pods scrape into the
                # same workload key but are the data plane, not
                # capacity (docs/serving-dataplane.md).
                fsel["role"] = "run"
            entry = _fetch_history_series(url, [series], fsel, since,
                                          step, q=q, agg=agg).get(series)
            values = [None if v is None else v * scale
                      for _, v in (entry or {}).get("points", [])]
        out.append((label, unit, values))
    return out


def _dash_rows(panels: List[tuple], width: int) -> List[List[str]]:
    rows = []
    for label, unit, values in panels:
        nums = [v for v in values if v is not None]
        if not nums:
            rows.append([label, "(no data)", "-", ""])
            continue
        cur = next(v for v in reversed(values) if v is not None)
        rows.append([label, _sparkline(values, width),
                     f"{cur:.4g}{unit}",
                     f"min {min(nums):.4g} max {max(nums):.4g}"])
    return rows


def cmd_dash(args) -> int:
    """Live terminal dashboard from the controller's fleet history
    (docs/observability.md "Fleet history"): unicode sparklines for the
    serving trends — TTFT p99, queue-wait p90, tok/s, KV occupancy, HBM
    headroom, error rate, replica count, SLO burn rate — without
    deploying Prometheus/Grafana. Point --url at the controller's
    metrics endpoint (or export RBT_CONTROLLER_URL); an optional
    servers/<name> scope filters to one Server's series."""
    url = args.url or os.environ.get("RBT_CONTROLLER_URL")
    if not url:
        raise SystemExit(
            "usage: rbt dash [servers/<name>] --url CONTROLLER_URL\n"
            "(the controller metrics endpoint serves /metrics/history — "
            "port-forward it, e.g. kubectl port-forward deploy/"
            "controller-manager 8080:8080 — or export "
            "RBT_CONTROLLER_URL)")
    scope_label = "fleet"
    sel = {}
    if args.scope:
        kind, name = parse_scope(args.scope)
        if kind != "Server" or not name:
            raise SystemExit(
                "usage: rbt dash [servers/<name>] [--url URL]")
        sel = {"name": name, "namespace": args.namespace}
        scope_label = f"servers/{name}"
    try:
        idx = _fetch_json(url.rstrip("/") + "/metrics/history")
    except (OSError, ValueError) as e:
        print(f"dash: history endpoint unreachable at {url}: {e}",
              file=sys.stderr)
        return 1
    cfg = idx.get("config", {})
    window = args.window or cfg.get("raw_retention_s", 900.0)
    # One grid cell per sparkline column by default, so the TREND spans
    # the whole advertised window (a finer step would silently render
    # only its newest `width` cells).
    step = args.step or max(cfg.get("raw_step_s", 10.0),
                            window / max(args.width, 1))
    header = ["PANEL", "TREND", "NOW", "RANGE"]
    try:
        while True:
            try:
                panels = _dash_panel_values(url, sel, window, step)
            except (OSError, ValueError) as e:
                if args.once:
                    print(f"dash: history fetch failed: {e}",
                          file=sys.stderr)
                    return 1
                panels = []
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(f"{time.strftime('%H:%M:%S')} {scope_label} dashboard "
                  f"(step {step:g}s, window {window:g}s"
                  + (")" if args.once else "; ctrl-c to exit)"))
            print_table(_dash_rows(panels, args.width) or
                        [["(none)", "", "", ""]], header)
            if args.once:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_logs(args) -> int:
    """Stream logs of an object's workload pods (the reference TUI streams
    these inline — internal/tui/pods.go; here it shells to kubectl with the
    same role/kind labels the reconcilers stamp on pods)."""
    kind, name = parse_scope(args.scope)
    if not kind or not name:
        raise SystemExit("usage: rbt logs <kind>/<name> [--role build|run]")
    selector = f"{kind.lower()}={name},role={args.role}"
    # kubectl defaults: --tail=10 with selectors (silent truncation) and a
    # 5-stream cap on -f (breaks multi-host slices); lift both.
    cmd = ["kubectl", "logs", "-n", args.namespace, "-l", selector,
           "--all-containers", "--prefix", f"--tail={args.tail}",
           "--max-log-requests", "64"]
    if args.follow:
        cmd.append("-f")
    try:
        return subprocess.call(cmd)
    except FileNotFoundError:
        print("kubectl not found on PATH", file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        return 0


def cmd_suspend(args) -> int:
    client = make_client(args)
    kind, name = parse_scope(args.scope)
    if kind != "Notebook" or not name:
        raise SystemExit("usage: rbt suspend notebooks/<name>")
    # Dedicated field manager owning only spec.suspend — applying with the
    # manifest's manager would SSA-prune the rest of the spec.
    client.apply({"apiVersion": API_VERSION, "kind": "Notebook",
                  "metadata": {"name": name, "namespace": args.namespace},
                  "spec": {"suspend": True}}, "rbt-cli-suspend")
    print(f"notebooks/{name} suspended")
    return 0


def cmd_check(args) -> int:
    """Static program & concurrency audit (docs/static-analysis.md):
    AST lint for the recurring concurrency/precision defect classes plus
    an abstract-trace audit of the registered hot programs — zero XLA
    backend compiles, so it runs in CI in seconds (`make check`)."""
    from runbooks_tpu.analysis.check import run_check

    report = run_check(programs=not args.no_programs,
                       lint=not args.no_lint,
                       write_baseline=args.write_baseline)
    if args.json:
        print(json.dumps({
            "active": [f.as_dict() for f in report.active],
            "suppressed": [f.as_dict() for f in report.suppressed],
            "stale": [dataclasses.asdict(s) for s in report.stale],
            "census": report.census,
            "compiles": report.compiles,
            "monitoring": report.monitoring,
            "seconds": round(report.seconds, 2),
        }, indent=2))
    else:
        for f in report.active:
            print(f.render())
        for s in report.stale:
            print(f"stale suppression: [{s.rule}] {s.path} "
                  f"({s.reason})")
        programs = ((report.census or {}).get("programs", [])
                    if report.census else [])
        compiles = (f"{report.compiles} backend compiles"
                    if report.monitoring
                    else "compiles UNVERIFIED (no jax.monitoring)")
        print(f"rbt check: {len(report.active)} active, "
              f"{len(report.suppressed)} suppressed, "
              f"{len(report.stale)} stale; "
              f"{len(programs)} programs audited, "
              f"{compiles}, "
              f"{report.seconds:.1f}s")
        if args.write_baseline and not args.no_programs:
            print("program baseline regenerated "
                  "(config/program_baseline.json); review and commit it")
    rc = report.exit_code(strict=args.strict)
    if args.strict and args.budget_s and report.seconds > args.budget_s:
        print(f"rbt check: wall time {report.seconds:.1f}s exceeded the "
              f"--budget-s {args.budget_s:.0f}s budget — the audit must "
              "stay cheap enough to gate every CI run", file=sys.stderr)
        rc = rc or 5
    return rc


def _inprocess_port_forward(client, namespace: str, pod: str,
                            local: int, remote: int) -> Optional[int]:
    """Pod port-forward over the Kubernetes websocket subresource — no
    kubectl needed (reference does the equivalent in-process over SPDY:
    internal/client/port_forward.go). Returns an exit code, or None when
    the client has no real KubeConfig (fake/demo mode) so the caller can
    fall back to kubectl."""
    cfg = getattr(client, "config", None)
    if cfg is None:
        return None
    from runbooks_tpu.k8s.portforward import PortForwarder

    pf = PortForwarder(
        cfg, namespace, pod, local, remote,
        on_ready=lambda p: print(
            f"forwarding localhost:{p} -> {pod}:{remote} (ctrl-c to stop)"))
    try:
        pf.serve()
    except KeyboardInterrupt:
        return 0
    except ConnectionError as e:
        print(f"port-forward failed: {e}", file=sys.stderr)
        return 1
    except OSError as e:  # e.g. local port already in use
        print(f"port-forward could not listen on localhost:{local}: {e}",
              file=sys.stderr)
        return 1
    finally:
        pf.stop()
    return 0


def _server_run_pod(client, namespace: str, name: str) -> Optional[str]:
    """A running pod of a Server's deployment (labels server=name,
    role=run) — the reference's serve flow watches for the same pod
    (internal/tui/serve.go:203-228)."""
    for pod in client.list("v1", "Pod", namespace=namespace,
                           label_selector={"server": name, "role": "run"}):
        if ko.deep_get(pod, "status", "phase", default="") == "Running":
            return ko.name(pod)
    return None


def _kubectl_port_forward(target: str, local: int, remote: int,
                          namespace: str) -> int:
    cmd = ["kubectl", "port-forward", "-n", namespace, target,
           f"{local}:{remote}"]
    backoff = 1.0
    for attempt in range(6):
        try:
            rc = subprocess.call(cmd)
        except FileNotFoundError:
            print("kubectl not found on PATH (needed for port-forward)",
                  file=sys.stderr)
            return 1
        except KeyboardInterrupt:
            return 0
        if rc == 0:
            return 0
        print(f"port-forward exited ({rc}); retrying in {backoff:.0f}s",
              file=sys.stderr)
        time.sleep(backoff)
        backoff = min(backoff * 2, 30)
    print(f"port-forward to {target} kept failing; giving up",
          file=sys.stderr)
    return 1


# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="rbt",
                                description="runbooks-tpu dev CLI")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--kubeconfig")
    p.add_argument("--plain", action="store_true",
                   help="plain line output instead of the full-screen TUI")
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp, filename=True):
        if filename:
            sp.add_argument("-f", "--filename", default=".")
        sp.add_argument("--timeout", type=float, default=720.0)
        sp.add_argument("--build", help="build-context dir to upload")

    sp = sub.add_parser("apply", help="apply manifests (with upload builds)")
    common(sp)
    sp.add_argument("--wait", action="store_true")
    sp.set_defaults(func=cmd_apply)

    sp = sub.add_parser("get", help="list resources with conditions")
    sp.add_argument("scope", nargs="?", default="")
    sp.add_argument("-w", "--watch", action="store_true",
                    help="live-updating table")
    sp.set_defaults(func=cmd_get)

    sp = sub.add_parser("delete", help="delete resources")
    sp.add_argument("scope", nargs="?", default="")
    sp.add_argument("-f", "--filename")
    sp.set_defaults(func=cmd_delete)

    sp = sub.add_parser("run", help="create-with-upload and wait")
    common(sp)
    group = sp.add_mutually_exclusive_group()
    group.add_argument("-i", "--increment", action="store_true")
    group.add_argument("-r", "--replace", action="store_true")
    sp.set_defaults(func=cmd_run)

    sp = sub.add_parser("serve", help="port-forward a ready Server")
    sp.add_argument("scope")
    sp.add_argument("--port", type=int, default=8000)
    sp.add_argument("--timeout", type=float, default=720.0)
    sp.set_defaults(func=cmd_serve)

    sp = sub.add_parser("notebook", help="notebook dev loop")
    common(sp)
    sp.add_argument("--no-sync", dest="sync", action="store_false")
    sp.add_argument("-r", "--resume", metavar="NAME",
                    help="reattach to an existing notebook (no upload)")
    sp.set_defaults(func=cmd_notebook)

    sp = sub.add_parser("chat", help="interactive chat with a Server")
    sp.add_argument("scope", nargs="?", default="")
    sp.add_argument("--url", help="server URL (skips port-forward)")
    sp.add_argument("--system", help="system prompt")
    sp.add_argument("--max-tokens", type=int, default=256)
    sp.add_argument("--temperature", type=float, default=0.7)
    sp.add_argument("--timeout", type=float, default=720.0)
    sp.set_defaults(func=cmd_chat)

    sp = sub.add_parser("profile",
                        help="capture an on-demand TPU profile from a "
                             "Server")
    sp.add_argument("scope", nargs="?", default="")
    sp.add_argument("--url", help="server URL (skips port-forward)")
    sp.add_argument("--seconds", type=float, default=5.0,
                    help="capture window (default 5)")
    sp.add_argument("--timeout", type=float, default=720.0)
    sp.set_defaults(func=cmd_profile)

    sp = sub.add_parser("top",
                        help="live per-replica fleet load + SLO view")
    sp.add_argument("scope", nargs="?", default="",
                    help="servers/<name> to port-forward one replica")
    sp.add_argument("--url",
                    help="a /metrics endpoint (the controller's for the "
                         "fleet view; skips port-forward)")
    sp.add_argument("--interval", type=float, default=2.0,
                    help="refresh interval seconds (default 2)")
    sp.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    sp.add_argument("--timeout", type=float, default=720.0)
    sp.set_defaults(func=cmd_top)

    sp = sub.add_parser("dash",
                        help="live sparkline dashboard from the "
                             "controller's fleet history")
    sp.add_argument("scope", nargs="?", default="",
                    help="servers/<name> to scope the panels to one "
                         "Server")
    sp.add_argument("--url",
                    help="controller metrics URL (serves "
                         "/metrics/history); or env RBT_CONTROLLER_URL")
    sp.add_argument("--interval", type=float, default=2.0,
                    help="refresh interval seconds (default 2)")
    sp.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (scripting)")
    sp.add_argument("--window", type=float,
                    help="lookback seconds (default: raw retention)")
    sp.add_argument("--step", type=float,
                    help="grid step seconds (default: raw scrape step)")
    sp.add_argument("--width", type=int, default=48,
                    help="sparkline width in cells (default 48)")
    sp.set_defaults(func=cmd_dash)

    sp = sub.add_parser(
        "trace",
        help="merged gateway→replica timeline for one request id")
    sp.add_argument("request_id")
    sp.add_argument("scope", nargs="?", default="",
                    help="servers/<name> to port-forward (a gateway "
                         "--url merges its replicas too)")
    sp.add_argument("--url", help="gateway or replica URL (skips "
                                  "port-forward)")
    sp.add_argument("--timeout", type=float, default=720.0)
    sp.set_defaults(func=cmd_trace)

    sp = sub.add_parser("incidents",
                        help="list/fetch incident bundles from a Server")
    sp.add_argument("scope", nargs="?", default="")
    sp.add_argument("--url", help="server URL (skips port-forward)")
    sp.add_argument("--fetch", metavar="NAME",
                    help="download one bundle's JSON")
    sp.add_argument("--out", help="local path for --fetch (default: "
                                  "the bundle name)")
    sp.add_argument("--timeout", type=float, default=720.0)
    sp.set_defaults(func=cmd_incidents)

    sp = sub.add_parser("logs", help="stream workload pod logs")
    sp.add_argument("scope")
    sp.add_argument("--role", default="run", choices=["run", "build"])
    sp.add_argument("-f", "--follow", action="store_true")
    sp.add_argument("--tail", type=int, default=-1,
                    help="lines per container (-1 = all)")
    sp.set_defaults(func=cmd_logs)

    sp = sub.add_parser("suspend", help="suspend a notebook")
    sp.add_argument("scope")
    sp.set_defaults(func=cmd_suspend)

    sp = sub.add_parser(
        "check",
        help="static program & concurrency audit (lint + jaxpr contracts)")
    sp.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline suppressions, any "
                         "backend compile during the audit, and a blown "
                         "--budget-s")
    sp.add_argument("--write-baseline", action="store_true",
                    help="regenerate config/program_baseline.json from "
                         "the current program census instead of diffing "
                         "against it")
    sp.add_argument("--no-programs", action="store_true",
                    help="skip the jaxpr program-contract side")
    sp.add_argument("--no-lint", action="store_true",
                    help="skip the AST lint side")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    sp.add_argument("--budget-s", type=float, default=0.0,
                    help="with --strict: fail if the audit takes longer "
                         "than this many seconds (CI wall-time budget)")
    sp.set_defaults(func=cmd_check)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.func(args)
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    raise SystemExit(main())
