"""In-memory fake Kubernetes API — the envtest analog.

The reference tests its reconcilers against a real envtest apiserver with no
kubelet, manually patching Job/Pod status (reference: internal/controller/
main_test.go fakeJobComplete/fakePodReady). This fake plays the same role
with zero external processes: it implements the same ``ApiClient`` interface
the real REST client exposes, with resourceVersion/uid/generation
bookkeeping, label-selector lists, server-side-apply-style merges, and watch
streams — enough fidelity for every controller test to run hermetically.
"""

from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

from runbooks_tpu.k8s import objects as ko

Obj = Dict[str, Any]
Key = Tuple[str, str, str, str]  # api_version, kind, namespace, name


class Conflict(Exception):
    pass


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


class ApiServerError(Exception):
    """Non-404/409 HTTP status from the apiserver (e.g. 500/503 during a
    rolling restart). Typed — not a bare RuntimeError — so the manager's
    watch loop can classify it as connectivity-shaped and retry instead of
    counting it toward its crash-after-N-identical-bugs heuristic."""

    def __init__(self, message: str, code: int = 0):
        super().__init__(message)
        self.code = code


def _key(api_version: str, kind: str, namespace: str, name: str) -> Key:
    return (api_version, kind, namespace, name)


def _matches_selector(obj: Obj, selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    lbls = ko.labels(obj)
    return all(lbls.get(k) == v for k, v in selector.items())


def _merge(dst: Any, src: Any) -> Any:
    """Server-side-apply-flavored merge: dicts merge recursively, None
    deletes a key, everything else replaces."""
    if isinstance(dst, dict) and isinstance(src, dict):
        out = dict(dst)
        for k, v in src.items():
            if v is None:
                out.pop(k, None)
            else:
                out[k] = _merge(out.get(k), v)
        return out
    return src


class Subscription:
    """A watch stream: iterate or poll events ("ADDED"/"MODIFIED"/"DELETED").

    close() ends the stream: puts become no-ops (the queue stops growing)
    and the wire client's reader thread exits its reconnect loop — without
    it, a manager re-subscribing after apiserver failure would leak one
    forever-reconnecting thread plus an undrained queue per hiccup."""

    def __init__(self):
        self.q: "queue.Queue[Tuple[str, Obj]]" = queue.Queue()
        self.closed = threading.Event()
        self._closers: List = []
        # The wire client parks its reader thread here so close(join=True)
        # can wait for it to actually exit (a closed-but-still-winding-down
        # reader printing "reconnecting" after pytest teardown is noise
        # that reads like a hang).
        self.reader_thread: "threading.Thread | None" = None

    def put(self, event: str, obj: Obj) -> None:
        if not self.closed.is_set():
            self.q.put((event, ko.clone(obj)))

    def add_closer(self, fn) -> None:
        """Register a callback run at close() — the wire reader registers
        its in-flight HTTP response so close() interrupts a blocked body
        read instead of waiting out the socket timeout."""
        if self.closed.is_set():
            try:
                fn()
            except Exception:  # noqa: BLE001
                pass
            return
        self._closers.append(fn)

    def remove_closer(self, fn) -> None:
        try:
            self._closers.remove(fn)
        except ValueError:
            pass

    def close(self, join: bool = False, timeout: float = 3.0) -> None:
        self.closed.set()
        closers, self._closers = self._closers, []
        for fn in closers:
            try:
                fn()
            except Exception:  # noqa: BLE001
                pass
        if join and self.reader_thread is not None \
                and self.reader_thread is not threading.current_thread():
            self.reader_thread.join(timeout=timeout)

    def poll(self, timeout: float = 0.0):
        try:
            return self.q.get(timeout=timeout) if timeout else self.q.get_nowait()
        except queue.Empty:
            return None


class FakeCluster:
    """Thread-safe in-memory object store implementing the ApiClient shape."""

    def __init__(self):
        self._objs: Dict[Key, Obj] = {}
        self._lock = threading.RLock()
        self._rv = itertools.count(1)
        self._uid = itertools.count(1)
        self._subs: List[Tuple[Optional[str], Optional[str], Subscription]] = []
        self._pod_logs: Dict[Tuple[str, str], List[str]] = {}

    # -- reads ---------------------------------------------------------

    def get(self, api_version: str, kind: str, namespace: str,
            name: str) -> Optional[Obj]:
        with self._lock:
            obj = self._objs.get(_key(api_version, kind, namespace, name))
            return ko.clone(obj) if obj else None

    def list(self, api_version: str, kind: str,
             namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[Obj]:
        with self._lock:
            out = []
            for (av, k, ns, _), obj in self._objs.items():
                if av == api_version and k == kind and \
                        (namespace is None or ns == namespace) and \
                        _matches_selector(obj, label_selector):
                    out.append(ko.clone(obj))
            return out

    # -- writes --------------------------------------------------------

    def create(self, obj: Obj) -> Obj:
        with self._lock:
            k = (ko.api_version(obj), ko.kind(obj), ko.namespace(obj),
                 ko.name(obj))
            if k in self._objs:
                raise AlreadyExists(f"{k} already exists")
            stored = ko.clone(obj)
            meta = stored.setdefault("metadata", {})
            meta.setdefault("namespace", "default")
            meta["uid"] = f"uid-{next(self._uid)}"
            meta["generation"] = 1
            meta["resourceVersion"] = str(next(self._rv))
            self._objs[k] = stored
            self._notify("ADDED", stored)
            return ko.clone(stored)

    def update(self, obj: Obj) -> Obj:
        """Full replace of spec/metadata (status preserved)."""
        with self._lock:
            k = (ko.api_version(obj), ko.kind(obj), ko.namespace(obj),
                 ko.name(obj))
            cur = self._objs.get(k)
            if cur is None:
                raise NotFound(str(k))
            rv = ko.deep_get(obj, "metadata", "resourceVersion")
            if rv is not None and rv != cur["metadata"]["resourceVersion"]:
                raise Conflict(f"resourceVersion mismatch for {k}")
            stored = ko.clone(obj)
            stored.setdefault("metadata", {})
            stored["metadata"]["uid"] = cur["metadata"]["uid"]
            if stored.get("spec") != cur.get("spec"):
                stored["metadata"]["generation"] = \
                    cur["metadata"].get("generation", 1) + 1
            else:
                stored["metadata"]["generation"] = \
                    cur["metadata"].get("generation", 1)
            stored["metadata"]["resourceVersion"] = str(next(self._rv))
            stored.setdefault("status", cur.get("status", {}))
            self._objs[k] = stored
            self._notify("MODIFIED", stored)
            return ko.clone(stored)

    def apply(self, patch: Obj, field_manager: str = "") -> Obj:
        """Server-side-apply style create-or-merge."""
        with self._lock:
            k = (ko.api_version(patch), ko.kind(patch), ko.namespace(patch),
                 ko.name(patch))
            cur = self._objs.get(k)
            if cur is None:
                return self.create(patch)
            merged = _merge(cur, {kk: vv for kk, vv in patch.items()
                                  if kk != "status"})
            merged["metadata"]["uid"] = cur["metadata"]["uid"]
            merged["metadata"]["resourceVersion"] = \
                cur["metadata"]["resourceVersion"]
            if merged.get("spec") != cur.get("spec"):
                merged["metadata"]["generation"] = \
                    cur["metadata"].get("generation", 1) + 1
            merged["metadata"]["resourceVersion"] = str(next(self._rv))
            self._objs[k] = merged
            self._notify("MODIFIED", merged)
            return ko.clone(merged)

    def update_status(self, obj: Obj) -> Obj:
        with self._lock:
            k = (ko.api_version(obj), ko.kind(obj), ko.namespace(obj),
                 ko.name(obj))
            cur = self._objs.get(k)
            if cur is None:
                raise NotFound(str(k))
            # Real apiservers 409 a status PUT carrying a stale
            # resourceVersion; matching that here keeps reconcilers honest
            # (a previous fake that skipped this check masked exactly that
            # bug class — write-then-stale-status-write).
            rv = ko.deep_get(obj, "metadata", "resourceVersion")
            if rv is not None and rv != cur["metadata"]["resourceVersion"]:
                raise Conflict(f"status resourceVersion mismatch for {k}")
            cur["status"] = ko.clone(obj.get("status", {}))
            cur["metadata"]["resourceVersion"] = str(next(self._rv))
            self._notify("MODIFIED", cur)
            return ko.clone(cur)

    def delete(self, api_version: str, kind: str, namespace: str,
               name: str) -> bool:
        with self._lock:
            obj = self._objs.pop(_key(api_version, kind, namespace, name),
                                 None)
            if obj is not None:
                self._notify("DELETED", obj)
            return obj is not None

    # -- watches -------------------------------------------------------

    def watch(self, api_version: Optional[str] = None,
              kind: Optional[str] = None) -> Subscription:
        sub = Subscription()
        with self._lock:
            self._subs.append((api_version, kind, sub))
            # Prime with existing objects (watch-from-now + initial list).
            for (av, k, _, _), obj in self._objs.items():
                if (api_version is None or av == api_version) and \
                        (kind is None or k == kind):
                    sub.put("ADDED", obj)
        return sub

    def unwatch(self, sub: Subscription) -> None:
        """Deregister a watch (long-lived servers like the HTTP fake must
        drop per-connection subscriptions or _notify fans out to an
        ever-growing dead list)."""
        with self._lock:
            self._subs = [(av, k, s) for (av, k, s) in self._subs
                          if s is not sub]

    def _notify(self, event: str, obj: Obj) -> None:
        # Prune closed subscriptions as a side effect: callers close() subs
        # without necessarily unwatch()ing (the manager's error-path
        # re-subscribe), and dead entries must not accumulate.
        live = [(av, k, s) for (av, k, s) in self._subs
                if not s.closed.is_set()]
        if len(live) != len(self._subs):
            self._subs = live
        for av, k, sub in live:
            if (av is None or av == ko.api_version(obj)) and \
                    (k is None or k == ko.kind(obj)):
                sub.put(event, obj)

    # -- pod logs ------------------------------------------------------

    def pod_logs(self, namespace: str, name: str,
                 container: Optional[str] = None, follow: bool = False,
                 tail_lines: Optional[int] = None):
        """Yield log lines recorded via set_pod_logs (kubelet stand-in for
        TUI/log-streaming tests)."""
        with self._lock:
            lines = list(self._pod_logs.get((namespace, name), []))
        if tail_lines is not None:
            lines = lines[-tail_lines:]
        yield from lines

    def set_pod_logs(self, namespace: str, name: str, text: str) -> None:
        with self._lock:
            self._pod_logs.setdefault((namespace, name), []).extend(
                text.splitlines())

    # -- test helpers (fakeJobComplete / fakePodReady analogs) ---------

    def mark_job_complete(self, namespace: str, name: str,
                          failed: bool = False) -> None:
        job = self.get("batch/v1", "Job", namespace, name)
        assert job is not None, f"no job {namespace}/{name}"
        cond = {"type": "Failed" if failed else "Complete", "status": "True"}
        job.setdefault("status", {})["conditions"] = [cond]
        if not failed:
            job["status"]["succeeded"] = 1
        self.update_status(job)

    def mark_pod_ready(self, namespace: str, name: str) -> None:
        pod = self.get("v1", "Pod", namespace, name)
        assert pod is not None, f"no pod {namespace}/{name}"
        pod.setdefault("status", {})["phase"] = "Running"
        pod["status"]["conditions"] = [{"type": "Ready", "status": "True"}]
        self.update_status(pod)

    def mark_deployment_ready(self, namespace: str, name: str,
                              replicas: int = 1) -> None:
        dep = self.get("apps/v1", "Deployment", namespace, name)
        assert dep is not None, f"no deployment {namespace}/{name}"
        dep.setdefault("status", {})["readyReplicas"] = replicas
        dep["status"]["replicas"] = replicas
        self.update_status(dep)
