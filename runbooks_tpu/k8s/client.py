"""Real Kubernetes REST client (stdlib HTTP, no external k8s SDK).

Implements the same ApiClient interface as k8s.fake.FakeCluster, so the
controller manager and CLI run unchanged against a live cluster or the fake.
(Reference analog: internal/client/client.go's RESTMapper-based dynamic
client + SSA apply with a field manager.)

Auth: in-cluster (service-account token + CA) or a kubeconfig
(current-context; token, client-cert, or insecure). Watches stream chunked
JSON lines into the same Subscription type the fake uses.
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import tempfile
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

import yaml

from runbooks_tpu.k8s import objects as ko
from runbooks_tpu.k8s.fake import (
    AlreadyExists,
    ApiServerError,
    Conflict,
    NotFound,
    Subscription,
)

# kind -> plural for the resources this framework touches.
PLURALS = {
    "Model": "models", "Dataset": "datasets", "Server": "servers",
    "Notebook": "notebooks", "Pod": "pods", "Service": "services",
    "ConfigMap": "configmaps", "Secret": "secrets",
    "ServiceAccount": "serviceaccounts", "Job": "jobs",
    "Deployment": "deployments", "Namespace": "namespaces",
    "CustomResourceDefinition": "customresourcedefinitions",
}


CLUSTER_SCOPED = {"Namespace", "CustomResourceDefinition", "ClusterRole",
                  "ClusterRoleBinding", "Node", "PersistentVolume"}


def plural(kind: str) -> str:
    return PLURALS.get(kind, kind.lower() + "s")


class KubeConfig:
    def __init__(self, server: str, ssl_ctx: ssl.SSLContext,
                 headers: Dict[str, str]):
        self.server = server.rstrip("/")
        self.ssl_ctx = ssl_ctx
        self.headers = headers

    @classmethod
    def in_cluster(cls) -> "KubeConfig":
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        sa = "/var/run/secrets/kubernetes.io/serviceaccount"
        with open(f"{sa}/token") as f:
            token = f.read().strip()
        ctx = ssl.create_default_context(cafile=f"{sa}/ca.crt")
        return cls(f"https://{host}:{port}", ctx,
                   {"Authorization": f"Bearer {token}"})

    @classmethod
    def from_kubeconfig(cls, path: Optional[str] = None,
                        context: Optional[str] = None) -> "KubeConfig":
        path = path or os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config"))
        with open(path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = context or cfg.get("current-context")
        ctx_entry = next(c["context"] for c in cfg["contexts"]
                         if c["name"] == ctx_name)
        cluster = next(c["cluster"] for c in cfg["clusters"]
                       if c["name"] == ctx_entry["cluster"])
        user = next(u["user"] for u in cfg["users"]
                    if u["name"] == ctx_entry["user"])

        if cluster.get("insecure-skip-tls-verify"):
            ssl_ctx = ssl._create_unverified_context()  # noqa: S323 — opt-in
        else:
            ssl_ctx = ssl.create_default_context()
            ca_data = cluster.get("certificate-authority-data")
            if ca_data:
                ssl_ctx.load_verify_locations(
                    cadata=base64.b64decode(ca_data).decode())
            elif cluster.get("certificate-authority"):
                ssl_ctx.load_verify_locations(
                    cafile=cluster["certificate-authority"])

        headers: Dict[str, str] = {}
        if user.get("token"):
            headers["Authorization"] = f"Bearer {user['token']}"
        elif user.get("client-certificate-data"):
            cert = base64.b64decode(user["client-certificate-data"])
            key = base64.b64decode(user["client-key-data"])
            cert_file = tempfile.NamedTemporaryFile(delete=False,
                                                    suffix=".pem")
            try:
                cert_file.write(cert + b"\n" + key)
                cert_file.close()
                ssl_ctx.load_cert_chain(cert_file.name)
            finally:
                # Never leave decoded key material on disk.
                os.unlink(cert_file.name)
        return cls(cluster["server"], ssl_ctx, headers)

    @classmethod
    def auto(cls) -> "KubeConfig":
        if os.environ.get("KUBERNETES_SERVICE_HOST"):
            return cls.in_cluster()
        return cls.from_kubeconfig()


class K8sClient:
    """Synchronous ApiClient over the Kubernetes REST API."""

    def __init__(self, config: Optional[KubeConfig] = None,
                 field_manager: str = "runbooks-tpu"):
        self.config = config or KubeConfig.auto()
        self.field_manager = field_manager

    # -- plumbing ------------------------------------------------------

    def _base_path(self, api_version: str) -> str:
        if "/" in api_version:
            return f"/apis/{api_version}"
        return f"/api/{api_version}"

    def _url(self, api_version: str, kind: str, namespace: Optional[str],
             name: Optional[str] = None, subresource: str = "",
             query: str = "") -> str:
        parts = [self.config.server, self._base_path(api_version)]
        if namespace and kind not in CLUSTER_SCOPED:
            parts.append(f"/namespaces/{namespace}")
        parts.append(f"/{plural(kind)}")
        if name:
            parts.append(f"/{name}")
        if subresource:
            parts.append(f"/{subresource}")
        url = "".join(parts)
        return url + (f"?{query}" if query else "")

    def _request(self, method: str, url: str, body: Optional[dict] = None,
                 content_type: str = "application/json") -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method,
                                     headers={
                                         **self.config.headers,
                                         "Content-Type": content_type,
                                         "Accept": "application/json",
                                     })
        try:
            with urllib.request.urlopen(
                    req, context=self.config.ssl_ctx, timeout=30) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            if e.code == 404:
                raise NotFound(detail)
            if e.code == 409:
                if "AlreadyExists" in detail:
                    raise AlreadyExists(detail)
                raise Conflict(detail)
            raise ApiServerError(f"{method} {url} -> {e.code}: {detail}",
                                 code=e.code)

    # -- ApiClient interface -------------------------------------------

    def get(self, api_version: str, kind: str, namespace: str,
            name: str) -> Optional[dict]:
        try:
            return self._request(
                "GET", self._url(api_version, kind, namespace, name))
        except NotFound:
            return None

    def list(self, api_version: str, kind: str,
             namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[dict]:
        query = ""
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in label_selector.items())
            query = f"labelSelector={urllib.request.quote(sel)}"
        resp = self._request(
            "GET", self._url(api_version, kind, namespace, query=query))
        items = resp.get("items", [])
        for item in items:  # lists omit apiVersion/kind on items
            item.setdefault("apiVersion", api_version)
            item.setdefault("kind", kind)
        return items

    def create(self, obj: dict) -> dict:
        return self._request(
            "POST",
            self._url(ko.api_version(obj), ko.kind(obj), ko.namespace(obj)),
            obj)

    def update(self, obj: dict) -> dict:
        return self._request(
            "PUT",
            self._url(ko.api_version(obj), ko.kind(obj), ko.namespace(obj),
                      ko.name(obj)),
            obj)

    def apply(self, obj: dict, field_manager: str = "") -> dict:
        fm = field_manager or self.field_manager
        query = f"fieldManager={fm}&force=true"
        return self._request(
            "PATCH",
            self._url(ko.api_version(obj), ko.kind(obj), ko.namespace(obj),
                      ko.name(obj), query=query),
            obj, content_type="application/apply-patch+yaml")

    def update_status(self, obj: dict) -> dict:
        return self._request(
            "PUT",
            self._url(ko.api_version(obj), ko.kind(obj), ko.namespace(obj),
                      ko.name(obj), subresource="status"),
            obj)

    def delete(self, api_version: str, kind: str, namespace: str,
               name: str) -> bool:
        try:
            self._request(
                "DELETE", self._url(api_version, kind, namespace, name))
            return True
        except NotFound:
            return False

    def pod_logs(self, namespace: str, name: str,
                 container: Optional[str] = None, follow: bool = False,
                 tail_lines: Optional[int] = None):
        """Stream pod log lines (GET .../pods/{name}/log). Generator of
        decoded lines; with follow=True it blocks on the HTTP stream until
        the pod finishes (reference analog: internal/tui/pods.go getLogs
        via the clientset's follow stream)."""
        query = []
        if container:
            query.append(f"container={container}")
        if follow:
            query.append("follow=true")
        if tail_lines is not None:
            query.append(f"tailLines={tail_lines}")
        url = self._url("v1", "Pod", namespace, name, subresource="log",
                        query="&".join(query))
        req = urllib.request.Request(
            url, headers={**self.config.headers, "Accept": "text/plain"})
        timeout = 3600 if follow else 30
        try:
            with urllib.request.urlopen(
                    req, context=self.config.ssl_ctx, timeout=timeout) as r:
                for raw in r:
                    yield raw.decode("utf-8", "replace").rstrip("\n")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return
            raise

    # -- watch ---------------------------------------------------------

    def watch(self, api_version: Optional[str] = None,
              kind: Optional[str] = None,
              namespace: Optional[str] = None) -> Subscription:
        assert api_version and kind, "real watches need api_version + kind"
        sub = Subscription()

        def reader():
            import sys
            import time

            last_log = -1e9
            resource_version = ""
            split = urllib.parse.urlsplit(self.config.server)
            preflight_addr = (split.hostname,
                              split.port or (443 if split.scheme == "https"
                                             else 80))
            # urlopen may route through an HTTP(S) proxy; a direct TCP
            # preflight would then fail even though requests work. Only
            # preflight when the connection is direct.
            try:
                proxied = (split.scheme in urllib.request.getproxies()
                           and not urllib.request.proxy_bypass(
                               split.hostname or ""))
            except OSError:
                proxied = False
            while not sub.closed.is_set():
                query = "watch=true&allowWatchBookmarks=true"
                if resource_version:
                    query += f"&resourceVersion={resource_version}"
                url = self._url(api_version, kind, namespace, query=query)
                req = urllib.request.Request(
                    url, headers={**self.config.headers,
                                  "Accept": "application/json"})
                try:
                    # Cheap TCP preflight with a short timeout: a
                    # black-holed apiserver must not pin this thread inside
                    # a long urlopen connect where close() is invisible —
                    # the manager re-subscribes per backoff cycle and would
                    # stack such threads.
                    if not proxied:
                        import socket as _socket

                        _socket.create_connection(preflight_addr,
                                                  timeout=5).close()
                        if sub.closed.is_set():
                            return
                    # Socket read timeout bounds half-open connections; the
                    # apiserver sends bookmarks well inside this window.
                    with urllib.request.urlopen(
                            req, context=self.config.ssl_ctx,
                            timeout=300) as resp:
                        if sub.closed.is_set():
                            return
                        # close() must interrupt a blocked body read, not
                        # wait out the 300s timeout: register the response
                        # so closing it from the closer thread errors the
                        # read (caught below as a reconnect).
                        sub.add_closer(resp.close)
                        try:
                            for line in resp:
                                if sub.closed.is_set():
                                    return
                                if not line.strip():
                                    continue
                                event = json.loads(line)
                                obj = event.get("object", {})
                                rv = ko.deep_get(obj, "metadata",
                                                 "resourceVersion")
                                if rv:
                                    resource_version = rv
                                etype = event.get("type", "MODIFIED")
                                if etype == "ERROR":
                                    # e.g. 410 Gone: resourceVersion
                                    # expired — restart from now (manager
                                    # resync covers the gap).
                                    resource_version = ""
                                    break
                                if etype == "BOOKMARK":
                                    continue
                                sub.put(etype, obj)
                        finally:
                            # Don't accumulate a stale closer per reconnect.
                            sub.remove_closer(resp.close)
                except Exception as e:  # noqa: BLE001 — reconnect loop
                    # Rate-limit the reconnect log: a dead apiserver (or a
                    # test server that shut down) would otherwise spam a
                    # line every 2s from this daemon thread.
                    now = time.monotonic()
                    if now - last_log > 30:
                        last_log = now
                        print(f"watch {kind}: reconnecting after {e!r}",
                              file=sys.stderr)
                    if sub.closed.wait(2):
                        return

        t = threading.Thread(target=reader, daemon=True)
        sub.reader_thread = t
        t.start()
        return sub
