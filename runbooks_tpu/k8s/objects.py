"""Kubernetes object helpers over plain dicts.

The whole orchestration layer treats K8s objects as dicts in manifest shape
(what you'd kubectl-apply). Typed wrappers in runbooks_tpu.api add accessors
for our CRDs; these helpers cover the generic metadata/condition machinery
(reference analogs: api/v1/conditions.go, meta helpers used throughout
internal/controller/).
"""

from __future__ import annotations

import copy
import time
from typing import Any, Dict, List, Optional

Obj = Dict[str, Any]


def new(api_version: str, kind: str, name: str, namespace: str = "default",
        spec: Optional[dict] = None, labels: Optional[dict] = None,
        annotations: Optional[dict] = None) -> Obj:
    meta: Dict[str, Any] = {"name": name, "namespace": namespace}
    if labels:
        meta["labels"] = dict(labels)
    if annotations:
        meta["annotations"] = dict(annotations)
    obj: Obj = {"apiVersion": api_version, "kind": kind, "metadata": meta}
    if spec is not None:
        obj["spec"] = spec
    return obj


def name(obj: Obj) -> str:
    return obj.get("metadata", {}).get("name", "")


def namespace(obj: Obj) -> str:
    return obj.get("metadata", {}).get("namespace", "default")


def kind(obj: Obj) -> str:
    return obj.get("kind", "")


def api_version(obj: Obj) -> str:
    return obj.get("apiVersion", "")


def uid(obj: Obj) -> str:
    return obj.get("metadata", {}).get("uid", "")


def key(obj: Obj) -> str:
    return f"{api_version(obj)}/{kind(obj)}/{namespace(obj)}/{name(obj)}"


def labels(obj: Obj) -> Dict[str, str]:
    return obj.get("metadata", {}).get("labels", {}) or {}


def annotations(obj: Obj) -> Dict[str, str]:
    return obj.get("metadata", {}).get("annotations", {}) or {}


def set_annotation(obj: Obj, k: str, v: str) -> None:
    obj.setdefault("metadata", {}).setdefault("annotations", {})[k] = v


def owner_reference(owner: Obj, controller: bool = True) -> dict:
    return {
        "apiVersion": api_version(owner),
        "kind": kind(owner),
        "name": name(owner),
        "uid": uid(owner),
        "controller": controller,
        "blockOwnerDeletion": True,
    }


def set_owner(obj: Obj, owner: Obj) -> None:
    refs = obj.setdefault("metadata", {}).setdefault("ownerReferences", [])
    ref = owner_reference(owner)
    for existing in refs:
        if existing.get("uid") == ref["uid"]:
            return
    refs.append(ref)


def deep_get(obj: Obj, *path: str, default=None):
    node: Any = obj
    for p in path:
        if not isinstance(node, dict) or p not in node:
            return default
        node = node[p]
    return node


def deep_set(obj: Obj, value: Any, *path: str) -> None:
    node = obj
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


# ---------------------------------------------------------------------------
# Conditions (mirrors the metav1.Condition convention the reference uses)
# ---------------------------------------------------------------------------

def get_condition(obj: Obj, ctype: str) -> Optional[dict]:
    for c in deep_get(obj, "status", "conditions", default=[]) or []:
        if c.get("type") == ctype:
            return c
    return None


def set_condition(obj: Obj, ctype: str, status: bool, reason: str,
                  message: str = "", generation: Optional[int] = None) -> bool:
    """Upsert a condition; returns True if it changed."""
    conds: List[dict] = obj.setdefault("status", {}).setdefault(
        "conditions", [])
    new_c = {
        "type": ctype,
        "status": "True" if status else "False",
        "reason": reason,
        "message": message,
        "observedGeneration": generation
        if generation is not None else deep_get(obj, "metadata", "generation",
                                                default=0),
    }
    for i, c in enumerate(conds):
        if c.get("type") == ctype:
            if (c.get("status") == new_c["status"]
                    and c.get("reason") == new_c["reason"]
                    and c.get("message") == new_c["message"]):
                return False
            new_c["lastTransitionTime"] = (
                c.get("lastTransitionTime")
                if c.get("status") == new_c["status"]
                else _now())
            conds[i] = new_c
            return True
    new_c["lastTransitionTime"] = _now()
    conds.append(new_c)
    return True


def is_condition_true(obj: Obj, ctype: str) -> bool:
    c = get_condition(obj, ctype)
    return bool(c and c.get("status") == "True")


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def clone(obj: Obj) -> Obj:
    return copy.deepcopy(obj)
