"""HTTP apiserver fake: FakeCluster semantics behind real Kubernetes REST
paths.

Purpose: wire-level testing of k8s.client.K8sClient (the stdlib REST
client) without a cluster — the reference runs a real envtest apiserver for
this (reference: internal/controller/main_test.go:46-191); this shim covers
the protocol layer (URL shapes, SSA PATCH content type + fieldManager,
status subresource, 404/409 mapping, chunked watch streams) while
delegating object semantics to the in-memory FakeCluster.

Every request is recorded (method, path, query, content type) so tests can
assert the client put the right bytes on the wire, not just that state
changed.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

from runbooks_tpu.k8s import objects as ko
from runbooks_tpu.k8s.fake import AlreadyExists, Conflict, FakeCluster, NotFound

# Reverse of client.PLURALS, plus lowercase kind fallback.
from runbooks_tpu.k8s.client import PLURALS

SINGULARS = {v: k for k, v in PLURALS.items()}


def _parse_path(path: str) -> Optional[dict]:
    """/api/v1/... or /apis/{group}/{version}/... ->
    {api_version, kind, namespace, name, subresource}."""
    parts = [p for p in path.split("/") if p]
    if not parts:
        return None
    if parts[0] == "api" and len(parts) >= 2:
        api_version = parts[1]
        rest = parts[2:]
    elif parts[0] == "apis" and len(parts) >= 3:
        api_version = f"{parts[1]}/{parts[2]}"
        rest = parts[3:]
    else:
        return None
    namespace = None
    if len(rest) >= 2 and rest[0] == "namespaces":
        namespace = rest[1]
        rest = rest[2:]
    if not rest:
        return None
    plural = rest[0]
    kind = SINGULARS.get(plural, plural[:-1].capitalize())
    name = rest[1] if len(rest) >= 2 else None
    subresource = rest[2] if len(rest) >= 3 else None
    return {"api_version": api_version, "kind": kind,
            "namespace": namespace, "name": name,
            "subresource": subresource}


class FakeApiServer:
    """Threaded HTTP server over a FakeCluster. Use as a context manager."""

    def __init__(self, cluster: Optional[FakeCluster] = None,
                 port: int = 0):
        self.cluster = cluster or FakeCluster()
        self.requests: List[Tuple[str, str, str, str]] = []  # m, p, q, ct
        shim = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _record(self):
                parsed = urllib.parse.urlparse(self.path)
                shim.requests.append(
                    (self.command, parsed.path, parsed.query,
                     self.headers.get("Content-Type", "")))
                return parsed

            def _send_json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            def _route(self):
                parsed = self._record()
                ref = _parse_path(parsed.path)
                if ref is None:
                    return self._send_json(404, {"message": "bad path"})
                query = urllib.parse.parse_qs(parsed.query)
                try:
                    self._dispatch(ref, query)
                except NotFound as e:
                    self._send_json(404, {"reason": "NotFound",
                                          "message": str(e)})
                except AlreadyExists as e:
                    self._send_json(409, {"reason": "AlreadyExists",
                                          "message": f"AlreadyExists: {e}"})
                except Conflict as e:
                    self._send_json(409, {"reason": "Conflict",
                                          "message": str(e)})

            def _dispatch(self, ref, query):
                c = shim.cluster
                av, kind = ref["api_version"], ref["kind"]
                ns, name = ref["namespace"], ref["name"]
                if self.command == "GET" and query.get("watch"):
                    return self._watch(av, kind, ns)
                if self.command == "GET" and name:
                    obj = c.get(av, kind, ns, name)
                    if obj is None:
                        raise NotFound(f"{kind} {ns}/{name}")
                    return self._send_json(200, obj)
                if self.command == "GET":
                    sel = None
                    if query.get("labelSelector"):
                        sel = dict(kv.split("=", 1) for kv in
                                   query["labelSelector"][0].split(","))
                    items = c.list(av, kind, namespace=ns,
                                   label_selector=sel)
                    return self._send_json(200, {"kind": f"{kind}List",
                                                 "items": items})
                if self.command == "POST":
                    return self._send_json(201, c.create(self._body()))
                if self.command == "PUT" and ref["subresource"] == "status":
                    return self._send_json(200, c.update_status(self._body()))
                if self.command == "PUT":
                    return self._send_json(200, c.update(self._body()))
                if self.command == "PATCH":
                    fm = (query.get("fieldManager") or [""])[0]
                    ct = self.headers.get("Content-Type", "")
                    if ct != "application/apply-patch+yaml":
                        return self._send_json(
                            415, {"message": f"unsupported patch type {ct}"})
                    if not fm:
                        return self._send_json(
                            422, {"message": "fieldManager is required for "
                                             "server-side apply"})
                    return self._send_json(200, c.apply(self._body(), fm))
                if self.command == "DELETE":
                    if not c.delete(av, kind, ns, name):
                        raise NotFound(f"{kind} {ns}/{name}")
                    return self._send_json(200, {"status": "Success"})
                self._send_json(405, {"message": self.command})

            def _watch(self, av, kind, ns):
                sub = shim.cluster.watch(av, kind)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def send_chunk(data: bytes):
                    self.wfile.write(f"{len(data):x}\r\n".encode()
                                     + data + b"\r\n")
                    self.wfile.flush()

                try:
                    idle = 0
                    while idle < 100:  # ~10s then close (client reconnects)
                        got = sub.poll(timeout=0.1)
                        if got is None:
                            idle += 1
                            continue
                        idle = 0
                        event, obj = got
                        if ns and ko.namespace(obj) != ns:
                            continue
                        line = json.dumps(
                            {"type": event, "object": obj}) + "\n"
                        send_chunk(line.encode())
                    send_chunk(b"")
                except (BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    shim.cluster.unwatch(sub)

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _route

        # A fixed port lets tests restart the "apiserver" at the same
        # address (manager crash-recovery coverage).
        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    @property
    def url(self) -> str:
        host, port = self._server.server_address
        return f"http://{host}:{port}"

    def __enter__(self) -> "FakeApiServer":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._server.shutdown()
        self._server.server_close()
