"""In-process pod port-forwarding over the Kubernetes websocket protocol.

Reference analog: internal/client/port_forward.go (SPDY via client-go).
Kubernetes serves the same subresource over websockets
(`v4.channel.k8s.io`), which needs no SPDY stack: each websocket message is
a 1-byte channel id + payload, with channels (2*i) = data and (2*i)+1 =
errors for the i-th requested port; the first message on each channel
carries the port number (uint16 LE). One websocket session == one TCP
connection's worth of streams, so every accepted local connection dials a
fresh session — exactly how kubectl's SPDY dialer behaves.

The websocket client itself is stdlib-only (RFC 6455: handshake, masked
client frames, ping/pong, fragmentation) — no external deps, same policy
as the rest of k8s/.
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import ssl
import struct
import threading
import urllib.parse
from typing import Callable, Optional

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class WebSocket:
    """Minimal RFC 6455 client over an established socket."""

    def __init__(self, sock):
        self.sock = sock
        self._buf = b""
        self._lock = threading.Lock()

    # -- handshake ---------------------------------------------------------

    @classmethod
    def connect(cls, url: str, headers: dict, subprotocol: str,
                ssl_ctx: Optional[ssl.SSLContext] = None,
                timeout: float = 30.0) -> "WebSocket":
        parts = urllib.parse.urlparse(url)
        secure = parts.scheme in ("https", "wss")
        port = parts.port or (443 if secure else 80)
        raw = socket.create_connection((parts.hostname, port), timeout)
        if secure:
            ctx = ssl_ctx or ssl.create_default_context()
            raw = ctx.wrap_socket(raw, server_hostname=parts.hostname)
        key = base64.b64encode(os.urandom(16)).decode()
        path = parts.path + (f"?{parts.query}" if parts.query else "")
        req = [f"GET {path} HTTP/1.1",
               f"Host: {parts.hostname}:{port}",
               "Upgrade: websocket",
               "Connection: Upgrade",
               f"Sec-WebSocket-Key: {key}",
               "Sec-WebSocket-Version: 13",
               f"Sec-WebSocket-Protocol: {subprotocol}"]
        req += [f"{k}: {v}" for k, v in headers.items()]
        raw.sendall(("\r\n".join(req) + "\r\n\r\n").encode())

        response = b""
        while b"\r\n\r\n" not in response:
            chunk = raw.recv(4096)
            if not chunk:
                raise ConnectionError("websocket handshake: connection closed")
            response += chunk
        head, _, rest = response.partition(b"\r\n\r\n")
        status = head.split(b"\r\n", 1)[0]
        if b"101" not in status:
            raise ConnectionError(
                f"websocket handshake rejected: {status.decode(errors='replace')}")
        accept = hashlib.sha1((key + _WS_GUID).encode()).digest()
        expect = base64.b64encode(accept).decode()
        got = None
        for line in head.decode(errors="replace").split("\r\n")[1:]:
            name, _, value = line.partition(":")
            if name.strip().lower() == "sec-websocket-accept":
                got = value.strip()
        if got != expect:
            raise ConnectionError("websocket handshake: bad accept key")
        ws = cls(raw)
        ws._buf = rest
        return ws

    # -- frames ------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("websocket closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def send(self, payload: bytes, opcode: int = 0x2) -> None:
        header = bytes([0x80 | opcode])
        n = len(payload)
        if n < 126:
            header += bytes([0x80 | n])
        elif n < 1 << 16:
            header += bytes([0x80 | 126]) + struct.pack(">H", n)
        else:
            header += bytes([0x80 | 127]) + struct.pack(">Q", n)
        mask = os.urandom(4)
        if n:
            # Vectorized XOR: per-byte Python masking caps bulk-forwarding
            # throughput (one interpreted op per byte).
            import numpy as np

            arr = np.frombuffer(payload, np.uint8)
            tiled = np.frombuffer(mask * ((n + 3) // 4), np.uint8)[:n]
            masked = (arr ^ tiled).tobytes()
        else:
            masked = b""
        with self._lock:
            self.sock.sendall(header + mask + masked)

    def recv(self) -> Optional[bytes]:
        """Next binary/text message payload; None on clean close.
        Handles fragmentation and control frames inline."""
        message = b""
        while True:
            b0, b1 = self._read_exact(2)
            opcode, fin = b0 & 0x0F, b0 & 0x80
            masked, n = b1 & 0x80, b1 & 0x7F
            if n == 126:
                n = struct.unpack(">H", self._read_exact(2))[0]
            elif n == 127:
                n = struct.unpack(">Q", self._read_exact(8))[0]
            mask = self._read_exact(4) if masked else b""
            payload = self._read_exact(n)
            if mask and payload:  # servers send unmasked; rarely taken
                import numpy as np

                tiled = np.frombuffer(
                    mask * ((len(payload) + 3) // 4), np.uint8)[:len(payload)]
                payload = (np.frombuffer(payload, np.uint8) ^ tiled).tobytes()
            if opcode == 0x8:                       # close
                try:
                    self.send(payload, opcode=0x8)
                except OSError:
                    pass
                return None
            if opcode == 0x9:                       # ping -> pong
                self.send(payload, opcode=0xA)
                continue
            if opcode == 0xA:                       # pong
                continue
            message += payload
            if fin:
                return message

    def close(self) -> None:
        try:
            self.send(b"", opcode=0x8)
        except OSError:
            pass
        # shutdown before close: close() alone neither wakes a thread
        # blocked in recv() on this socket nor sends FIN while that
        # syscall pins the fd — the peer would hang, not see EOF.
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class PortForwarder:
    """Forward localhost:local_port -> pod:remote_port, one websocket
    session per accepted TCP connection."""

    def __init__(self, config, namespace: str, pod: str,
                 local_port: int, remote_port: int,
                 on_ready: Optional[Callable[[int], None]] = None):
        self.config = config            # k8s.client.KubeConfig
        self.namespace = namespace
        self.pod = pod
        self.local_port = local_port
        self.remote_port = remote_port
        self.on_ready = on_ready
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._error: Optional[BaseException] = None

    def _fail(self, exc: BaseException) -> None:
        """Record the first fatal error (from any connection thread) and
        wind down serve() so callers actually see it."""
        if self._error is None:
            self._error = exc
        self._stop.set()

    def _ws_url(self) -> str:
        server = self.config.server
        return (f"{server}/api/v1/namespaces/{self.namespace}/pods/"
                f"{self.pod}/portforward?ports={self.remote_port}")

    def _dial(self) -> WebSocket:
        ws = WebSocket.connect(
            self._ws_url(), self.config.headers, "v4.channel.k8s.io",
            ssl_ctx=(self.config.ssl_ctx
                     if self.config.server.startswith("https") else None))
        # First message per channel announces the port (uint16 LE).
        for _ in range(2):
            msg = ws.recv()
            if msg is None or len(msg) < 3:
                raise ConnectionError("port-forward: missing port header")
            (port,) = struct.unpack("<H", msg[1:3])
            if port != self.remote_port:
                raise ConnectionError(
                    f"port-forward: unexpected port {port}")
        return ws

    def _pump(self, conn: socket.socket) -> None:
        try:
            ws = self._dial()
        except Exception as e:  # auth expiry, pod gone, apiserver down
            conn.close()
            self._fail(ConnectionError(f"port-forward dial failed: {e}"))
            return

        def local_to_ws():
            try:
                while not self._stop.is_set():
                    data = conn.recv(65536)
                    if not data:
                        break
                    ws.send(b"\x00" + data)   # channel 0 = data
            except OSError:
                pass
            ws.close()

        threading.Thread(target=local_to_ws, daemon=True).start()
        try:
            while not self._stop.is_set():
                msg = ws.recv()
                if msg is None or not msg:
                    break
                channel, payload = msg[0], msg[1:]
                if channel == 0 and payload:
                    conn.sendall(payload)
                elif channel == 1 and payload:
                    # Apiserver error event (e.g. "container not running"):
                    # must surface, not vanish — ConnectionError is an
                    # OSError subclass, so catch order matters below.
                    self._fail(ConnectionError(
                        "port-forward error: "
                        f"{payload.decode(errors='replace')}"))
                    break
        except OSError:
            pass
        finally:
            # shutdown first: local_to_ws may be blocked in conn.recv();
            # a bare close() would leave it stuck and never FIN the client.
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
            ws.close()

    def serve(self) -> None:
        """Listen and forward until stop(); calls on_ready(local_port) once
        listening (the bound port — useful with local_port=0). Raises
        ConnectionError on dial/auth failures or apiserver error events."""
        # Preflight one session so bad auth/paths fail fast, before the
        # caller is told the tunnel is ready.
        try:
            self._dial().close()
        except Exception as e:
            raise ConnectionError(
                f"port-forward dial failed: {e}") from e
        listener = socket.create_server(("127.0.0.1", self.local_port))
        self._listener = listener
        self.local_port = listener.getsockname()[1]
        if self.on_ready is not None:
            self.on_ready(self.local_port)
        listener.settimeout(0.5)
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = listener.accept()
                except socket.timeout:
                    continue
                threading.Thread(target=self._pump, args=(conn,),
                                 daemon=True).start()
        finally:
            listener.close()
        if self._error is not None:
            raise ConnectionError(str(self._error))

    def stop(self) -> None:
        self._stop.set()
