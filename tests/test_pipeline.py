"""Pipeline-parallelism tests: the GPipe-over-stage-axis path must be
numerically identical to the plain layer scan (same params, same batch),
forward and backward, and must compose with the train step.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import forward, init_params
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh


def pp_cfg(**over):
    kw = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
              num_layers=4, num_heads=4, num_kv_heads=4, head_dim=8,
              max_seq_len=16, dtype="float32")
    kw.update(over)
    return get_config("debug", **kw)


def batch_tokens(cfg, b=8, s=12, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)


def test_pipeline_forward_matches_plain():
    cfg = pp_cfg()
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg)

    plain_mesh = make_mesh(MeshConfig(data=2, fsdp=4))
    with jax.set_mesh(plain_mesh):
        want, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)

    pp_mesh = make_mesh(MeshConfig(data=2, stage=4, fsdp=1))
    with jax.set_mesh(pp_mesh):
        got, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_more_microbatches_than_stages():
    cfg = pp_cfg(pipeline_microbatches=4)
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg)

    plain = make_mesh(MeshConfig(fsdp=8))
    with jax.set_mesh(plain):
        want, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)

    pp_mesh = make_mesh(MeshConfig(stage=2, fsdp=4))
    with jax.set_mesh(pp_mesh):
        got, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_plain():
    cfg = pp_cfg()
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg)
    targets = batch_tokens(cfg, seed=1)

    def loss_fn(p, t, y):
        logits, _ = forward(cfg, p, t)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, y[..., None], -1))

    plain_mesh = make_mesh(MeshConfig(fsdp=8))
    with jax.set_mesh(plain_mesh):
        want = jax.jit(jax.grad(loss_fn))(params, tokens, targets)

    pp_mesh = make_mesh(MeshConfig(stage=4, fsdp=2))
    with jax.set_mesh(pp_mesh):
        got = jax.jit(jax.grad(loss_fn))(params, tokens, targets)

    flat_w, _ = jax.tree.flatten(want)
    flat_g, _ = jax.tree.flatten(got)
    for w, g in zip(flat_w, flat_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-5)


def test_pipeline_train_step_runs():
    from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
    from runbooks_tpu.train.step import create_train_state, make_train_step

    cfg = pp_cfg()
    mesh = make_mesh(MeshConfig(data=2, stage=2, fsdp=1, tensor=2))
    opt = make_optimizer(OptimizerConfig(total_steps=4, warmup_steps=0))
    state, shardings = create_train_state(cfg, opt, mesh, jax.random.key(0))
    step = make_train_step(cfg, opt, mesh, shardings)

    tokens = np.asarray(batch_tokens(cfg, b=8, s=13))
    batch = {"tokens": tokens[:, :-1], "targets": tokens[:, 1:],
             "loss_mask": np.ones((8, 12), np.float32)}
    with jax.set_mesh(mesh):
        losses = []
        for _ in range(3):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # actually learning through the pipeline

    # Layer params really are stage-sharded (the point of PP: per-device
    # parameter memory drops by the stage factor).
    wq = state.params["layers"]["attn"]["wq"]
    assert wq.sharding.spec[0] == "stage"


def test_pipeline_rejects_indivisible():
    cfg = pp_cfg(num_layers=3)
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg)
    mesh = make_mesh(MeshConfig(stage=2, fsdp=4))
    with jax.set_mesh(mesh):
        with pytest.raises(ValueError, match="not divisible"):
            jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)


def test_pipeline_composes_with_ring_attention():
    """SP (ring attention over the sequence axis) inside PP stages: nested
    shard_map (stage manual outside, sequence manual inside) must match the
    plain forward exactly."""
    cfg = pp_cfg(attention_impl="ring")
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg, b=4, s=8)

    plain = make_mesh(MeshConfig(fsdp=8))
    with jax.set_mesh(plain):
        want, _ = jax.jit(lambda p, t: forward(
            dataclasses.replace(cfg, attention_impl="xla"), p, t))(
                params, tokens)

    mesh = make_mesh(MeshConfig(stage=2, sequence=2, fsdp=2))
    with jax.set_mesh(mesh):
        got, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
