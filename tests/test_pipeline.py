"""Pipeline-parallelism tests: the GPipe-over-stage-axis path must be
numerically identical to the plain layer scan (same params, same batch),
forward and backward, and must compose with the train step.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import forward, init_params
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh
from tests.conftest import partial_manual_shard_map_broken

# The stage-manual (partial-manual) shard_map these tests exercise cannot
# be SPMD-partitioned on old jaxlibs (PartitionId limitation) — probe once
# and skip instead of carrying known-red tests (tests/conftest.py).
needs_partial_manual = pytest.mark.skipif(
    partial_manual_shard_map_broken(),
    reason="old-jaxlib SPMD PartitionId limitation: partial-manual "
           "(stage) shard_map cannot be partitioned")


def pp_cfg(**over):
    kw = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
              num_layers=4, num_heads=4, num_kv_heads=4, head_dim=8,
              max_seq_len=16, dtype="float32")
    kw.update(over)
    return get_config("debug", **kw)


def batch_tokens(cfg, b=8, s=12, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)


@needs_partial_manual
def test_pipeline_forward_matches_plain():
    cfg = pp_cfg()
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg)

    plain_mesh = make_mesh(MeshConfig(data=2, fsdp=4))
    with jax.set_mesh(plain_mesh):
        want, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)

    pp_mesh = make_mesh(MeshConfig(data=2, stage=4, fsdp=1))
    with jax.set_mesh(pp_mesh):
        got, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@needs_partial_manual
def test_pipeline_more_microbatches_than_stages():
    cfg = pp_cfg(pipeline_microbatches=4)
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg)

    plain = make_mesh(MeshConfig(fsdp=8))
    with jax.set_mesh(plain):
        want, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)

    pp_mesh = make_mesh(MeshConfig(stage=2, fsdp=4))
    with jax.set_mesh(pp_mesh):
        got, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@needs_partial_manual
def test_pipeline_gradients_match_plain():
    cfg = pp_cfg()
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg)
    targets = batch_tokens(cfg, seed=1)

    def loss_fn(p, t, y):
        logits, _ = forward(cfg, p, t)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, y[..., None], -1))

    plain_mesh = make_mesh(MeshConfig(fsdp=8))
    with jax.set_mesh(plain_mesh):
        want = jax.jit(jax.grad(loss_fn))(params, tokens, targets)

    pp_mesh = make_mesh(MeshConfig(stage=4, fsdp=2))
    with jax.set_mesh(pp_mesh):
        got = jax.jit(jax.grad(loss_fn))(params, tokens, targets)

    flat_w, _ = jax.tree.flatten(want)
    flat_g, _ = jax.tree.flatten(got)
    for w, g in zip(flat_w, flat_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-5)


@needs_partial_manual
def test_pipeline_train_step_runs():
    from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
    from runbooks_tpu.train.step import create_train_state, make_train_step

    cfg = pp_cfg()
    mesh = make_mesh(MeshConfig(data=2, stage=2, fsdp=1, tensor=2))
    opt = make_optimizer(OptimizerConfig(total_steps=4, warmup_steps=0))
    state, shardings = create_train_state(cfg, opt, mesh, jax.random.key(0))
    step = make_train_step(cfg, opt, mesh, shardings)

    tokens = np.asarray(batch_tokens(cfg, b=8, s=13))
    batch = {"tokens": tokens[:, :-1], "targets": tokens[:, 1:],
             "loss_mask": np.ones((8, 12), np.float32)}
    with jax.set_mesh(mesh):
        losses = []
        for _ in range(3):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # actually learning through the pipeline

    # Layer params really are stage-sharded (the point of PP: per-device
    # parameter memory drops by the stage factor).
    wq = state.params["layers"]["attn"]["wq"]
    assert wq.sharding.spec[0] == "stage"


def test_pipeline_rejects_indivisible():
    cfg = pp_cfg(num_layers=3)
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg)
    mesh = make_mesh(MeshConfig(stage=2, fsdp=4))
    with jax.set_mesh(mesh):
        with pytest.raises(ValueError, match="not divisible"):
            jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)


def loss_weight_grads_ref(cfg, params, tokens, targets, mask=None):
    """Oracle: plain autodiff CE loss/grads (runs GPipe when the active
    mesh has stage > 1, plain scan otherwise)."""
    from runbooks_tpu.train.step import cross_entropy_loss

    def loss_fn(p):
        logits, _, aux = forward(cfg, p, tokens, with_aux=True)
        loss, total = cross_entropy_loss(logits, targets, mask)
        if cfg.moe_num_experts:
            loss = loss + cfg.moe_aux_coef * aux
        return loss, total

    (loss, total), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params)
    return loss, grads, total


@needs_partial_manual
def test_1f1b_matches_autodiff_grads():
    """The explicit 1F1B backward must reproduce plain-autodiff loss and
    grads exactly (same math, different schedule) — including with more
    microbatches than stages and a non-trivial loss mask."""
    from runbooks_tpu.models.transformer import loss_and_grads_1f1b

    cfg = pp_cfg(pipeline_microbatches=4)
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg)
    targets = batch_tokens(cfg, seed=1)
    rng = np.random.default_rng(2)
    mask = jnp.asarray(rng.integers(0, 2, tokens.shape), jnp.float32)

    plain = make_mesh(MeshConfig(fsdp=8))
    with jax.set_mesh(plain):
        want_loss, want_grads, want_total = jax.jit(
            lambda p: loss_weight_grads_ref(cfg, p, tokens, targets, mask)
        )(params)

    pp_mesh = make_mesh(MeshConfig(stage=2, fsdp=4))
    with jax.set_mesh(pp_mesh):
        got_loss, got_grads, got_total = jax.jit(
            lambda p: loss_and_grads_1f1b(cfg, p, tokens, targets, mask)
        )(params)

    assert float(got_total) == float(want_total)
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5)
    flat_w, tw = jax.tree.flatten(want_grads)
    flat_g, tg = jax.tree.flatten(got_grads)
    assert tw == tg
    for w, g in zip(flat_w, flat_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-5)


@needs_partial_manual
@pytest.mark.slow
def test_1f1b_train_step_matches_gpipe_step():
    """Full train step through both schedules from identical state: same
    loss metric, same updated params (1F1B is a reschedule, not a
    different optimizer path)."""
    from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
    from runbooks_tpu.train.step import create_train_state, make_train_step

    tokens = None
    results = {}
    for schedule in ("gpipe", "1f1b"):
        cfg = pp_cfg(pipeline_schedule=schedule, pipeline_microbatches=4)
        mesh = make_mesh(MeshConfig(stage=2, fsdp=4))
        opt = make_optimizer(OptimizerConfig(total_steps=4, warmup_steps=0))
        state, shardings = create_train_state(cfg, opt, mesh,
                                              jax.random.key(0))
        step = make_train_step(cfg, opt, mesh, shardings)
        if tokens is None:
            tokens = np.asarray(batch_tokens(cfg, b=8, s=13))
        batch = {"tokens": tokens[:, :-1], "targets": tokens[:, 1:],
                 "loss_mask": np.ones((8, 12), np.float32)}
        with jax.set_mesh(mesh):
            state, metrics = step(state, batch)
        results[schedule] = (float(metrics["loss"]),
                             jax.tree.map(np.asarray, state.params))
    assert np.isclose(results["gpipe"][0], results["1f1b"][0], rtol=1e-5)
    for a, b in zip(jax.tree.leaves(results["gpipe"][1]),
                    jax.tree.leaves(results["1f1b"][1])):
        np.testing.assert_allclose(b, a, rtol=5e-4, atol=5e-5)


def test_1f1b_rejects_indivisible_microbatches():
    from runbooks_tpu.models.transformer import loss_and_grads_1f1b

    cfg = pp_cfg(pipeline_microbatches=3)
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg, b=6)
    mesh = make_mesh(MeshConfig(stage=2, fsdp=4))
    with jax.set_mesh(mesh):
        with pytest.raises(ValueError, match="divisible by"):
            jax.jit(lambda p: loss_and_grads_1f1b(
                cfg, p, tokens, tokens))(params)


def test_1f1b_activation_memory_bounded_by_stages():
    """1F1B's cross-tick activation state is a ring of min(M, 2S-1)
    microbatch inputs (+ the dx bank), while GPipe autodiff tapes every
    microbatch's per-layer activations. At CONSTANT microbatch size
    (batch grows with M), GPipe's tape grows by a full per-microbatch
    activation set for every added microbatch; 1F1B adds only the dx-bank
    row. Compare compiled temp growth M=2 -> M=8 on a 2-stage mesh."""
    from runbooks_tpu.models.transformer import loss_and_grads_1f1b

    if "cpu" in jax.default_backend().lower():
        # Measured: CPU temp_size_in_bytes grows ~equally for both
        # schedules at constant microbatch size (~0.4 MB/mb) — it reports
        # allocation totals without liveness-based reuse across the
        # unrolled ticks, so the cross-tick bound is invisible. TPU
        # buffer assignment is liveness-accurate; the comparison runs
        # there (BENCH_NOTES.md records it when relay hardware is up).
        pytest.skip("CPU memory_analysis lacks cross-tick buffer reuse")

    mesh = make_mesh(MeshConfig(stage=2, fsdp=4))
    mb_rows = 4  # microbatch size held constant

    def temp_bytes(schedule, m):
        cfg = pp_cfg(pipeline_microbatches=m, pipeline_schedule=schedule,
                     num_layers=4, remat_policy="none")
        params = init_params(cfg, jax.random.key(0))
        tokens = batch_tokens(cfg, b=mb_rows * m, s=16)
        targets = batch_tokens(cfg, b=mb_rows * m, s=16, seed=1)
        with jax.set_mesh(mesh):
            if schedule == "1f1b":
                fn = jax.jit(lambda p: loss_and_grads_1f1b(
                    cfg, p, tokens, targets))
            else:
                fn = jax.jit(lambda p: loss_weight_grads_ref(
                    cfg, p, tokens, targets))
            mem = fn.lower(params).compile().memory_analysis()
        if mem is None:
            pytest.skip("memory_analysis unavailable on this backend")
        return mem.temp_size_in_bytes

    gpipe_growth = temp_bytes("gpipe", 8) - temp_bytes("gpipe", 2)
    f1b_growth = temp_bytes("1f1b", 8) - temp_bytes("1f1b", 2)
    assert f1b_growth < max(gpipe_growth / 2, 1), \
        (f1b_growth, gpipe_growth)


@needs_partial_manual
def test_pipeline_composes_with_ring_attention():
    """SP (ring attention over the sequence axis) inside PP stages: nested
    shard_map (stage manual outside, sequence manual inside) must match the
    plain forward exactly."""
    cfg = pp_cfg(attention_impl="ring")
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg, b=4, s=8)

    plain = make_mesh(MeshConfig(fsdp=8))
    with jax.set_mesh(plain):
        want, _ = jax.jit(lambda p, t: forward(
            dataclasses.replace(cfg, attention_impl="xla"), p, t))(
                params, tokens)

    mesh = make_mesh(MeshConfig(stage=2, sequence=2, fsdp=2))
    with jax.set_mesh(mesh):
        got, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("over", [
    dict(tie_embeddings=True),     # tied: head must stay replicated
    dict(vocab_size=65),           # odd: 65 % 2 != 0 -> replicated fallback
], ids=["tied", "indivisible-vocab"])
@needs_partial_manual
def test_1f1b_replicated_head_path_matches_autodiff(over):
    """The vocab-sharded head only applies to untied, stage-divisible
    vocabularies; these configs must take the replicated-head path and
    still match plain autodiff exactly."""
    from runbooks_tpu.models.transformer import loss_and_grads_1f1b

    cfg = pp_cfg(pipeline_microbatches=4, **over)
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg)
    targets = batch_tokens(cfg, seed=1)

    plain = make_mesh(MeshConfig(fsdp=8))
    with jax.set_mesh(plain):
        want_loss, want_grads, _ = jax.jit(
            lambda p: loss_weight_grads_ref(cfg, p, tokens, targets, None)
        )(params)

    pp_mesh = make_mesh(MeshConfig(stage=2, fsdp=4))
    with jax.set_mesh(pp_mesh):
        got_loss, got_grads, _ = jax.jit(
            lambda p: loss_and_grads_1f1b(cfg, p, tokens, targets, None)
        )(params)

    np.testing.assert_allclose(float(got_loss), float(want_loss), rtol=1e-5)
    for w, g in zip(jax.tree.leaves(want_grads), jax.tree.leaves(got_grads)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-5)


@needs_partial_manual
def test_1f1b_bf16_activations_compile_on_cpu():
    """bf16 activations cross the pipeline's psums (y broadcast, dy, dx):
    XLA CPU's AllReducePromotion crashes on bf16 all-reduces, so _psum
    upcasts around the collective there (TPU keeps native bf16). This
    pins the CPU-gate path — without the workaround this test aborts the
    process, not just fails."""
    from runbooks_tpu.models.transformer import loss_and_grads_1f1b

    cfg = pp_cfg(pipeline_microbatches=2, dtype="bfloat16")
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg)
    pp_mesh = make_mesh(MeshConfig(stage=2, fsdp=4))
    with jax.set_mesh(pp_mesh):
        loss, grads, _ = jax.jit(
            lambda p: loss_and_grads_1f1b(cfg, p, tokens, tokens))(params)
    assert np.isfinite(float(loss))


@needs_partial_manual
@pytest.mark.slow
def test_pipeline_composes_with_ring_flash_inner():
    """PP x SP with the FLASH ring inner (the TPU-default composition):
    forward and 1F1B gradients must match plain autodiff. This pins the
    nesting — stage-manual shard_map outside, the flash ring's own
    shard_map + custom_vjp inside."""
    from runbooks_tpu.models.transformer import loss_and_grads_1f1b

    cfg = pp_cfg(attention_impl="ring", ring_flash_inner=True,
                 flash_block_q=16, flash_block_k=16,
                 pipeline_microbatches=2)
    params = init_params(cfg, jax.random.key(0))
    tokens = batch_tokens(cfg, b=4, s=16)
    targets = batch_tokens(cfg, b=4, s=16, seed=1)

    plain = make_mesh(MeshConfig(fsdp=8))
    with jax.set_mesh(plain):
        want_loss, want_grads, _ = jax.jit(
            lambda p: loss_weight_grads_ref(
                dataclasses.replace(cfg, attention_impl="xla"),
                p, tokens, targets, None))(params)

    mesh = make_mesh(MeshConfig(stage=2, sequence=2, fsdp=2))
    with jax.set_mesh(mesh):
        got_loss, got_grads, _ = jax.jit(
            lambda p: loss_and_grads_1f1b(cfg, p, tokens, targets,
                                          None))(params)

    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5)
    for w, g in zip(jax.tree.leaves(want_grads),
                    jax.tree.leaves(got_grads)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=5e-4, atol=5e-4)
