"""Leader election (Lease-based) + metrics registry tests."""

import time

from runbooks_tpu.controller.leader import LeaderElector
from runbooks_tpu.controller.metrics import Registry
from runbooks_tpu.k8s.fake import FakeCluster


def test_single_elector_acquires():
    client = FakeCluster()
    e = LeaderElector(client, lease_duration_s=2.0, renew_s=0.1)
    e.run()
    assert e.is_leader.wait(timeout=3)
    e.stop()


def test_second_elector_waits_then_takes_over():
    client = FakeCluster()
    e1 = LeaderElector(client, lease_duration_s=1.0, renew_s=0.1)
    e1.run()
    assert e1.is_leader.wait(timeout=3)

    e2 = LeaderElector(client, lease_duration_s=1.0, renew_s=0.1)
    e2.run()
    time.sleep(0.5)
    assert not e2.is_leader.is_set()  # holder still renewing

    e1.stop()  # leader dies; lease expires after lease_duration
    deadline = time.time() + 5
    while time.time() < deadline and not e2.is_leader.is_set():
        time.sleep(0.1)
    assert e2.is_leader.is_set()
    e2.stop()


def test_metrics_registry_renders_prometheus_text():
    r = Registry()
    r.inc("controller_reconcile_total", kind="Model")
    r.inc("controller_reconcile_total", kind="Model")
    r.inc("controller_reconcile_total", kind="Server")
    r.set_gauge("queue_depth", 3, kind="Model")
    text = r.render()
    assert 'controller_reconcile_total{kind="Model"} 2.0' in text
    assert 'controller_reconcile_total{kind="Server"} 1.0' in text
    assert 'queue_depth{kind="Model"} 3' in text
    assert "process_uptime_seconds" in text
