"""Leader election (Lease-based) + metrics registry tests."""

import time

from runbooks_tpu.controller.leader import LeaderElector
from runbooks_tpu.controller.metrics import Registry
from runbooks_tpu.k8s.fake import FakeCluster


def test_single_elector_acquires():
    client = FakeCluster()
    e = LeaderElector(client, lease_duration_s=2.0, renew_s=0.1)
    e.run()
    assert e.is_leader.wait(timeout=3)
    e.stop()


def test_second_elector_waits_then_takes_over():
    client = FakeCluster()
    # lease_duration must comfortably exceed the 0.5s observation window
    # below: a scheduler stall > lease_duration between e1's renewals
    # (seen >1s under full-suite load on the CPU container) hands e2 the
    # lease and fails the holder-still-renewing assert.
    e1 = LeaderElector(client, lease_duration_s=3.0, renew_s=0.1)
    e1.run()
    assert e1.is_leader.wait(timeout=3)

    e2 = LeaderElector(client, lease_duration_s=3.0, renew_s=0.1)
    e2.run()
    time.sleep(0.5)
    assert not e2.is_leader.is_set()  # holder still renewing

    e1.stop()  # leader dies; lease expires after lease_duration
    deadline = time.time() + 12
    while time.time() < deadline and not e2.is_leader.is_set():
        time.sleep(0.1)
    assert e2.is_leader.is_set()
    e2.stop()


def test_metrics_registry_renders_prometheus_text():
    r = Registry()
    r.inc("controller_reconcile_total", kind="Model")
    r.inc("controller_reconcile_total", kind="Model")
    r.inc("controller_reconcile_total", kind="Server")
    r.set_gauge("queue_depth", 3, kind="Model")
    text = r.render()
    assert 'controller_reconcile_total{kind="Model"} 2.0' in text
    assert 'controller_reconcile_total{kind="Server"} 1.0' in text
    assert 'queue_depth{kind="Model"} 3' in text
    assert "process_uptime_seconds" in text


def test_elector_lose_and_reacquire_cycle():
    """Acquire -> another holder steals the (expired-looking) lease -> the
    elector steps down -> the usurper stops renewing -> reacquire.
    (VERDICT item 10: round 1 only covered acquisition.)"""
    client = FakeCluster()
    e = LeaderElector(client, lease_duration_s=0.8, renew_s=0.1)
    e.run()
    assert e.is_leader.wait(timeout=3)

    # A rival writes itself into the lease with a fresh renewTime (e.g. our
    # renew stalled long enough for it to consider the lease expired).
    from runbooks_tpu.controller.leader import LEASE_API, _now
    lease = client.get(LEASE_API, "Lease", e.namespace, e.name)
    lease["spec"].update({"holderIdentity": "rival", "renewTime": _now()})
    client.update(lease)
    # Keep the rival's renewals fresh until our elector notices.
    deadline = time.time() + 5
    while time.time() < deadline and e.is_leader.is_set():
        cur = client.get(LEASE_API, "Lease", e.namespace, e.name)
        if cur["spec"]["holderIdentity"] == "rival":
            cur["spec"]["renewTime"] = _now()
            try:
                client.update(cur)
            except Exception:
                pass
        time.sleep(0.05)
    assert not e.is_leader.is_set(), "elector must step down"

    # Rival stops renewing; after lease_duration our elector reacquires.
    deadline = time.time() + 5
    while time.time() < deadline and not e.is_leader.is_set():
        time.sleep(0.1)
    assert e.is_leader.is_set()
    cur = client.get(LEASE_API, "Lease", e.namespace, e.name)
    assert cur["spec"]["holderIdentity"] == e.identity
    e.stop()


def test_run_with_leader_election_gates_reconciling():
    """The manager runs only while the lease is held: lose -> its stop event
    fires; reacquire -> a fresh run starts (controller/main.py handoff)."""
    import threading

    from runbooks_tpu.controller.main import run_with_leader_election

    class FakeElector:
        def __init__(self):
            self.is_leader = threading.Event()

    class RecordingManager:
        def __init__(self):
            self.runs = 0
            self.running = threading.Event()

        def run(self, stop_event, **kwargs):
            self.runs += 1
            self.running.set()
            stop_event.wait(timeout=10)
            self.running.clear()

    elector, mgr = FakeElector(), RecordingManager()
    stop = threading.Event()
    t = threading.Thread(
        target=run_with_leader_election, args=(mgr, elector, stop, 0.05),
        daemon=True)
    t.start()

    time.sleep(0.3)
    assert mgr.runs == 0  # standby: never ran without the lease

    elector.is_leader.set()  # acquire
    assert mgr.running.wait(timeout=3)

    elector.is_leader.clear()  # lose -> reconciling must stop
    deadline = time.time() + 3
    while time.time() < deadline and mgr.running.is_set():
        time.sleep(0.02)
    assert not mgr.running.is_set()
    assert mgr.runs == 1

    elector.is_leader.set()  # reacquire -> fresh run
    assert mgr.running.wait(timeout=3)
    assert mgr.runs == 2

    stop.set()
    elector.is_leader.clear()
    t.join(timeout=3)
