"""Unified observability subsystem tests (runbooks_tpu.obs).

Covers the ISSUE-5 acceptance surface: histogram bucket/quantile math,
promtool-style exposition lint over both the controller and serve
endpoints (every line parses, # TYPE precedes samples, counters end in
_total, proper content type), spec label escaping, trace JSONL
well-formedness under concurrent spans, goodput accounting across a
fault-injected restart, and the serve latency histograms populated via
the engine smoke path.
"""

import dataclasses
import json
import math
import os
import re
import threading
import urllib.request

import jax
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import init_params
from runbooks_tpu.obs import goodput as obs_goodput
from runbooks_tpu.obs import metrics as obs_metrics
from runbooks_tpu.obs import profile as obs_profile
from runbooks_tpu.obs import trace as obs_trace
from runbooks_tpu.obs.metrics import CONTENT_TYPE, Registry


def tiny_cfg():
    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype="float32",
    )


# ---------------------------------------------------------------------------
# Exposition lint (promtool-style): every line must parse, # TYPE must
# precede its family's samples, counters must end in _total.
# ---------------------------------------------------------------------------

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_COMMENT_RE = re.compile(
    rf"^# (HELP ({_NAME}) .+|TYPE ({_NAME}) (counter|gauge|histogram))$")
_LABEL_RE = re.compile(rf'({_NAME})="((?:[^"\\\n]|\\\\|\\"|\\n)*)"')
_SAMPLE_RE = re.compile(rf"^({_NAME})(\{{(.*)\}})? (\S+)$")


def lint_exposition(text: str):
    """Parse a Prometheus text exposition; assert structural validity.
    Returns {family: type}."""
    assert text.endswith("\n"), "exposition must end with a newline"
    types = {}
    seen_samples = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            m = _COMMENT_RE.match(line)
            assert m, f"malformed comment line: {line!r}"
            if m.group(3):  # TYPE
                name = m.group(3)
                assert name not in types, f"duplicate # TYPE for {name}"
                assert name not in seen_samples, \
                    f"# TYPE after samples for {name}"
                types[name] = m.group(4)
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, label_blob, value = m.group(1), m.group(3), m.group(4)
        float(value)  # must parse
        if label_blob:
            stripped = _LABEL_RE.sub("", label_blob).replace(",", "")
            assert stripped == "", \
                f"unparseable labels in {line!r}: leftover {stripped!r}"
        family = name
        if family not in types:
            for suffix in ("_bucket", "_sum", "_count"):
                base = name[: -len(suffix)] if name.endswith(suffix) else None
                if base and types.get(base) == "histogram":
                    family = base
                    break
        assert family in types, f"sample {name} has no preceding # TYPE"
        seen_samples.add(family)
        if types[family] == "counter":
            assert name.endswith("_total"), \
                f"counter {name} must end in _total"
        if types[family] == "histogram" and name.endswith("_bucket"):
            assert 'le="' in (label_blob or ""), \
                f"histogram bucket sample without le label: {line!r}"
    return types


# ---------------------------------------------------------------------------
# Metrics core
# ---------------------------------------------------------------------------

def test_histogram_buckets_sum_count_and_exposition():
    r = Registry()
    values = [0.0005, 0.003, 0.003, 0.04, 0.7, 20.0, 99.0]
    for v in values:
        r.observe("ttft_seconds", v, help_text="test hist")
    text = r.render()
    lint_exposition(text)
    # Cumulative bucket counts at selected bounds.
    assert 'ttft_seconds_bucket{le="0.001"} 1' in text
    assert 'ttft_seconds_bucket{le="0.005"} 3' in text
    assert 'ttft_seconds_bucket{le="0.05"} 4' in text
    assert 'ttft_seconds_bucket{le="1"} 5' in text
    assert 'ttft_seconds_bucket{le="30"} 6' in text
    # +Inf equals _count; 99.0 lives only there.
    assert 'ttft_seconds_bucket{le="+Inf"} 7' in text
    assert "ttft_seconds_count 7" in text
    assert f"ttft_seconds_sum {round(sum(values), 9)}" in text


def test_histogram_quantile_estimates():
    r = Registry()
    # 100 observations uniform in (0, 0.1]: the q-quantile should land
    # near q * 0.1 (bucket-interpolation error bounded by bucket width).
    for i in range(1, 101):
        r.observe("lat_seconds", i / 1000.0)
    for q in (0.5, 0.9, 0.99):
        est = r.quantile("lat_seconds", q)
        assert abs(est - q * 0.1) <= 0.026, (q, est)
    # Quantile of an empty/unknown series is NaN, not a crash.
    assert math.isnan(r.quantile("nope_seconds", 0.5))
    # Everything past the top bound clamps to the top finite bound.
    r2 = Registry()
    r2.observe("big_seconds", 1e6)
    assert r2.quantile("big_seconds", 0.99) == 30.0


def test_histogram_per_labelset_series():
    r = Registry()
    r.observe("disp_seconds", 0.002, bucket="16")
    r.observe("disp_seconds", 0.2, bucket="128")
    text = r.render()
    lint_exposition(text)
    assert 'disp_seconds_bucket{bucket="16",le="0.0025"} 1' in text
    assert 'disp_seconds_bucket{bucket="128",le="0.25"} 1' in text
    assert 'disp_seconds_count{bucket="16"} 1' in text


def test_label_escaping_per_spec():
    r = Registry()
    r.set_gauge("weird_gauge", 1, path='a"b\\c\nd')
    text = r.render()
    lint_exposition(text)
    # One line, with the three specials escaped exactly per the spec.
    assert 'weird_gauge{path="a\\"b\\\\c\\nd"} 1' in text
    assert "\nd" not in text.split("weird_gauge")[1].splitlines()[0]


def test_registry_type_lines_and_counter_naming():
    r = Registry()
    r.inc("controller_reconcile_total", kind="Model")
    r.set_gauge("queue_depth", 3, kind="Model")
    r.observe("reconcile_seconds", 0.01, kind="Model")
    types = lint_exposition(r.render())
    assert types["controller_reconcile_total"] == "counter"
    assert types["queue_depth"] == "gauge"
    assert types["reconcile_seconds"] == "histogram"
    assert types["process_uptime_seconds"] == "gauge"


def test_set_counter_mirrors_absolute_value():
    r = Registry()
    r.set_counter("serve_decode_steps_total", 41)
    r.set_counter("serve_decode_steps_total", 42)
    assert r.counter_value("serve_decode_steps_total") == 42.0
    assert "serve_decode_steps_total 42.0" in r.render()


def test_controller_metrics_reexport_and_http_content_type():
    """controller/metrics.py re-exports the obs registry, and its HTTP
    endpoint serves the spec content type (satellite: no bare
    text/plain)."""
    from runbooks_tpu.controller import metrics as controller_metrics

    assert controller_metrics.REGISTRY is obs_metrics.REGISTRY
    assert controller_metrics.Registry is Registry
    httpd = controller_metrics.serve_metrics(0)
    try:
        port = httpd.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
            assert resp.headers["Content-Type"] == CONTENT_TYPE
            body = resp.read().decode("utf-8")
        lint_exposition(body)
        assert "process_uptime_seconds" in body
    finally:
        httpd.shutdown()
        httpd.server_close()


# ---------------------------------------------------------------------------
# Trace spans
# ---------------------------------------------------------------------------

def read_trace_events(path):
    """Parse the trace file: a '[' header then one JSON event per line
    (trailing comma allowed — the Chrome JSON Array Format with the
    closing bracket omitted). Every line must parse."""
    events = []
    with open(path) as f:
        first = f.readline().strip()
        assert first == "[", "trace must open the JSON array"
        for line in f:
            line = line.strip().rstrip(",")
            if not line:
                continue
            events.append(json.loads(line))
    return events


def test_trace_spans_concurrent_writers(tmp_path, monkeypatch):
    monkeypatch.setenv("RBT_TRACE", "1")
    path = str(tmp_path / "trace.jsonl")
    obs_trace.configure(path)
    try:
        def worker(tid):
            for i in range(25):
                with obs_trace.span("phase", worker=tid, i=i):
                    pass
                obs_trace.instant("tick", worker=tid)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        obs_trace.close()
        obs_trace.configure(None)
    events = read_trace_events(path)
    spans = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    assert len(spans) == 100 and len(instants) == 100
    for e in events:
        assert isinstance(e["ts"], (int, float))
        assert {"name", "ph", "pid", "tid"} <= set(e)
    for e in spans:
        assert e["dur"] >= 0
    # All four writer identities present (no thread's events torn/lost).
    assert {e["args"]["worker"] for e in spans} == {0, 1, 2, 3}


def test_trace_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv("RBT_TRACE", raising=False)
    obs_trace.configure(str(tmp_path / "off.jsonl"))
    try:
        with obs_trace.span("x", a=1):
            pass
        obs_trace.instant("y")
    finally:
        obs_trace.configure(None)
    # RBT_TRACE off: nothing reaches the FILE (events still land in the
    # always-on flight ring, obs/flight.py).
    assert not os.path.exists(tmp_path / "off.jsonl")
    # With the flight recorder ALSO off, the span path hands back a
    # shared null context (no allocation at all).
    monkeypatch.setenv("RBT_FLIGHT", "0")
    assert obs_trace.span("a") is obs_trace.span("b")


# ---------------------------------------------------------------------------
# Goodput accounting
# ---------------------------------------------------------------------------

def test_goodput_tracker_math():
    g = obs_goodput.GoodputTracker()
    g.exclude(10.0, "restore")
    g.exclude(5.0, "compile")
    for _ in range(10):
        g.step(0.1, data_wait_s=0.02, ckpt_s=0.01)
    snap = g.snapshot()
    assert snap["restore_s"] == 10.0 and snap["compile_s"] == 5.0
    assert snap["productive_s"] == 1.0
    assert snap["data_wait_s"] == pytest.approx(0.2)
    assert snap["ckpt_s"] == pytest.approx(0.1)
    # Wall here is milliseconds while exclusions are 15s: the accountable
    # window is <= 0, which must clamp, not divide by a negative.
    assert 0.0 <= g.ratio() <= 1.0


# ---------------------------------------------------------------------------
# Trainer integration: step breakdown, goodput across a fault-injected
# restart, incremental atomic metrics.json, RBT_PROFILE_AT_STEP.
# ---------------------------------------------------------------------------

def _job(artifacts, steps=8, **kw):
    from runbooks_tpu.parallel.mesh import MeshConfig
    from runbooks_tpu.train.optimizer import OptimizerConfig
    from runbooks_tpu.train.trainer import TrainJobConfig

    return TrainJobConfig(
        model="debug", model_overrides={"dtype": "float32"},
        mesh=MeshConfig(data=2, fsdp=2, tensor=2),
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=0,
                                  total_steps=100, schedule="constant"),
        batch_size=4, seq_len=32, steps=steps, checkpoint_every=3,
        log_every=1, artifacts_dir=str(artifacts), **kw)


def test_goodput_excludes_restart_overhead_after_kill(tmp_path, monkeypatch):
    """Fault-injected restart (RBT_FAULT_INJECT=kill): the resumed run's
    goodput must exclude restore + recompile from the accountable window
    — and metrics.json must already exist after the kill (incremental
    atomic writes), not only at job end."""
    from runbooks_tpu.train.trainer import SimulatedFault, run_training

    monkeypatch.setenv("RBT_FAULT_INJECT", "kill:5")
    with pytest.raises(SimulatedFault):
        run_training(_job(tmp_path))
    monkeypatch.delenv("RBT_FAULT_INJECT")

    # Satellite: the killed run's metrics survived (written incrementally,
    # atomically) even though the process died mid-run.
    mpath = os.path.join(str(tmp_path), "metrics.json")
    assert os.path.exists(mpath)
    partial = json.load(open(mpath))
    assert partial["in_progress"] is True
    assert [e["step"] for e in partial["history"]] == [1, 2, 3, 4, 5]
    assert not os.path.exists(mpath + ".tmp")  # temp never left behind

    resumed = run_training(_job(tmp_path))
    detail = resumed["goodput_detail"]
    # Restore and recompile really happened on resume...
    assert resumed["restore_time_s"] > 0
    assert detail["restore_s"] > 0 and detail["compile_s"] > 0
    # ...and the ratio is computed over wall MINUS that restart overhead:
    accountable = detail["wall_s"] - detail["restore_s"] - detail["compile_s"]
    assert accountable > 0
    want = min(detail["productive_s"] / accountable, 1.0)
    assert resumed["goodput"] == pytest.approx(want, rel=0.05)
    # On CPU the recompile dominates wall: the naive ratio (productive /
    # raw wall) would be far smaller — the exclusion is load-bearing.
    naive = detail["productive_s"] / detail["wall_s"]
    assert resumed["goodput"] > naive
    # Per-step breakdown present in every post-compile history entry and
    # in the file (the compile step is excluded wholesale from goodput, so
    # its entry carries compile_time_s instead of a breakdown).
    final = json.load(open(mpath))
    assert "in_progress" not in final
    assert final["history"][0]["compile_time_s"] > 0
    breakdown = final["history"][1:]
    assert breakdown, "no steady-state entries logged"
    for entry in breakdown:
        assert entry["data_wait_s"] >= 0
        assert entry["step_s"] > 0
        assert 0 <= entry["goodput"] <= 1


def test_trainer_trace_file_loads(tmp_path, monkeypatch):
    """RBT_TRACE=1 training writes a Perfetto-loadable trace.jsonl with
    the step-phase spans (data_wait, step, checkpoint)."""
    from runbooks_tpu.train.trainer import run_training

    monkeypatch.setenv("RBT_TRACE", "1")
    run_training(_job(tmp_path, steps=4))
    events = read_trace_events(tmp_path / "trace.jsonl")
    names = {e["name"] for e in events}
    assert {"data_wait", "step", "checkpoint"} <= names
    steps_traced = {e["args"]["step"] for e in events
                    if e["name"] == "step"}
    assert steps_traced == {0, 1, 2, 3}


def test_profile_at_step_env_capture(tmp_path, monkeypatch):
    from runbooks_tpu.train.trainer import run_training

    monkeypatch.setenv("RBT_PROFILE_AT_STEP", "2:2")
    run_training(_job(tmp_path, steps=4))
    prof = tmp_path / "profiles" / "step2"
    assert prof.is_dir()
    files = [os.path.join(r, f) for r, _, fs in os.walk(prof) for f in fs]
    assert files, "profiler capture produced no files"


def test_parse_profile_at_step_validation():
    assert obs_profile.parse_profile_at_step("7") == (7, 1)
    assert obs_profile.parse_profile_at_step("7:3") == (7, 3)
    assert obs_profile.parse_profile_at_step("") is None
    with pytest.raises(ValueError):
        obs_profile.parse_profile_at_step("x")
    with pytest.raises(ValueError):
        obs_profile.parse_profile_at_step("3:0")


def test_profiler_busy_guard(tmp_path):
    p = obs_profile.Profiler()
    d = p.start(str(tmp_path / "cap"))
    try:
        with pytest.raises(obs_profile.ProfilerBusy):
            p.start(str(tmp_path / "cap2"))
    finally:
        assert p.stop() == d
    assert p.stop() is None  # idempotent


# ---------------------------------------------------------------------------
# Serve latency histograms via the engine smoke path + /metrics exposition
# ---------------------------------------------------------------------------

def test_engine_smoke_populates_latency_histograms():
    from runbooks_tpu.serve.engine import InferenceEngine, Request

    reg = obs_metrics.REGISTRY
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=2, seed=0)
    before_ttft = _hist_count(reg, "serve_ttft_seconds")
    before_inter = _hist_count(reg, "serve_inter_token_seconds")
    reqs = [Request(prompt_tokens=[1, 2, 3], max_tokens=4)
            for _ in range(3)]
    engine.generate(reqs)
    assert all(len(r.output_tokens) == 4 for r in reqs)
    assert _hist_count(reg, "serve_ttft_seconds") == before_ttft + 3
    # 3 requests x 3 non-first tokens each.
    assert _hist_count(reg, "serve_inter_token_seconds") \
        == before_inter + 9
    text = reg.render()
    lint_exposition(text)
    for family in ("serve_ttft_seconds", "serve_inter_token_seconds",
                   "serve_queue_wait_seconds",
                   "serve_request_duration_seconds",
                   "serve_prefill_dispatch_seconds",
                   "serve_decode_dispatch_seconds"):
        assert f"# TYPE {family} histogram" in text
        assert f"{family}_bucket" in text


def _hist_count(reg, name, **labels):
    total = 0
    with reg._lock:
        for (hname, _), hist in reg._hists.items():
            if hname == name:
                total += hist.count
    return total


def test_http_metrics_renders_from_registry_with_content_type():
    """GET /metrics on the serve API: rendered by runbooks_tpu.obs (no
    hand-built metric strings), proper content type, lints clean, and
    includes the TTFT/inter-token histogram series."""
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    app = create_server(cfg, params, max_slots=2)

    async def drive():
        import asyncio  # noqa: F401

        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "hello", "max_tokens": 4, "temperature": 0.0})
            assert r.status == 200
            r = await client.get("/metrics")
            assert r.status == 200
            assert r.headers["Content-Type"] == CONTENT_TYPE
            text = await r.text()
            types = lint_exposition(text)
            assert types["serve_requests_total"] == "counter"
            assert types["serve_ttft_seconds"] == "histogram"
            assert types["serve_inter_token_seconds"] == "histogram"
            for series in ("serve_ttft_seconds_bucket",
                           "serve_ttft_seconds_sum",
                           "serve_ttft_seconds_count",
                           "serve_inter_token_seconds_bucket",
                           "serve_inter_token_seconds_sum",
                           "serve_inter_token_seconds_count"):
                assert series in text, series
            assert "serve_requests_total 1" in text
            assert "serve_tokens_generated_total 4" in text

    import asyncio

    asyncio.run(drive())


def test_http_debug_profile_endpoint(tmp_path, monkeypatch):
    """POST /debug/profile?seconds=N captures a trace under
    {artifacts}/profiles/ and rejects concurrent/malformed captures."""
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    monkeypatch.setenv("RBT_CONTENT_DIR", str(tmp_path))
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    app = create_server(cfg, params, max_slots=2)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/debug/profile?seconds=0.2")
            assert r.status == 200
            body = await r.json()
            assert body["seconds"] == 0.2
            assert os.path.isdir(body["path"])
            files = [f for _, _, fs in os.walk(body["path"]) for f in fs]
            assert files, "capture produced no files"
            r = await client.post("/debug/profile?seconds=oops")
            assert r.status == 400
            r = await client.post("/debug/profile?seconds=0")
            assert r.status == 400
            r = await client.post("/debug/profile?seconds=9999")
            assert r.status == 400

    import asyncio

    asyncio.run(drive())


# ---------------------------------------------------------------------------
# Controller exposition end-to-end (manager increments -> lint)
# ---------------------------------------------------------------------------

def test_controller_reconcile_metrics_lint():
    from runbooks_tpu.controller.metrics import REGISTRY

    REGISTRY.inc("controller_reconcile_total", kind="Model")
    REGISTRY.observe("controller_reconcile_seconds", 0.004, kind="Model")
    text = REGISTRY.render()
    types = lint_exposition(text)
    assert types["controller_reconcile_total"] == "counter"
    assert types["controller_reconcile_seconds"] == "histogram"
