"""Multi-tenant batched LoRA serving tests (docs/multi-tenant-lora.md).

Parity discipline:

- **float32**: the pooled engine's runtime delta ``x@W + (x@A)@B`` and
  the merged oracle's ``x@(W + s·AB)`` agree to f32 rounding, so a
  heterogeneous-adapter batch is token-for-token identical to dedicated
  per-adapter MERGED-weights engines (the load-time fold path — the
  acceptance oracle).
- **bf16 / int8-quantized base**: folding rounds ``W + ΔW`` at weight
  precision while the runtime path keeps W exact and adds a bf16 delta —
  mathematically equal, numerically ~2^-8 apart, so greedy argmax on a
  random tiny model diverges mid-rollout. At serving precision the
  invariant that must hold exactly is BATCHING NEUTRALITY: a tenant's
  output in a heterogeneous multi-tenant batch is token-for-token what a
  single-tenant engine (same precision, same delta arithmetic) produces,
  dense AND paged (the same engine-vs-engine discipline the paged-KV
  parity tests use). The merged oracle still pins the prefill argmax
  (first token), which survives the rounding gap on these seeds.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import forward, init_params
from runbooks_tpu.ops.quantization import quantize_params
from runbooks_tpu.serve.engine import (
    EngineOverloaded,
    InferenceEngine,
    Request,
)
from runbooks_tpu.serve.lora_pool import (
    AdapterLoadError,
    AdapterPool,
    load_adapter_tree,
    save_adapter,
)
from runbooks_tpu.serve.paging import PagedInferenceEngine
from runbooks_tpu.train.lora import LoraConfig, apply_lora, init_lora


def tiny_cfg(dtype="float32", **over):
    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype=dtype, param_dtype="float32",
        adapter_pool=4, lora_rank=8, **over)


N_ADAPTERS = 4
PROMPTS = [[5, 9, 17], [3, 4, 5, 6, 7], [40, 2], [8, 8, 8, 9]]


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    """Base params + four distinct rank-4 adapters saved as artifacts,
    plus their merged-weights parameter trees."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    root = tmp_path_factory.mktemp("adapters")
    paths, merged, loras = [], [], []
    for i in range(N_ADAPTERS):
        lcfg = LoraConfig(rank=4, alpha=8.0)
        lora = init_lora(params, lcfg, jax.random.key(10 + i))
        # B inits to zero (delta = 0); perturb so each adapter actually
        # changes the model, distinctly per tenant.
        lora = jax.tree.map(
            lambda x, i=i: x + 0.03 * jax.random.normal(
                jax.random.key(20 + i), x.shape, x.dtype), lora)
        path = os.path.join(str(root), f"tenant{i}")
        save_adapter(path, lora, rank=4, alpha=8.0)
        paths.append(path)
        loras.append((lora, lcfg))
        merged.append(apply_lora(params, lora, lcfg))
    return cfg, params, paths, merged, loras


def _reqs(paths, max_tokens=8):
    return [Request(prompt_tokens=list(p), max_tokens=max_tokens,
                    temperature=0.0, adapter=a)
            for p, a in zip(PROMPTS, paths)]


# ---------------------------------------------------------------------------
# Heterogeneous-batch parity vs the merged-weights oracle (float32 exact)
# ---------------------------------------------------------------------------

def test_heterogeneous_batch_parity_dense(world):
    """Four distinct adapters concurrently on ONE dense engine ==
    token-for-token four dedicated merged-weights engines."""
    cfg, params, paths, merged, _ = world
    pooled = InferenceEngine(cfg, params, max_slots=N_ADAPTERS)
    reqs = _reqs(paths)
    for r in reqs:
        pooled.submit(r)
    pooled.step()
    # One admission tick filled every slot: heterogeneous tenants rode
    # the same batched dispatch, not one dispatch per tenant.
    assert int(pooled.active.sum()) == N_ADAPTERS
    while pooled.has_work():
        pooled.step()
    for prompt, m, r in zip(PROMPTS, merged, reqs):
        dedicated = InferenceEngine(cfg, m, max_slots=N_ADAPTERS)
        oracle = Request(prompt_tokens=list(prompt), max_tokens=8,
                         temperature=0.0)
        dedicated.generate([oracle])
        assert r.output_tokens == oracle.output_tokens, r.adapter
    stats = pooled.adapter_stats()
    assert stats["loads"] == N_ADAPTERS
    assert sorted(stats["resident"]) == sorted(paths)


def test_heterogeneous_batch_parity_paged(world):
    cfg, params, paths, merged, _ = world
    pooled = PagedInferenceEngine(cfg, params, max_slots=N_ADAPTERS,
                                  page_size=8)
    reqs = _reqs(paths)
    pooled.generate(reqs)
    for prompt, m, r in zip(PROMPTS, merged, reqs):
        dedicated = InferenceEngine(cfg, m, max_slots=N_ADAPTERS)
        oracle = Request(prompt_tokens=list(prompt), max_tokens=8,
                         temperature=0.0)
        dedicated.generate([oracle])
        assert r.output_tokens == oracle.output_tokens, r.adapter


def test_mixed_base_and_adapter_traffic_one_dispatch(world):
    """Base-only rows (trash lane) and tenant rows share one batch; the
    base rows are BITWISE the no-pool engine's output."""
    cfg, params, paths, merged, _ = world
    pooled = InferenceEngine(cfg, params, max_slots=3)
    reqs = [
        Request(prompt_tokens=[5, 9, 17], max_tokens=8, temperature=0.0,
                adapter=paths[0]),
        Request(prompt_tokens=[3, 4, 5, 6], max_tokens=8,
                temperature=0.0),
        Request(prompt_tokens=[42, 11], max_tokens=8, temperature=0.0,
                adapter=paths[1]),
    ]
    pooled.generate(reqs)
    plain = InferenceEngine(dataclasses.replace(cfg, adapter_pool=0),
                            params, max_slots=3)
    base_oracle = Request(prompt_tokens=[3, 4, 5, 6], max_tokens=8,
                          temperature=0.0)
    plain.generate([base_oracle])
    assert reqs[1].output_tokens == base_oracle.output_tokens
    for i, m in ((0, merged[0]), (2, merged[1])):
        dedicated = InferenceEngine(cfg, m, max_slots=3)
        oracle = Request(prompt_tokens=list(reqs[i].prompt_tokens),
                         max_tokens=8, temperature=0.0)
        dedicated.generate([oracle])
        assert reqs[i].output_tokens == oracle.output_tokens
        # Adapters actually changed the model (deltas not silently zero).
        assert reqs[i].output_tokens != base_oracle.output_tokens or \
            reqs[i].prompt_tokens != base_oracle.prompt_tokens


# ---------------------------------------------------------------------------
# Serving-precision axes: bf16 and int8-quantized base
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("quantize", ["none", "int8"])
@pytest.mark.parametrize("engine_cls", ["dense", "paged"])
def test_batching_neutrality_bf16_and_int8(world, quantize, engine_cls):
    """bf16 / int8-base: each tenant's output in the heterogeneous batch
    == a single-tenant engine at the same precision, and the prefill
    argmax (first token) == the merged-weights oracle."""
    _, params, paths, _, loras = world
    cfg = tiny_cfg("bfloat16", quantize=quantize)
    # quantize_params packs IN PLACE (deliberate — bounds the load-time
    # f32 footprint); copy the tree structure so the module-scoped
    # fixture's params stay float for the tests after this one.
    eng_params = (quantize_params(jax.tree.map(lambda x: x, params),
                                  quantize)
                  if quantize != "none" else params)

    def make(pool):
        c = dataclasses.replace(cfg, adapter_pool=pool)
        if engine_cls == "paged":
            return PagedInferenceEngine(c, eng_params,
                                        max_slots=N_ADAPTERS, page_size=8)
        return InferenceEngine(c, eng_params, max_slots=N_ADAPTERS)

    multi = make(N_ADAPTERS)
    reqs = _reqs(paths)
    multi.generate(reqs)
    for prompt, path, (lora, lcfg), r in zip(PROMPTS, paths, loras, reqs):
        solo = make(1)
        oracle = Request(prompt_tokens=list(prompt), max_tokens=8,
                         temperature=0.0, adapter=path)
        solo.generate([oracle])
        assert r.output_tokens == oracle.output_tokens, path
        if quantize == "none":
            # Merged-oracle prefill argmax (weight-fold rounding is far
            # smaller than the first token's logit gap on these seeds).
            m = apply_lora(params, lora, lcfg)
            logits, _ = forward(cfg, m, jnp.asarray([prompt], jnp.int32))
            assert r.output_tokens[0] == int(jnp.argmax(logits[0, -1]))


# ---------------------------------------------------------------------------
# Pool residency: eviction, page-back-in, refcount pinning
# ---------------------------------------------------------------------------

def test_pool_eviction_and_page_back_in(world):
    """pool=2 serving 3 tenants round-robin: LRU eviction under
    pressure, page-back-in on return, correctness after reload."""
    cfg, params, paths, merged, _ = world
    eng = InferenceEngine(dataclasses.replace(cfg, adapter_pool=2),
                          params, max_slots=2)
    expected = []
    for prompt, m in zip(PROMPTS[:3], merged[:3]):
        dedicated = InferenceEngine(cfg, m, max_slots=2)
        oracle = Request(prompt_tokens=list(prompt), max_tokens=6,
                         temperature=0.0)
        dedicated.generate([oracle])
        expected.append(oracle.output_tokens)
    # Two full rounds over 3 tenants in a 2-lane pool.
    for _round in range(2):
        for i in range(3):
            r = Request(prompt_tokens=list(PROMPTS[i]), max_tokens=6,
                        temperature=0.0, adapter=paths[i])
            eng.generate([r])
            assert r.output_tokens == expected[i], (
                _round, i, eng.adapter_stats())
    stats = eng.adapter_stats()
    assert stats["evictions"] >= 3          # 3 tenants churned 2 lanes
    assert stats["loads"] >= 5              # reloads after eviction
    assert len(stats["resident"]) == 2


def test_pool_refcount_pins_active_lane(world):
    """An adapter pinned by an in-flight request is never the eviction
    victim; releasing it at finish frees the lane."""
    cfg, params, paths, _, _ = world
    pool = AdapterPool(dataclasses.replace(cfg, adapter_pool=2))
    lane_a = pool.acquire(paths[0])
    lane_b = pool.acquire(paths[1])
    assert {lane_a, lane_b} == {0, 1}
    # Both pinned: a third adapter cannot enter.
    assert pool.acquire(paths[2]) is None
    pool.release(lane_a)
    lane_c = pool.acquire(paths[2])
    assert lane_c == lane_a                 # LRU victim was the freed lane
    assert pool.evictions == 1
    stats = pool.stats()
    assert paths[0] not in stats["resident"]
    assert paths[1] in stats["resident"] and paths[2] in stats["resident"]


def test_admission_429_on_pool_exhaustion(world):
    """Every lane pinned by in-flight decodes: new tenants queue, the
    queue backs up, submit() sheds with the typed 429 — and the queued
    tenant is served once a lane frees."""
    cfg, params, paths, merged, _ = world
    eng = InferenceEngine(dataclasses.replace(cfg, adapter_pool=1),
                          params, max_slots=2, max_queue=2)
    long_req = Request(prompt_tokens=[5, 9, 17], max_tokens=30,
                       temperature=0.0, adapter=paths[0])
    eng.submit(long_req)
    eng.step()                              # adapter 0 pinned by slot
    assert eng.active.any()
    waiting = Request(prompt_tokens=[40, 2], max_tokens=4,
                      temperature=0.0, adapter=paths[1])
    eng.submit(waiting)
    eng.step()
    assert not waiting.finished and waiting in eng.queue  # lane pinned
    eng.submit(Request(prompt_tokens=[1, 2], max_tokens=4,
                       temperature=0.0, adapter=paths[1]))
    with pytest.raises(EngineOverloaded):
        eng.submit(Request(prompt_tokens=[1, 2], max_tokens=4,
                           temperature=0.0, adapter=paths[1]))
    while eng.has_work():
        eng.step()
    assert long_req.finished and waiting.finished
    dedicated = InferenceEngine(cfg, merged[1], max_slots=2)
    oracle = Request(prompt_tokens=[40, 2], max_tokens=4, temperature=0.0)
    dedicated.generate([oracle])
    assert waiting.output_tokens == oracle.output_tokens


# ---------------------------------------------------------------------------
# Compile discipline
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("engine_cls", ["dense", "paged"])
def test_zero_unexpected_compiles_steady_adapter_swapping(world,
                                                          engine_cls):
    """Warmed pooled engine: a steady loop that swaps adapters (loads,
    evictions, lane churn, mixed base traffic) performs ZERO XLA
    compiles — pool geometry is static and lane indices are operands."""
    from runbooks_tpu.obs import device as obs_device

    cfg, params, paths, _, _ = world
    c = dataclasses.replace(cfg, adapter_pool=2)
    if engine_cls == "paged":
        eng = PagedInferenceEngine(c, params, max_slots=2, page_size=8)
    else:
        eng = InferenceEngine(c, params, max_slots=2)
    sentinel = obs_device.SENTINEL
    if not sentinel.install():
        pytest.skip("jax.monitoring unavailable; sentinel cannot verify")
    eng.warmup()
    before_unexpected = sentinel.unexpected
    before_total = sentinel.total
    try:
        for i in range(6):
            r = Request(prompt_tokens=list(PROMPTS[i % 4]), max_tokens=4,
                        temperature=0.0,
                        adapter=paths[i % 3] if i % 4 else None)
            eng.generate([r])
            assert r.finished and r.finish_reason != "error"
        stats = eng.adapter_stats()
        assert stats["evictions"] >= 1      # the loop really churned
        assert sentinel.total == before_total, "compiled under traffic"
        assert sentinel.unexpected == before_unexpected
    finally:
        eng.release_steady()


# ---------------------------------------------------------------------------
# Validation + artifact loading
# ---------------------------------------------------------------------------

def test_adapter_request_without_pool_rejected(world):
    cfg, params, paths, _, _ = world
    eng = InferenceEngine(dataclasses.replace(cfg, adapter_pool=0),
                          params, max_slots=2)
    with pytest.raises(ValueError, match="no adapter pool"):
        eng.submit(Request(prompt_tokens=[1, 2], adapter=paths[0]))


def test_unknown_adapter_path_rejected_at_submit(world):
    cfg, params, _, _, _ = world
    eng = InferenceEngine(cfg, params, max_slots=2)
    with pytest.raises(ValueError, match="no such directory"):
        eng.submit(Request(prompt_tokens=[1, 2],
                           adapter="/does/not/exist"))


def test_rank_above_bucket_rejected(world, tmp_path):
    """rank > pool bucket cannot pad — load refuses with a clear error
    (lane shapes are static program shapes)."""
    cfg, params, _, _, _ = world
    lcfg = LoraConfig(rank=16, alpha=16.0)
    lora = init_lora(params, lcfg, jax.random.key(7))
    path = str(tmp_path / "bigrank")
    save_adapter(path, lora, rank=16, alpha=16.0)
    with pytest.raises(AdapterLoadError, match="rank 16 exceeds"):
        load_adapter_tree(path, cfg, cfg.lora_targets, cfg.lora_rank)


def test_malformed_artifact_raises_typed_error(world, tmp_path):
    """A structurally broken artifact (target values that are not
    {a, b} trees) raises AdapterLoadError — never a raw KeyError that
    would escape the engine's per-request handling into the worker's
    crash-and-reset path."""
    cfg, params, _, _, _ = world
    from runbooks_tpu.train.checkpoint import CheckpointManager

    path = str(tmp_path / "broken")
    mgr = CheckpointManager(path)
    try:
        mgr.save(0, {"params": {
            "attn.wq": np.zeros((2, 64, 4), np.float32)}}, force=True)
        mgr.wait()
    finally:
        mgr.close()
    with pytest.raises(AdapterLoadError, match="not an .a, b. LoRA"):
        load_adapter_tree(path, cfg, cfg.lora_targets, cfg.lora_rank)
    # And end to end: the engine finishes the request with an error
    # instead of crashing the loop (load fails only at admission — the
    # artifact dir itself looks valid to the cheap submit-time probe).
    eng = InferenceEngine(cfg, params, max_slots=2)
    r = Request(prompt_tokens=[1, 2, 3], max_tokens=4, temperature=0.0,
                adapter=path)
    eng.generate([r])
    assert r.finished and r.finish_reason == "error"
    ok = Request(prompt_tokens=[1, 2, 3], max_tokens=4, temperature=0.0)
    eng.generate([ok])          # the engine still serves
    assert ok.finish_reason == "length"


def test_small_rank_pads_exactly(world, tmp_path):
    """A rank-2 adapter in a rank-8 pool serves exactly its own merged
    oracle (zero-padding contributes nothing)."""
    cfg, params, _, _, _ = world
    lcfg = LoraConfig(rank=2, alpha=4.0)
    lora = init_lora(params, lcfg, jax.random.key(8))
    lora = jax.tree.map(
        lambda x: x + 0.05 * jax.random.normal(jax.random.key(9),
                                               x.shape, x.dtype), lora)
    path = str(tmp_path / "r2")
    save_adapter(path, lora, rank=2, alpha=4.0)
    eng = InferenceEngine(cfg, params, max_slots=2)
    r = Request(prompt_tokens=[5, 9, 17], max_tokens=6, temperature=0.0,
                adapter=path)
    eng.generate([r])
    dedicated = InferenceEngine(cfg, apply_lora(params, lora, lcfg),
                                max_slots=2)
    oracle = Request(prompt_tokens=[5, 9, 17], max_tokens=6,
                     temperature=0.0)
    dedicated.generate([oracle])
    assert r.output_tokens == oracle.output_tokens


def test_load_model_folds_adapter_when_pool_off(world, tmp_path,
                                                monkeypatch):
    """Baseline single-adapter path: `adapter: <path>` with the pool off
    folds at load (serve/api.load_model) — the parity oracle."""
    from runbooks_tpu.serve.api import load_model

    monkeypatch.setenv("RBT_CONTENT_DIR", str(tmp_path / "content"))
    cfg = get_config("debug")
    base = jax.jit(lambda r: init_params(cfg, r))(jax.random.key(0))
    lcfg = LoraConfig(rank=4, alpha=8.0)
    lora = init_lora(base, lcfg, jax.random.key(3))
    lora = jax.tree.map(
        lambda x: x + 0.02 * jax.random.normal(jax.random.key(4),
                                               x.shape, x.dtype), lora)
    path = str(tmp_path / "fold-adapter")
    save_adapter(path, lora, rank=4, alpha=8.0)
    got_cfg, got_params = load_model({"model": "debug", "seed": 0,
                                      "adapter": path})
    want = apply_lora(base, lora, lcfg)
    for a, b in zip(jax.tree.leaves(got_params), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-5, atol=2e-5)
    assert got_cfg.name == "debug"


def test_paged_radix_respects_adapter_namespaces(world):
    """Same prompt prefix, different adapters: pages never cross tenants
    (the K/V differ per adapter); same adapter reuses pages."""
    cfg, params, paths, merged, _ = world
    eng = PagedInferenceEngine(cfg, params, max_slots=2, page_size=8)
    long_prompt = list(range(1, 25))
    r1 = Request(prompt_tokens=long_prompt + [30], max_tokens=3,
                 temperature=0.0, adapter=paths[0])
    eng.generate([r1])
    before = eng.pager.pages_reused_total
    r2 = Request(prompt_tokens=long_prompt + [31], max_tokens=3,
                 temperature=0.0, adapter=paths[0])
    eng.generate([r2])
    assert eng.pager.pages_reused_total > before  # same-tenant reuse
    before = eng.pager.pages_reused_total
    r3 = Request(prompt_tokens=long_prompt + [31], max_tokens=3,
                 temperature=0.0, adapter=paths[1])
    eng.generate([r3])
    assert eng.pager.pages_reused_total == before  # tenant isolation
    dedicated = InferenceEngine(cfg, merged[1], max_slots=2)
    oracle = Request(prompt_tokens=long_prompt + [31], max_tokens=3,
                     temperature=0.0)
    dedicated.generate([oracle])
    assert r3.output_tokens == oracle.output_tokens


def test_sharded_adapter_engine_matches_unsharded(world):
    """Tensor-sharded serving mesh + adapter pool: the pool device_puts
    by its logical axes and the grouped delta runs SPMD — outputs match
    the meshless engine token for token."""
    from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg, params, paths, _, _ = world
    plain = InferenceEngine(cfg, params, max_slots=2)
    r0 = Request(prompt_tokens=[5, 9, 17], max_tokens=6, temperature=0.0,
                 adapter=paths[0])
    plain.generate([r0])
    sharded = InferenceEngine(cfg, params, max_slots=2,
                              mesh=make_mesh(MeshConfig(tensor=2)))
    r1 = Request(prompt_tokens=[5, 9, 17], max_tokens=6, temperature=0.0,
                 adapter=paths[0])
    sharded.generate([r1])
    assert r0.output_tokens == r1.output_tokens


# ---------------------------------------------------------------------------
# HTTP surface + metrics
# ---------------------------------------------------------------------------

def test_http_adapter_field_and_metrics(world):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg, params, paths, _, _ = world
    app = create_server(cfg, params, max_slots=2, adapter_pool=2)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "hello", "max_tokens": 3, "temperature": 0.0,
                "adapter": paths[0]})
            assert r.status == 200
            body = await r.json()
            assert body["choices"][0]["finish_reason"] == "length"
            # Unknown adapter -> 400, not a hung engine.
            r = await client.post("/v1/completions", json={
                "prompt": "x", "max_tokens": 2,
                "adapter": "/no/such/adapter"})
            assert r.status == 400
            r = await client.post("/v1/completions", json={
                "prompt": "x", "max_tokens": 2, "adapter": 7})
            assert r.status == 400
            r = await client.get("/metrics")
            text = await r.text()
            assert "serve_adapter_loads_total 1" in text
            assert "serve_adapters_resident 1" in text
            assert 'serve_adapter_requests_total{adapter="' in text
            r = await client.get("/debug/programs")
            body = await r.json()
            assert body["adapters"]["pool_size"] == 2
            assert body["adapters"]["loads"] == 1
    asyncio.run(drive())
    # Pool-less engines export no adapter families (catalog contract:
    # the families exist exactly on pooled engines). Fresh registry: the
    # process-wide one still carries the pooled server's series.
    from runbooks_tpu.obs import metrics as obs_metrics

    obs_metrics.REGISTRY.reset()
    plain = create_server(dataclasses.replace(cfg, adapter_pool=0),
                          params, max_slots=2)

    async def drive_plain():
        async with TestClient(TestServer(plain)) as client:
            r = await client.get("/metrics")
            text = await r.text()
            assert "serve_adapter_loads_total" not in text
    asyncio.run(drive_plain())


# ---------------------------------------------------------------------------
# Controller: validation + shared-engine tenants
# ---------------------------------------------------------------------------

def test_validate_params_adapter_knobs():
    from runbooks_tpu.controller.common import validate_params

    assert validate_params({"adapter_pool": 8, "lora_rank": 16,
                            "adapter_dir": "/srv/adapters"}) is None
    assert validate_params({"adapter": "tenants/a"}) is None
    assert validate_params({"adapterPool": 4}) is None
    assert "adapter_pool" in validate_params({"adapter_pool": -1})
    assert "lora_rank" in validate_params({"adapter_pool": 2,
                                           "lora_rank": 0})
    # Pool-tuning knobs without a pool are spec typos, not silent no-ops.
    assert "only applies" in validate_params({"lora_rank": 8})
    assert "only applies" in validate_params({"adapter_dir": "/srv/a"})
    assert "adapter" in validate_params({"adapter": "  "})
    assert "adapter" in validate_params({"adapter": 3})
    # Fold-at-load and the pool are mutually exclusive serving modes on
    # one Server (tenants reference the pool host via engineRef).
    assert "cannot combine" in validate_params(
        {"adapter": "tenants/a", "adapter_pool": 4})


def test_shared_engine_tenant_reconcile():
    from runbooks_tpu.api import conditions as cond
    from runbooks_tpu.api.types import API_VERSION, Server
    from runbooks_tpu.cloud.base import CommonConfig
    from runbooks_tpu.cloud.local import LocalCloud
    from runbooks_tpu.controller.manager import Ctx, Manager
    from runbooks_tpu.controller.server import ServerReconciler
    from runbooks_tpu.k8s import objects as ko
    from runbooks_tpu.k8s.fake import FakeCluster
    from runbooks_tpu.sci.base import FakeSCI

    client = FakeCluster()
    cloud = LocalCloud(CommonConfig(cluster_name="t",
                                    artifact_bucket_url="file:///tmp/b",
                                    registry_url="r.local:5000"))
    mgr = Manager(Ctx(client=client, cloud=cloud, sci=FakeSCI()),
                  [ServerReconciler()])

    tenant = Server.new("tenant-a", spec={
        "engineRef": "pool-host",
        "params": {"adapter": "tenants/a"}})
    client.create(tenant.obj)
    mgr.reconcile_until_stable()
    cur = Server(client.get(API_VERSION, "Server", "default", "tenant-a"))
    c = ko.get_condition(cur.obj, cond.SERVING)
    assert c["reason"] == cond.REASON_ENGINE_NOT_FOUND

    # Host exists but runs no pool: the tenant's per-request adapter
    # would 400 on every call — surface it.
    host = Server.new("pool-host", spec={
        "image": "img", "model": {"name": "m"}, "params": {}})
    client.create(host.obj)
    mgr.reconcile_until_stable()
    cur = Server(client.get(API_VERSION, "Server", "default", "tenant-a"))
    c = ko.get_condition(cur.obj, cond.SERVING)
    assert c["reason"] == cond.REASON_ENGINE_NO_POOL

    host.obj["spec"]["params"] = {"adapter_pool": 8}
    client.apply(host.obj, "test")
    mgr.reconcile_until_stable()
    cur = Server(client.get(API_VERSION, "Server", "default", "tenant-a"))
    c = ko.get_condition(cur.obj, cond.SERVING)
    assert c["reason"] == cond.REASON_ENGINE_NOT_READY

    # Host flips ready: the tenant serves through it — via a Service
    # aliasing the HOST's replica pods, with NO tenant Deployment.
    hcur = client.get(API_VERSION, "Server", "default", "pool-host")
    hcur.setdefault("status", {})["ready"] = True
    client.update_status(hcur)
    mgr.reconcile_until_stable()
    cur = Server(client.get(API_VERSION, "Server", "default", "tenant-a"))
    assert cur.ready
    c = ko.get_condition(cur.obj, cond.SERVING)
    assert c["status"] == "True"
    svc = client.get("v1", "Service", "default", "tenant-a")
    assert svc["spec"]["selector"] == {"server": "pool-host",
                                      "role": "run"}
    assert client.get("apps/v1", "Deployment", "default",
                      "tenant-a") is None

    # Tenant without an adapter param is invalid, not silently base.
    bad = Server.new("tenant-bad", spec={"engineRef": "pool-host",
                                         "params": {}})
    client.create(bad.obj)
    mgr.reconcile_until_stable()
    cur = Server(client.get(API_VERSION, "Server", "default",
                            "tenant-bad"))
    c = ko.get_condition(cur.obj, cond.SERVING)
    assert c["reason"] == cond.REASON_INVALID_PARAMS

    # A host EVENT fans out to its tenants (DEPENDENT_INDEXES maps the
    # plain-string engineRef): the watch path, without a full resync.
    hcur = client.get(API_VERSION, "Server", "default", "pool-host")
    hcur["status"]["ready"] = False
    client.update_status(hcur)
    mgr._reconcile_dependents("Server", hcur)
    cur = Server(client.get(API_VERSION, "Server", "default", "tenant-a"))
    assert not cur.ready
    c = ko.get_condition(cur.obj, cond.SERVING)
    assert c["reason"] == cond.REASON_ENGINE_NOT_READY

    # Host deletion: the delete event re-reconciles the tenant, which
    # flips to SharedEngineNotFound instead of staying stale-ready.
    client.delete(API_VERSION, "Server", "default", "pool-host")
    mgr._reconcile_dependents("Server", hcur)
    cur = Server(client.get(API_VERSION, "Server", "default", "tenant-a"))
    c = ko.get_condition(cur.obj, cond.SERVING)
    assert c["reason"] == cond.REASON_ENGINE_NOT_FOUND
