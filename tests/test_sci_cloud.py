"""Mock-SDK tests for the GCP/AWS SCI implementations.

Round-1 gap (VERDICT item 9): sci/gcp.py and sci/aws.py logic had never
executed anywhere (the SDKs are not in this image). These tests monkeypatch
the lazy SDK import seams (_require_google / _boto3) and assert the request
SHAPES — V4-signing inputs, workload-identity binding payload, S3 presign
params, trust-policy edits — mirroring the reference's credential-gated
tests (reference: internal/sci/gcp/manager_test.go:20-27,
internal/sci/aws/server_test.go:44-78) without needing cloud creds.
"""

import base64
import json
from unittest import mock

import pytest

from runbooks_tpu.sci import aws as aws_mod
from runbooks_tpu.sci import gcp as gcp_mod

MD5 = "0123456789abcdef0123456789abcdef"
MD5_B64 = base64.b64encode(bytes.fromhex(MD5)).decode()


# ---------------------------------------------------------------------------
# GCP
# ---------------------------------------------------------------------------

@pytest.fixture()
def gcp():
    return gcp_mod.GCPSCI(project_id="proj", cluster_name="c",
                          cluster_location="us-central1",
                          service_account="signer@proj.iam.gserviceaccount.com")


def gcp_modules(monkeypatch, **modules):
    """Route _require_google(module) to the given fakes."""
    def fake_require(name):
        for prefix, module in modules.items():
            if name == prefix:
                return module
        raise AssertionError(f"unexpected SDK import {name}")
    monkeypatch.setattr(gcp_mod, "_require_google", fake_require)


def test_gcp_signed_url_v4_inputs(gcp, monkeypatch):
    storage = mock.MagicMock()
    blob = storage.Client.return_value.bucket.return_value.blob.return_value
    blob.generate_signed_url.return_value = "https://signed"

    # Workload-identity path: default creds cannot sign -> impersonation.
    auth = mock.MagicMock()
    del auth.default.return_value  # configure explicitly below
    creds = mock.Mock(spec=[])     # no sign_bytes attr
    auth.default = mock.Mock(return_value=(creds, "proj"))
    imp = mock.MagicMock()

    gcp_modules(monkeypatch, **{
        "google.cloud.storage": storage,
        "google.auth": auth,
        "google.auth.impersonated_credentials": imp,
    })
    url = gcp.create_signed_url("bkt", "uploads/latest.tar.gz",
                                expiration_seconds=300, md5_checksum=MD5)
    assert url == "https://signed"

    storage.Client.assert_called_once_with(project="proj")
    storage.Client.return_value.bucket.assert_called_once_with("bkt")
    kwargs = blob.generate_signed_url.call_args.kwargs
    # The V4-signing inputs the reference also pins (manager.go:50-104):
    assert kwargs["version"] == "v4"
    assert kwargs["method"] == "PUT"
    assert kwargs["expiration"] == 300
    assert kwargs["content_md5"] == MD5_B64
    # Impersonated signer targets the configured GSA.
    assert imp.Credentials.call_args.kwargs["target_principal"] == \
        "signer@proj.iam.gserviceaccount.com"
    assert kwargs["credentials"] is imp.Credentials.return_value


def test_gcp_signed_url_direct_creds_skip_impersonation(gcp, monkeypatch):
    storage = mock.MagicMock()
    blob = storage.Client.return_value.bucket.return_value.blob.return_value
    creds = mock.Mock()  # has sign_bytes
    auth = mock.Mock()
    auth.default = mock.Mock(return_value=(creds, "proj"))
    gcp_modules(monkeypatch, **{"google.cloud.storage": storage,
                                "google.auth": auth})
    gcp.create_signed_url("b", "o")
    assert blob.generate_signed_url.call_args.kwargs["credentials"] is creds


def test_gcp_object_md5_decodes_b64(gcp, monkeypatch):
    storage = mock.MagicMock()
    got = storage.Client.return_value.bucket.return_value.get_blob
    got.return_value.md5_hash = MD5_B64
    gcp_modules(monkeypatch, **{"google.cloud.storage": storage})
    assert gcp.get_object_md5("b", "o") == MD5

    got.return_value = None
    assert gcp.get_object_md5("b", "o") is None


def test_gcp_bind_identity_payload_and_idempotency(gcp, monkeypatch):
    iam = mock.MagicMock()
    sa = iam.build.return_value.projects.return_value.serviceAccounts \
        .return_value
    policy = {"bindings": [{"role": "roles/other", "members": ["x"]}]}
    sa.getIamPolicy.return_value.execute.return_value = policy
    gcp_modules(monkeypatch, **{"googleapiclient.discovery": iam})

    gcp.bind_identity("signer@proj.iam.gserviceaccount.com", "modeller",
                      "team-a")
    set_call = sa.setIamPolicy.call_args
    assert set_call.kwargs["resource"] == (
        "projects/proj/serviceAccounts/signer@proj.iam.gserviceaccount.com")
    new_policy = set_call.kwargs["body"]["policy"]
    wi = [b for b in new_policy["bindings"]
          if b["role"] == "roles/iam.workloadIdentityUser"]
    # The exact member format GKE workload identity requires
    # (reference manager.go:118-144).
    assert wi[0]["members"] == [
        "serviceAccount:proj.svc.id.goog[team-a/modeller]"]

    # Second bind with the member already present: no write.
    sa.setIamPolicy.reset_mock()
    sa.getIamPolicy.return_value.execute.return_value = new_policy
    gcp.bind_identity("signer@proj.iam.gserviceaccount.com", "modeller",
                      "team-a")
    sa.setIamPolicy.assert_not_called()


def test_gcp_ensure_tpu_node_pool_create_and_idempotent(gcp, monkeypatch):
    container = mock.MagicMock()
    pools = container.build.return_value.projects.return_value \
        .locations.return_value.clusters.return_value.nodePools.return_value
    pools.list.return_value.execute.return_value = {"nodePools": []}
    gcp_modules(monkeypatch, **{"googleapiclient.discovery": container})

    name, created = gcp.ensure_tpu_node_pool("v5e", "4x4")
    assert created and name == "tpu-v5e-4-4"
    body = pools.create.call_args.kwargs["body"]["nodePool"]
    # GKE multi-host v5e slices use 4-chip hosts: 4x4 = 4 x ct5lp-hightpu-4t.
    assert body["config"]["machineType"] == "ct5lp-hightpu-4t"
    assert body["initialNodeCount"] == 4
    assert body["placementPolicy"] == {"type": "COMPACT",
                                       "tpuTopology": "4x4"}
    assert pools.create.call_args.kwargs["parent"] == (
        "projects/proj/locations/us-central1/clusters/c")

    pools.create.reset_mock()
    pools.list.return_value.execute.return_value = {
        "nodePools": [{"name": "tpu-v5e-4-4"}]}
    name, created = gcp.ensure_tpu_node_pool("v5e", "4x4")
    assert not created
    pools.create.assert_not_called()


# ---------------------------------------------------------------------------
# AWS
# ---------------------------------------------------------------------------

@pytest.fixture()
def aws():
    return aws_mod.AWSSCI(region="us-west-2", role_name="workload-role",
                          account_id="123456789012",
                          oidc_provider_url="https://oidc.eks.example/id/ABC")


def boto(monkeypatch, **clients):
    fake = mock.MagicMock()
    fake.client.side_effect = lambda svc, **kw: clients[svc]
    monkeypatch.setattr(aws_mod, "_boto3", lambda: fake)
    return fake


def test_aws_presigned_put_params(aws, monkeypatch):
    s3 = mock.MagicMock()
    s3.generate_presigned_url.return_value = "https://presigned"
    boto(monkeypatch, s3=s3)
    url = aws.create_signed_url("bkt", "uploads/latest.tar.gz",
                                expiration_seconds=300, md5_checksum=MD5)
    assert url == "https://presigned"
    call = s3.generate_presigned_url.call_args
    assert call.args[0] == "put_object"
    assert call.kwargs["ExpiresIn"] == 300
    assert call.kwargs["Params"] == {
        "Bucket": "bkt", "Key": "uploads/latest.tar.gz",
        "ContentMD5": MD5_B64}


def test_aws_etag_as_md5(aws, monkeypatch):
    s3 = mock.MagicMock()
    s3.head_object.return_value = {"ETag": f'"{MD5}"'}
    boto(monkeypatch, s3=s3)
    assert aws.get_object_md5("b", "o") == MD5
    # Multipart ETags are not MD5s (reference server.go:36-58).
    s3.head_object.return_value = {"ETag": '"abc-2"'}
    assert aws.get_object_md5("b", "o") is None


def test_aws_trust_policy_edit_and_idempotency(aws, monkeypatch):
    iam = mock.MagicMock()
    policy = {"Version": "2012-10-17", "Statement": []}
    iam.get_role.return_value = {"Role": {"AssumeRolePolicyDocument": policy}}
    boto(monkeypatch, iam=iam)

    aws.bind_identity("", "modeller", "team-a")
    call = iam.update_assume_role_policy.call_args
    assert call.kwargs["RoleName"] == "workload-role"
    doc = json.loads(call.kwargs["PolicyDocument"])
    stmt = doc["Statement"][0]
    # The IRSA trust shape the reference edits (server.go:88-162).
    assert stmt["Principal"]["Federated"] == (
        "arn:aws:iam::123456789012:oidc-provider/oidc.eks.example/id/ABC")
    assert stmt["Action"] == "sts:AssumeRoleWithWebIdentity"
    assert stmt["Condition"]["StringEquals"] == {
        "oidc.eks.example/id/ABC:sub":
            "system:serviceaccount:team-a:modeller"}

    # Same (ns, ksa) again: no second write.
    iam.update_assume_role_policy.reset_mock()
    iam.get_role.return_value = {"Role": {"AssumeRolePolicyDocument": doc}}
    aws.bind_identity("", "modeller", "team-a")
    iam.update_assume_role_policy.assert_not_called()
