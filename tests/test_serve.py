"""Serving engine + HTTP API tests.

Engine correctness oracle: greedy rollout through the full no-cache forward
must equal the engine's slot-based cached decode.
"""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import forward, init_params
from runbooks_tpu.serve.engine import InferenceEngine, Request


def tiny_cfg():
    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype="float32",
    )


def greedy_rollout(cfg, params, prompt, n):
    toks = list(prompt)
    for _ in range(n):
        logits, _ = forward(cfg, params, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def test_engine_matches_full_forward_greedy():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=4)

    prompts = [[5, 9, 17], [3, 4, 5, 6, 7, 8, 9, 10], [42]]
    reqs = [Request(prompt_tokens=p, max_tokens=8, temperature=0.0)
            for p in prompts]
    engine.generate(reqs)
    for p, r in zip(prompts, reqs):
        expect = greedy_rollout(cfg, params, p, 8)
        assert r.output_tokens == expect, (p, r.output_tokens, expect)


def test_engine_continuous_batching_mid_flight():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=2)

    r1 = Request(prompt_tokens=[5, 9, 17], max_tokens=10, temperature=0.0)
    r2 = Request(prompt_tokens=[3, 4, 5, 6], max_tokens=10, temperature=0.0)
    engine.submit(r1)
    engine.step()
    engine.step()  # r1 is 2 tokens in
    engine.submit(r2)  # joins mid-flight
    while engine.has_work():
        engine.step()
    assert r1.output_tokens == greedy_rollout(cfg, params, [5, 9, 17], 10)
    assert r2.output_tokens == greedy_rollout(cfg, params, [3, 4, 5, 6], 10)


def test_engine_eos_and_limits():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=2)
    expect = greedy_rollout(cfg, params, [7, 7, 7], 6)
    eos = expect[2]
    r = Request(prompt_tokens=[7, 7, 7], max_tokens=6, temperature=0.0,
                eos_id=eos)
    engine.generate([r])
    assert r.finish_reason == "stop"
    assert r.output_tokens[-1] == eos
    # stops at the FIRST occurrence of eos in the greedy rollout
    assert len(r.output_tokens) == expect.index(eos) + 1

    r2 = Request(prompt_tokens=[7, 7, 7], max_tokens=2, temperature=0.0)
    engine.generate([r2])
    assert r2.finish_reason == "length"
    assert len(r2.output_tokens) == 2


def test_engine_uses_full_capacity():
    # Regression: the length bound used to double-count generated tokens and
    # truncate at ~half capacity.
    cfg = dataclasses.replace(tiny_cfg(), max_seq_len=32)
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=1, max_seq_len=32)
    r = Request(prompt_tokens=[1, 2, 3, 4], max_tokens=100, temperature=0.0)
    engine.generate([r])
    # 28 tokens fill the cache (4 prompt + 28 = 32 slots); the final token
    # is sampled without needing a cache write => 29 outputs total.
    assert len(r.output_tokens) == 32 - 4 + 1
    assert r.finish_reason == "length"


def test_engine_sampled_temperature_varies():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=4, seed=1)
    reqs = [Request(prompt_tokens=[11, 12], max_tokens=12, temperature=2.0,
                    top_k=50)
            for _ in range(3)]
    engine.generate(reqs)
    outs = {tuple(r.output_tokens) for r in reqs}
    assert len(outs) > 1  # high temperature should decorrelate slots


def test_engine_sharded_matches_unsharded():
    from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    prompts = [[5, 9, 17], [3, 4, 5, 6]]

    plain = InferenceEngine(cfg, params, max_slots=2)
    plain_reqs = [Request(prompt_tokens=list(p), max_tokens=8,
                          temperature=0.0) for p in prompts]
    plain.generate(plain_reqs)

    mesh = make_mesh(MeshConfig(data=1, fsdp=2, sequence=1, tensor=4))
    sharded = InferenceEngine(cfg, params, max_slots=2, mesh=mesh)
    shard_reqs = [Request(prompt_tokens=list(p), max_tokens=8,
                          temperature=0.0) for p in prompts]
    sharded.generate(shard_reqs)

    for a, b in zip(plain_reqs, shard_reqs):
        assert a.output_tokens == b.output_tokens
    # params really are distributed (a TP-sharded layer matrix)
    wq = sharded.params["layers"]["attn"]["wq"]
    assert len({s.device for s in wq.addressable_shards}) == 8


def test_engine_warmup_precompiles_and_resets():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=2)
    engine.warmup()
    assert not engine.active.any() and not engine.queue
    # Generation after warmup still correct.
    r = Request(prompt_tokens=[5, 9, 17], max_tokens=4, temperature=0.0)
    engine.generate([r])
    assert r.output_tokens == greedy_rollout(cfg, params, [5, 9, 17], 4)


def test_worker_crash_containment():
    """An engine failure mid-flight must fail waiting requests with the
    error and leave the worker serving subsequent requests."""
    from runbooks_tpu.serve.api import EngineWorker

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=2)
    worker = EngineWorker(engine)

    boom = {"armed": True}
    orig_step = engine.step

    def exploding_step():
        if boom["armed"]:
            boom["armed"] = False
            raise RuntimeError("synthetic device failure")
        return orig_step()

    engine.step = exploding_step
    fut = worker.submit(Request(prompt_tokens=[1, 2], max_tokens=3,
                                temperature=0.0))
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="synthetic device failure"):
        fut.result(timeout=30)

    # Worker thread survived; next request succeeds on the reset engine.
    fut2 = worker.submit(Request(prompt_tokens=[1, 2], max_tokens=3,
                                 temperature=0.0))
    done = fut2.result(timeout=60)
    assert len(done.output_tokens) == 3
    worker.stop()


def test_http_api_end_to_end():
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    app = create_server(cfg, params, max_slots=2)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.get("/")
            assert r.status == 200
            body = await r.json()
            assert body["status"] == "ok"

            r = await client.post("/v1/completions", json={
                "prompt": "hello", "max_tokens": 4, "temperature": 0.0})
            assert r.status == 200
            body = await r.json()
            assert body["object"] == "text_completion"
            assert body["choices"][0]["finish_reason"] in ("length", "stop")
            assert body["usage"]["completion_tokens"] >= 1

            # batch (list) prompt: one choice per element
            r = await client.post("/v1/completions", json={
                "prompt": ["a", "bb"], "max_tokens": 3, "temperature": 0.0})
            assert r.status == 200
            body = await r.json()
            assert [c["index"] for c in body["choices"]] == [0, 1]

            # chat endpoint
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 3, "temperature": 0.0})
            assert r.status == 200
            body = await r.json()
            assert body["object"] == "chat.completion"
            assert body["choices"][0]["message"]["role"] == "assistant"
            r = await client.post("/v1/chat/completions", json={})
            assert r.status == 400

            # malformed requests
            r = await client.post("/v1/completions", json={"max_tokens": 4})
            assert r.status == 400
            r = await client.post("/v1/completions", data=b"{not json")
            assert r.status == 400
            r = await client.post("/v1/completions", json={
                "prompt": "x", "max_tokens": 0})
            assert r.status == 400
            # over-long prompt -> 400, not silent truncation
            r = await client.post("/v1/completions", json={
                "prompt": "x" * 500, "max_tokens": 4})
            assert r.status == 400
            body = await r.json()
            assert "context window" in body["error"]["message"]

    asyncio.run(drive())


def test_http_streaming_sse():
    """`stream: true` returns SSE chunks whose concatenated deltas equal the
    non-streamed completion, ending with a finish chunk and [DONE] (the
    reference's documented server, basaran, streams the same protocol)."""
    import json as _json

    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    app = create_server(cfg, params, max_slots=2)

    def parse_sse(raw: str):
        events = []
        for line in raw.split("\n"):
            if line.startswith("data: "):
                payload = line[len("data: "):]
                events.append(payload if payload == "[DONE]"
                              else _json.loads(payload))
        return events

    async def drive():
        async with TestClient(TestServer(app)) as client:
            # Reference answer without streaming (greedy => deterministic).
            r = await client.post("/v1/completions", json={
                "prompt": "hello", "max_tokens": 5, "temperature": 0.0})
            expect = (await r.json())["choices"][0]["text"]

            r = await client.post("/v1/completions", json={
                "prompt": "hello", "max_tokens": 5, "temperature": 0.0,
                "stream": True})
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/event-stream")
            events = parse_sse(await r.text())
            assert events[-1] == "[DONE]"
            chunks = events[:-1]
            assert all(e["object"] == "text_completion" for e in chunks)
            text = "".join(c["choices"][0]["text"] for c in chunks)
            assert text == expect
            finishes = [c["choices"][0]["finish_reason"] for c in chunks]
            assert finishes[-1] in ("length", "stop")
            # more than one delta chunk => actually incremental
            assert len(chunks) >= 2

            # chat streaming: delta format, role announced once
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4, "temperature": 0.0, "stream": True})
            assert r.status == 200
            events = parse_sse(await r.text())
            assert events[-1] == "[DONE]"
            chunks = events[:-1]
            assert all(e["object"] == "chat.completion.chunk"
                       for e in chunks)
            deltas = [c["choices"][0]["delta"] for c in chunks]
            assert any(d.get("content") for d in deltas)
            # the assistant role is announced exactly once, in the first delta
            assert deltas[0].get("role") == "assistant"
            assert sum(1 for d in deltas if "role" in d) == 1

    asyncio.run(drive())


@pytest.mark.slow
def test_engine_chunked_decode_matches_single_step():
    """decode_chunk>1 (the TPU default: K scan steps per host round-trip)
    must emit token-for-token what chunk=1 stepping emits — including
    requests that hit EOS or max_tokens MID-chunk (device liveness mask)."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    prompts = [[5, 9, 17], [3, 4, 5, 6, 7, 8, 9, 10], [42]]
    expect = {tuple(p): greedy_rollout(cfg, params, p, 11) for p in prompts}
    eos = expect[(5, 9, 17)][4]  # force a mid-chunk stop for request 0

    for chunk in (3, 4, 8):
        engine = InferenceEngine(cfg, params, max_slots=4,
                                 decode_chunk=chunk)
        reqs = [Request(prompt_tokens=list(p), max_tokens=n,
                        temperature=0.0, eos_id=e)
                for p, n, e in [(prompts[0], 11, eos),
                                (prompts[1], 7, None),
                                (prompts[2], 11, None)]]
        engine.generate(reqs)
        full = expect[tuple(prompts[0])]
        stop_at = full.index(eos) + 1 if eos in full else 11
        assert reqs[0].output_tokens == full[:stop_at]
        if eos in full:
            assert reqs[0].finish_reason == "stop"
        assert reqs[1].output_tokens == expect[tuple(prompts[1])][:7]
        assert reqs[1].finish_reason == "length"
        assert reqs[2].output_tokens == expect[tuple(prompts[2])]


def test_engine_chunked_decode_capacity_bound():
    """Out-of-room detection works on device: a chunk never writes past the
    cache even when the request budget would keep going."""
    cfg = dataclasses.replace(tiny_cfg(), max_seq_len=32)
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=1, max_seq_len=32,
                             decode_chunk=8)
    r = Request(prompt_tokens=[1, 2, 3, 4], max_tokens=100, temperature=0.0)
    engine.generate([r])
    assert len(r.output_tokens) == 32 - 4 + 1
    assert r.finish_reason == "length"


@pytest.mark.slow
def test_engine_batched_prefill_mixed_buckets():
    """Admissions in one tick group by length bucket; each group prefills
    as one [rows, bucket] call, and results still match the per-request
    greedy oracle (incl. the power-of-two row padding path: 3 real rows
    in a rows=4 call, plus a second bucket group)."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=8, prefill_budget=1024)
    prompts = [[5, 9, 17], [3, 4], [42],                      # bucket 16
               list(range(2, 22)), list(range(7, 25))]        # bucket 32
    reqs = [Request(prompt_tokens=list(p), max_tokens=6, temperature=0.0)
            for p in prompts]
    for r in reqs:
        engine.submit(r)
    engine.step()  # one tick admits all five (two grouped prefill calls)
    assert int(engine.active.sum()) == 5
    while engine.has_work():
        engine.step()
    for p, r in zip(prompts, reqs):
        assert r.output_tokens == greedy_rollout(cfg, params, p, 6), p


@pytest.mark.slow
def test_engine_bucketed_cache_view_parity():
    """Decode through small cache-read views (the HBM-bandwidth
    optimization) emits exactly what the full-cache read emits, across
    view-bucket transitions as contexts grow."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=2, decode_chunk=4)
    assert engine.view_buckets == [64]  # tiny cap -> single bucket
    engine.view_buckets = [16, 32, 64]  # force bucket transitions
    prompts = [[5, 9, 17], [3, 4, 5, 6, 7, 8, 9, 10]]
    reqs = [Request(prompt_tokens=list(p), max_tokens=30, temperature=0.0)
            for p in prompts]
    engine.generate(reqs)
    for p, r in zip(prompts, reqs):
        assert r.output_tokens == greedy_rollout(cfg, params, p, 30), p
    # the run actually crossed view buckets (3+30+chunk > 32 > 16)
    assert len(engine._decode_fns) >= 2


def test_engine_prefill_budget_spreads_admission():
    """A burst of prompts is admitted over multiple steps bounded by the
    per-step prefill-token budget (bucket-padded), so in-flight decodes
    keep making progress during the burst; a single over-budget prompt
    still admits alone (no starvation)."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(cfg, params, max_slots=4, prefill_budget=32)

    # 3 prompts of 20 tokens -> bucket 32 each: one admission per step.
    reqs = [Request(prompt_tokens=list(range(1, 21)), max_tokens=10,
                    temperature=0.0) for _ in range(3)]
    for r in reqs:
        eng.submit(r)
    eng.step()
    assert int(eng.active.sum()) == 1 and len(eng.queue) == 2
    eng.step()
    assert int(eng.active.sum()) == 2 and len(eng.queue) == 1
    eng.step()
    assert int(eng.active.sum()) == 3 and not eng.queue
    # Earlier admissions kept decoding while later ones waited their turn.
    assert [len(r.output_tokens) for r in reqs] == [4, 3, 2]
    while eng.has_work():
        eng.step()
    assert all(len(r.output_tokens) == 10 for r in reqs)

    # Over-budget single prompt (bucket 64 > 32) admits immediately.
    eng.submit(Request(prompt_tokens=list(range(1, 41)), max_tokens=2,
                       temperature=0.0))
    eng.step()
    assert not eng.queue  # admitted despite exceeding the budget

    # Short prompts (bucket 16) pack two-per-step under the same budget.
    while eng.has_work():
        eng.step()
    for _ in range(4):
        eng.submit(Request(prompt_tokens=[1, 2, 3], max_tokens=10,
                           temperature=0.0))
    eng.step()
    assert int(eng.active.sum()) == 2 and len(eng.queue) == 2


@pytest.mark.slow
def test_engine_shared_prefix_reuse_matches_full_prefill():
    """Requests whose prompt starts with a registered prefix must produce
    EXACTLY the tokens a full prefill would (the cached prefix K/V plus a
    suffix-only scatter prefill is numerically the same computation), and
    the engine must actually reuse the prefix (prefix_tokens_reused)."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    prefix = [int(t) for t in rng.integers(1, cfg.vocab_size, 16)]
    suffixes = [[7, 9], [11], [3, 5, 8, 13]]

    ref = InferenceEngine(cfg, params, max_slots=4)
    reqs_ref = [Request(prompt_tokens=prefix + s, max_tokens=8)
                for s in suffixes]
    ref.generate(reqs_ref)

    eng = InferenceEngine(cfg, params, max_slots=4)
    assert eng.register_prefix(prefix) == 16
    reqs = [Request(prompt_tokens=prefix + s, max_tokens=8)
            for s in suffixes]
    eng.generate(reqs)

    assert eng.prefix_tokens_reused == 16 * len(suffixes)
    for got, want in zip(reqs, reqs_ref):
        assert got.output_tokens == want.output_tokens, (
            got.output_tokens, want.output_tokens)


@pytest.mark.slow
def test_engine_prefix_register_rounds_and_evicts():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(cfg, params, max_slots=2)
    # Too short to cache.
    assert eng.register_prefix([1, 2, 3]) == 0
    # 19 tokens round down to 16.
    toks = list(range(1, 20))
    assert eng.register_prefix(toks) == 16
    # Re-registration is a cache hit (no growth).
    assert eng.register_prefix(toks) == 16
    assert len(eng._prefix_cache) == 1
    # LRU bound holds.
    for i in range(eng.prefix_cache_size + 1):
        eng.register_prefix([100 + i] * 16)
    assert len(eng._prefix_cache) == eng.prefix_cache_size


@pytest.mark.slow
def test_engine_prefix_mixed_with_plain_requests():
    """A tick admitting both prefix-hit and plain requests splits into
    separate prefill groups and all outputs match the no-prefix engine."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    prefix = list(range(2, 18))
    prompts = [prefix + [40, 41], [9, 8, 7], prefix + [50]]

    ref = InferenceEngine(cfg, params, max_slots=4)
    reqs_ref = [Request(prompt_tokens=p, max_tokens=6) for p in prompts]
    ref.generate(reqs_ref)

    eng = InferenceEngine(cfg, params, max_slots=4)
    eng.register_prefix(prefix)
    reqs = [Request(prompt_tokens=p, max_tokens=6) for p in prompts]
    eng.generate(reqs)
    assert eng.prefix_tokens_reused == 32
    for got, want in zip(reqs, reqs_ref):
        assert got.output_tokens == want.output_tokens


def test_http_prefix_registration_endpoint():
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    app = create_server(cfg, params, max_slots=2)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            toks = list(range(2, 22))
            r = await client.post("/v1/prefix", json={"tokens": toks})
            assert r.status == 200
            assert (await r.json())["cached_prefix_len"] == 16

            # A completion whose prompt starts with the prefix reuses it.
            eng = app["worker"].engine
            before = eng.prefix_tokens_reused
            req = Request(prompt_tokens=toks[:16] + [30, 31], max_tokens=3)
            fut = app["worker"].submit(req)
            await asyncio.wrap_future(fut)
            assert eng.prefix_tokens_reused == before + 16

            r = await client.post("/v1/prefix", json={"tokens": "nope"})
            assert r.status == 400
            r = await client.get("/metrics")
            assert "serve_prefix_tokens_reused_total 16" in await r.text()

    asyncio.run(drive())


@pytest.mark.slow
def test_engine_prefix_in_use_survives_eviction_pressure():
    """Admission hits refresh the LRU: the prefix serving live traffic
    must outlive later registrations."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(cfg, params, max_slots=2)
    hot = list(range(2, 18))
    eng.register_prefix(hot)
    # Traffic keeps hitting the hot prefix while cold prefixes register
    # past the cache bound; each admission hit refreshes its LRU slot.
    for i in range(eng.prefix_cache_size):
        eng.generate([Request(prompt_tokens=hot + [30 + i], max_tokens=2)])
        eng.register_prefix([100 + i] * 16)
    assert eng.prefix_tokens_reused == 16 * eng.prefix_cache_size
    assert tuple(hot) in eng._prefix_cache, "hot prefix was evicted"


def test_engine_register_prefix_from_slot_matches_full_prefill():
    """Zero-forward prefix registration: KV copied out of a finished
    request's slot must serve later longer prompts with EXACTLY the
    outputs a full prefill produces."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    turn1 = list(range(2, 20))            # 18 tokens -> bucket 16 cached
    turn2 = turn1 + [30, 31, 32]

    ref = InferenceEngine(cfg, params, max_slots=2)
    want = Request(prompt_tokens=list(turn2), max_tokens=6)
    ref.generate([want])

    eng = InferenceEngine(cfg, params, max_slots=2)
    first = Request(prompt_tokens=list(turn1), max_tokens=4)
    eng.generate([first])
    assert first._slot >= 0
    assert eng.register_prefix_from_slot(first._slot, turn1) == 16
    got = Request(prompt_tokens=list(turn2), max_tokens=6)
    eng.generate([got])
    assert eng.prefix_tokens_reused == 16
    assert got.output_tokens == want.output_tokens


def test_http_chat_auto_prefix_multi_turn():
    """auto_prefix_chat: turn N's prompt KV is registered from its slot
    and turn N+1 (whose rendered prompt extends it) reuses it, with
    identical answers to a server without the feature."""
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg = dataclasses.replace(tiny_cfg(), max_seq_len=256)
    params = init_params(cfg, jax.random.key(0))

    async def converse(app):
        msgs = [{"role": "system",
                 "content": "Be concise and always answer in English."},
                {"role": "user", "content": "hello there"}]
        answers = []
        async with TestClient(TestServer(app)) as client:
            for turn in range(2):
                r = await client.post("/v1/chat/completions", json={
                    "messages": msgs, "max_tokens": 4, "temperature": 0.0})
                assert r.status == 200
                body = await r.json()
                text = body["choices"][0]["message"]["content"]
                answers.append(text)
                msgs.append({"role": "assistant", "content": text})
                msgs.append({"role": "user", "content": "and again"})
            # Worker registers from the slot after each completion; by
            # the second turn the first turn's prompt must have been
            # reused (rendered history strictly extends it).
            eng = app["worker"].engine
            return answers, eng.prefix_tokens_reused

    app_off = create_server(cfg, params, max_slots=2)
    want, reused_off = asyncio.run(converse(app_off))
    assert reused_off == 0

    app_on = create_server(cfg, params, max_slots=2, auto_prefix_chat=True)
    got, reused_on = asyncio.run(converse(app_on))
    assert reused_on > 0, "second turn did not reuse the first turn's KV"
    assert got == want
