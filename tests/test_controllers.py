"""Reconciler tests against the in-memory fake cluster.

Mirrors the reference's envtest technique (SURVEY.md §4): no kubelet, so
tests simulate runtime by marking Jobs complete / Pods ready / Deployments
available, then assert the reconcilers converge conditions and status.
"""

import pytest

from runbooks_tpu.api import conditions as cond
from runbooks_tpu.api.types import (
    API_VERSION,
    Dataset,
    Model,
    Notebook,
    Server,
)
from runbooks_tpu.cloud.base import CommonConfig
from runbooks_tpu.cloud.local import LocalCloud
from runbooks_tpu.controller.build import BuildReconciler
from runbooks_tpu.controller.dataset import DatasetReconciler
from runbooks_tpu.controller.manager import Ctx, Manager
from runbooks_tpu.controller.model import ModelReconciler
from runbooks_tpu.controller.notebook import NotebookReconciler
from runbooks_tpu.controller.server import ServerReconciler
from runbooks_tpu.k8s import objects as ko
from runbooks_tpu.k8s.fake import FakeCluster
from runbooks_tpu.sci.base import FakeSCI


@pytest.fixture()
def harness(tmp_path):
    client = FakeCluster()
    cloud = LocalCloud(CommonConfig(
        cluster_name="testcluster",
        artifact_bucket_url=f"file://{tmp_path}/bucket",
        registry_url="registry.local:5000"))
    sci = FakeSCI()
    ctx = Ctx(client=client, cloud=cloud, sci=sci)
    mgr = Manager(ctx, [
        BuildReconciler("Model"), BuildReconciler("Dataset"),
        BuildReconciler("Server"), BuildReconciler("Notebook"),
        ModelReconciler(), DatasetReconciler(), ServerReconciler(),
        NotebookReconciler(),
    ])
    return client, cloud, sci, mgr


def get(client, kind, name, ns="default"):
    return client.get(API_VERSION, kind, ns, name)


# ---------------------------------------------------------------------------
# Build reconciler
# ---------------------------------------------------------------------------

def test_build_upload_handshake_and_job(harness):
    client, cloud, sci, mgr = harness
    m = Model.new("m1", spec={
        "build": {"upload": {"md5checksum": "abc123", "requestID": "r1"}}})
    client.create(m.obj)
    mgr.reconcile_until_stable()

    cur = Model(get(client, "Model", "m1"))
    # Signed URL issued for this requestID; Uploaded=False until storage
    # has the right md5.
    assert cur.upload_status["signedURL"].startswith("https://signed.example/")
    assert cur.upload_status["requestID"] == "r1"
    assert not cur.condition_true(cond.UPLOADED)
    assert len(sci.signed) >= 1

    # Simulate the client PUTting the tarball (storage now has the md5).
    bucket, obj_name = sci.signed[-1][0], sci.signed[-1][1]
    sci.objects[f"{bucket}/{obj_name}"] = "abc123"
    mgr.reconcile_until_stable()

    cur = Model(get(client, "Model", "m1"))
    assert cur.condition_true(cond.UPLOADED)
    # Build job created with the image annotation; not yet Built.
    job = client.get("batch/v1", "Job", "default", "m1-model-bld")
    assert job is not None
    target = ko.annotations(job)["runbooks-tpu.dev/target-image"]
    assert target.startswith("registry.local:5000/testcluster-model-default-m1:")
    assert not cur.condition_true(cond.BUILT)

    client.mark_job_complete("default", "m1-model-bld")
    mgr.reconcile_until_stable()
    cur = Model(get(client, "Model", "m1"))
    assert cur.condition_true(cond.BUILT)
    assert cur.image == target
    # container-builder SA reconciled
    assert client.get("v1", "ServiceAccount", "default",
                      "container-builder") is not None


def test_build_storage_job_mounts_local_bucket(harness):
    """CLOUD=local storage builds mount the hostPath artifact prefix into
    the kaniko pod and read the tarball through the mount — otherwise
    kaniko has no way to reach a file:// bucket on a real kind cluster
    (reference: build_reconciler.go:442-468)."""
    client, cloud, sci, mgr = harness
    m = Model.new("mb", spec={
        "build": {"upload": {"md5checksum": "feed01", "requestID": "r9"}}})
    client.create(m.obj)
    mgr.reconcile_until_stable()
    bucket, obj_name = sci.signed[-1][0], sci.signed[-1][1]
    sci.objects[f"{bucket}/{obj_name}"] = "feed01"
    mgr.reconcile_until_stable()

    job = client.get("batch/v1", "Job", "default", "mb-model-bld")
    assert job is not None
    pod = job["spec"]["template"]["spec"]
    vols = {v["name"]: v for v in pod["volumes"]}
    assert "bucket" in vols and "hostPath" in vols["bucket"]
    host_path = vols["bucket"]["hostPath"]["path"]
    from runbooks_tpu.cloud.base import parse_bucket_url
    _, rest = parse_bucket_url(cloud.object_artifact_url(m))
    assert host_path == "/" + rest.lstrip("/")
    kaniko = pod["containers"][0]
    assert {"name": "bucket", "mountPath": "/bucket",
            "readOnly": True} in kaniko["volumeMounts"]
    assert "--context=tar:///bucket/uploads/latest.tar.gz" in kaniko["args"]


def test_build_git_job_args(harness):
    client, cloud, sci, mgr = harness
    m = Model.new("m2", spec={
        "build": {"git": {"url": "https://example.com/repo.git",
                          "branch": "dev", "path": "img"}}})
    client.create(m.obj)
    mgr.reconcile_until_stable()
    job = client.get("batch/v1", "Job", "default", "m2-model-bld")
    assert job is not None
    init = job["spec"]["template"]["spec"]["initContainers"][0]
    assert init["args"][:2] == ["clone", "https://example.com/repo.git"]
    kaniko = job["spec"]["template"]["spec"]["containers"][0]
    assert any(a == "--context=dir:///workspace/img" for a in kaniko["args"])
    # tag derives from the branch
    assert any(a.endswith(":dev") for a in kaniko["args"]
               if a.startswith("--destination="))


# ---------------------------------------------------------------------------
# Model reconciler
# ---------------------------------------------------------------------------

def test_model_job_lifecycle(harness):
    client, cloud, sci, mgr = harness
    m = Model.new("imp", spec={"image": "loader:latest",
                               "params": {"name": "opt-125m"}})
    client.create(m.obj)
    mgr.reconcile_until_stable()

    job = client.get("batch/v1", "Job", "default", "imp-modeller")
    assert job is not None
    assert job["spec"]["backoffLimit"] == 3  # cheap CPU import retries
    cm = client.get("v1", "ConfigMap", "default", "imp-model-params")
    assert cm is not None and "params.json" in cm["data"]
    cur = Model(get(client, "Model", "imp"))
    assert cur.artifacts_url.startswith("file://")
    assert not cur.ready

    # env contract: PARAM_* injected
    env = {e["name"]: e.get("value")
           for e in job["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env.get("PARAM_NAME") == "opt-125m"

    client.mark_job_complete("default", "imp-modeller")
    mgr.reconcile_until_stable()
    cur = Model(get(client, "Model", "imp"))
    assert cur.ready and cur.condition_true(cond.COMPLETE)


def test_model_failed_job_sets_condition(harness):
    client, cloud, sci, mgr = harness
    client.create(Model.new("bad", spec={"image": "x"}).obj)
    mgr.reconcile_until_stable()
    client.mark_job_complete("default", "bad-modeller", failed=True)
    mgr.reconcile_until_stable()
    cur = Model(get(client, "Model", "bad"))
    assert not cur.ready
    c = ko.get_condition(cur.obj, cond.COMPLETE)
    assert c["status"] == "False" and c["reason"] == cond.REASON_JOB_FAILED


def test_model_dependency_chain(harness):
    client, cloud, sci, mgr = harness
    client.create(Dataset.new("d", spec={"image": "loader"}).obj)
    client.create(Model.new("base", spec={"image": "loader"}).obj)
    client.create(Model.new("ft", spec={
        "image": "trainer", "model": {"name": "base"},
        "dataset": {"name": "d"}}).obj)
    mgr.reconcile_until_stable()

    # Gated: no modeller job until base+dataset are ready.
    assert client.get("batch/v1", "Job", "default", "ft-modeller") is None
    cur = Model(get(client, "Model", "ft"))
    c = ko.get_condition(cur.obj, cond.COMPLETE)
    assert c["status"] == "False"

    client.mark_job_complete("default", "d-data-loader")
    client.mark_job_complete("default", "base-modeller")
    mgr.reconcile_until_stable()
    job = client.get("batch/v1", "Job", "default", "ft-modeller")
    assert job is not None
    mounts = {m["mountPath"] for c in
              job["spec"]["template"]["spec"]["containers"]
              for m in c["volumeMounts"]}
    assert {"/content/artifacts", "/content/data", "/content/model",
            "/content/params.json"} <= mounts

    client.mark_job_complete("default", "ft-modeller")
    mgr.reconcile_until_stable()
    assert Model(get(client, "Model", "ft")).ready


def test_model_tpu_multihost_fanout(harness):
    client, cloud, sci, mgr = harness
    client.create(Model.new("big", spec={
        "image": "trainer",
        "resources": {"tpu": {"type": "v5e", "topology": "2x4"}}}).obj)
    mgr.reconcile_until_stable()
    job = client.get("batch/v1", "Job", "default", "big-modeller")
    assert job is not None
    spec = job["spec"]
    assert spec["completions"] == 2 and spec["parallelism"] == 2
    assert spec["completionMode"] == "Indexed"
    # Multi-host: no in-place pod retries (a lost host crashes the peers
    # with generic exit codes — exit-code policy can't tell preemption
    # from error); the reconciler's slice-recreate path handles restarts.
    assert spec["backoffLimit"] == 0
    assert "podFailurePolicy" not in spec
    pod = spec["template"]["spec"]
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"] == \
        "tpu-v5-lite-podslice"
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x4"
    env = {e["name"]: e for e in pod["containers"][0]["env"]}
    assert "JAX_COORDINATOR_ADDRESS" in env
    assert env["JAX_NUM_PROCESSES"]["value"] == "2"
    res = pod["containers"][0]["resources"]
    assert res["requests"]["google.com/tpu"] == "4"
    # headless service for stable pod DNS
    svc = client.get("v1", "Service", "default", "big-modeller")
    assert svc is not None and svc["spec"]["clusterIP"] == "None"


def test_model_tpu_multislice(harness):
    client, cloud, sci, mgr = harness
    client.create(Model.new("ms", spec={
        "image": "trainer",
        "resources": {"tpu": {"type": "v5e", "topology": "2x4",
                              "slices": 2}}}).obj)
    mgr.reconcile_until_stable()
    jobs = [client.get("batch/v1", "Job", "default", f"ms-modeller-slice-{i}")
            for i in range(2)]
    assert all(jobs)
    for i, job in enumerate(jobs):
        env = {e["name"]: e.get("value") for e in
               job["spec"]["template"]["spec"]["containers"][0]["env"]}
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        assert env["MEGASCALE_SLICE_ID"] == str(i)
        assert env["MEGASCALE_COORDINATOR_ADDRESS"].startswith(
            "ms-modeller-slice-0-0.")
        assert job["spec"]["completions"] == 2  # 2 hosts per slice

    # completes only when ALL slices complete
    client.mark_job_complete("default", "ms-modeller-slice-0")
    mgr.reconcile_until_stable()
    assert not Model(get(client, "Model", "ms")).ready
    client.mark_job_complete("default", "ms-modeller-slice-1")
    mgr.reconcile_until_stable()
    assert Model(get(client, "Model", "ms")).ready


# ---------------------------------------------------------------------------
# Server reconciler
# ---------------------------------------------------------------------------

def test_server_lifecycle(harness):
    client, cloud, sci, mgr = harness
    client.create(Model.new("m", spec={"image": "loader"}).obj)
    client.create(Server.new("srv", spec={
        "image": "server-img", "model": {"name": "m"}}).obj)
    mgr.reconcile_until_stable()
    # Gated on model readiness.
    assert client.get("apps/v1", "Deployment", "default", "srv") is None

    client.mark_job_complete("default", "m-modeller")
    mgr.reconcile_until_stable()
    dep = client.get("apps/v1", "Deployment", "default", "srv")
    svc = client.get("v1", "Service", "default", "srv")
    assert dep is not None and svc is not None
    assert svc["spec"]["ports"][0]["port"] == 80
    assert svc["spec"]["ports"][0]["targetPort"] == 8080
    container = dep["spec"]["template"]["spec"]["containers"][0]
    assert container["readinessProbe"]["httpGet"]["path"] == "/"
    mounts = {m["mountPath"] for m in container["volumeMounts"]}
    assert "/content/model" in mounts
    cur = Server(get(client, "Server", "srv"))
    assert not cur.ready

    client.mark_deployment_ready("default", "srv")
    mgr.reconcile_until_stable()
    cur = Server(get(client, "Server", "srv"))
    assert cur.ready and cur.condition_true(cond.SERVING)


def test_dependent_requeue_on_model_event(harness):
    """A Model watch event fans out to Servers referencing it (the
    field-index requeue; reference: internal/controller/manager.go:23-72,
    server_controller.go:83-112) — no resync involved at any point."""
    client, cloud, sci, mgr = harness
    client.create(Model.new("wm", spec={"image": "loader"}).obj)
    client.create(Server.new("ws", spec={
        "image": "server-img", "model": {"name": "wm"}}).obj)

    # Initial events: modeller Job created, Server gated on model readiness.
    mgr.process_event("Model", get(client, "Model", "wm"))
    mgr.process_event("Server", get(client, "Server", "ws"))
    assert client.get("batch/v1", "Job", "default", "wm-modeller") is not None
    assert client.get("apps/v1", "Deployment", "default", "ws") is None

    # Job completes; the resulting Model event both readies the Model and
    # fans out to the Server, which creates its Deployment immediately.
    client.mark_job_complete("default", "wm-modeller")
    mgr.process_event("Model", get(client, "Model", "wm"))
    assert Model(get(client, "Model", "wm")).ready
    assert client.get("apps/v1", "Deployment", "default", "ws") is not None

    # Deployment becomes available; the next Model event (any event on the
    # dependency requeues dependents) flips the Server to Serving.
    client.mark_deployment_ready("default", "ws")
    mgr.process_event("Model", get(client, "Model", "wm"))
    cur = Server(get(client, "Server", "ws"))
    assert cur.ready and cur.condition_true(cond.SERVING)


def test_watch_loop_advances_chain_without_resync(harness):
    """Manager.run with resync effectively disabled: the Model->Server chain
    advances via watch events + requeue_after scheduling alone."""
    import threading
    import time

    client, cloud, sci, mgr = harness
    client.create(Model.new("lm", spec={"image": "loader"}).obj)
    client.create(Server.new("ls", spec={
        "image": "server-img", "model": {"name": "lm"}}).obj)

    stop = threading.Event()
    t = threading.Thread(target=mgr.run, args=(stop,),
                         kwargs={"resync_seconds": 3600.0}, daemon=True)
    t.start()

    def wait_for(pred, timeout=10.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return True
            time.sleep(0.05)
        return pred()

    try:
        assert wait_for(lambda: client.get(
            "batch/v1", "Job", "default", "lm-modeller") is not None)
        client.mark_job_complete("default", "lm-modeller")
        assert wait_for(lambda: client.get(
            "apps/v1", "Deployment", "default", "ls") is not None)
        client.mark_deployment_ready("default", "ls")
        assert wait_for(
            lambda: Server(get(client, "Server", "ls")).ready)
    finally:
        stop.set()
        t.join(timeout=5)


def test_server_requires_model(harness):
    client, cloud, sci, mgr = harness
    client.create(Server.new("s2", spec={"image": "img"}).obj)
    mgr.reconcile_until_stable()
    cur = Server(get(client, "Server", "s2"))
    c = ko.get_condition(cur.obj, cond.SERVING)
    assert c["status"] == "False"
    assert c["reason"] == cond.REASON_MODEL_NOT_FOUND


# ---------------------------------------------------------------------------
# Notebook reconciler
# ---------------------------------------------------------------------------

def test_notebook_lifecycle_and_suspend(harness):
    client, cloud, sci, mgr = harness
    client.create(Notebook.new("nb", spec={"image": "nb-img"}).obj)
    mgr.reconcile_until_stable()
    pod = client.get("v1", "Pod", "default", "nb-notebook")
    assert pod is not None
    container = pod["spec"]["containers"][0]
    assert container["ports"][0]["containerPort"] == 8888
    assert container["readinessProbe"]["httpGet"]["path"] == "/api"
    assert container["command"][0] == "jupyter"

    client.mark_pod_ready("default", "nb-notebook")
    mgr.reconcile_until_stable()
    assert Notebook(get(client, "Notebook", "nb")).ready

    # Suspend deletes the pod and flips conditions.
    cur = get(client, "Notebook", "nb")
    cur["spec"]["suspend"] = True
    client.update(cur)
    mgr.reconcile_until_stable()
    assert client.get("v1", "Pod", "default", "nb-notebook") is None
    nb = Notebook(get(client, "Notebook", "nb"))
    assert nb.condition_true(cond.SUSPENDED) and not nb.ready

    # Resume recreates it.
    cur = get(client, "Notebook", "nb")
    cur["spec"]["suspend"] = False
    client.update(cur)
    mgr.reconcile_until_stable()
    assert client.get("v1", "Pod", "default", "nb-notebook") is not None


def test_notebook_recreated_on_spec_change(harness):
    client, cloud, sci, mgr = harness
    client.create(Notebook.new("nb2", spec={"image": "img:v1"}).obj)
    mgr.reconcile_until_stable()
    pod1 = client.get("v1", "Pod", "default", "nb2-notebook")
    cur = get(client, "Notebook", "nb2")
    cur["spec"]["image"] = "img:v2"
    client.update(cur)
    mgr.reconcile_until_stable()
    pod2 = client.get("v1", "Pod", "default", "nb2-notebook")
    assert pod2["spec"]["containers"][0]["image"] == "img:v2"
    assert pod2["metadata"]["uid"] != pod1["metadata"]["uid"]


# ---------------------------------------------------------------------------
# Full end-to-end chain (the system-test analog)
# ---------------------------------------------------------------------------

def test_e2e_dataset_model_server(harness):
    client, cloud, sci, mgr = harness
    client.create(Dataset.new("squad", spec={"image": "loader"}).obj)
    client.create(Model.new("llm", spec={
        "image": "trainer", "dataset": {"name": "squad"},
        "resources": {"tpu": {"type": "v5e", "topology": "2x2"}}}).obj)
    client.create(Server.new("api", spec={
        "image": "server", "model": {"name": "llm"}}).obj)

    mgr.reconcile_until_stable()
    client.mark_job_complete("default", "squad-data-loader")
    mgr.reconcile_until_stable()
    client.mark_job_complete("default", "llm-modeller")
    mgr.reconcile_until_stable()
    client.mark_deployment_ready("default", "api")
    mgr.reconcile_until_stable()

    assert Dataset(get(client, "Dataset", "squad")).ready
    assert Model(get(client, "Model", "llm")).ready
    srv = Server(get(client, "Server", "api"))
    assert srv.ready and srv.condition_true(cond.SERVING)
    # single-host 2x2: plain job, no fan-out service
    job = client.get("batch/v1", "Job", "default", "llm-modeller")
    assert "completionMode" not in job["spec"]


def test_model_tpu_slice_restart_with_resume(harness):
    """SURVEY §7 hard part #1: one host dies => the whole slice Job fails
    (backoffLimit 0) => the reconciler recreates it (bounded) and the
    trainer resumes from the last orbax checkpoint. The reference treats
    any job failure as terminal; this is net-new."""
    from runbooks_tpu.controller.model import RESTARTS_ANNOTATION

    client, cloud, sci, mgr = harness
    client.create(Model.new("slice", spec={
        "image": "trainer",
        "resources": {"tpu": {"type": "v5e", "topology": "2x4",
                              "maxRestarts": 2}}}).obj)
    mgr.reconcile_until_stable()
    job1 = client.get("batch/v1", "Job", "default", "slice-modeller")
    assert job1 is not None

    # Host dies -> slice Job fails -> Job recreated, attempt recorded.
    client.mark_job_complete("default", "slice-modeller", failed=True)
    mgr.reconcile_until_stable()
    cur = Model(get(client, "Model", "slice"))
    assert ko.annotations(cur.obj)[RESTARTS_ANNOTATION] == "1"
    job2 = client.get("batch/v1", "Job", "default", "slice-modeller")
    assert job2 is not None
    assert job2["metadata"]["uid"] != job1["metadata"]["uid"]  # recreated
    assert not ko.deep_get(job2, "status", "conditions", default=[])

    # Second failure: one retry left.
    client.mark_job_complete("default", "slice-modeller", failed=True)
    mgr.reconcile_until_stable()
    cur = Model(get(client, "Model", "slice"))
    assert ko.annotations(cur.obj)[RESTARTS_ANNOTATION] == "2"

    # Third failure exhausts maxRestarts -> terminal JobFailed.
    client.mark_job_complete("default", "slice-modeller", failed=True)
    mgr.reconcile_until_stable()
    cur = Model(get(client, "Model", "slice"))
    c = ko.get_condition(cur.obj, cond.COMPLETE)
    assert c["status"] == "False" and c["reason"] == cond.REASON_JOB_FAILED
    assert not cur.ready

    # The recreated Job's pod is unchanged — same artifacts mount — so the
    # trainer-side half (resume from the orbax checkpoint in artifacts) is
    # proven by tests/test_trainer.py::test_training_resumes_from_checkpoint.


def test_model_single_pod_failure_stays_terminal(harness):
    """Non-TPU (cheap CPU) jobs keep reference semantics: Job-level
    backoffLimit retries, then terminal failure — no slice restart."""
    client, cloud, sci, mgr = harness
    client.create(Model.new("cheap", spec={"image": "x"}).obj)
    mgr.reconcile_until_stable()
    client.mark_job_complete("default", "cheap-modeller", failed=True)
    mgr.reconcile_until_stable()
    cur = Model(get(client, "Model", "cheap"))
    c = ko.get_condition(cur.obj, cond.COMPLETE)
    assert c["reason"] == cond.REASON_JOB_FAILED


def test_model_invalid_accumulate_steps_surfaces_condition(harness):
    """A bad spec.params.accumulateSteps (non-power-of-two, or not dividing
    batch_size) must become an InvalidParams condition on the Model, not a
    ValueError crash-loop in the trainer Job."""
    client, cloud, sci, mgr = harness
    client.create(Model.new("am", spec={
        "image": "img",
        "params": {"model": "debug", "accumulateSteps": 3}}).obj)
    mgr.reconcile_until_stable()
    cur = Model(get(client, "Model", "am"))
    c = ko.get_condition(cur.obj, cond.COMPLETE)
    assert c["status"] == "False"
    assert c["reason"] == cond.REASON_INVALID_PARAMS
    assert "accumulateSteps" in c["message"]

    # Power-of-two but not dividing batch_size: still invalid.
    cur.obj["spec"]["params"] = {"model": "debug", "accumulate_steps": 4,
                                 "batch_size": 6}
    client.update(cur.obj)
    mgr.reconcile_until_stable()
    c = ko.get_condition(Model(get(client, "Model", "am")).obj,
                         cond.COMPLETE)
    assert c["reason"] == cond.REASON_INVALID_PARAMS
    assert "divide" in c["message"]

    # Fixing the spec clears the gate (the modeller Job gets created).
    cur = Model(get(client, "Model", "am"))
    cur.obj["spec"]["params"] = {"model": "debug", "accumulate_steps": 4,
                                 "batch_size": 8}
    client.update(cur.obj)
    mgr.reconcile_until_stable()
    c = ko.get_condition(Model(get(client, "Model", "am")).obj,
                         cond.COMPLETE)
    assert c["reason"] != cond.REASON_INVALID_PARAMS


def test_server_invalid_quantize_param_surfaces_condition(harness):
    """A typo'd spec.params.quantize must become a visible condition, not a
    crash-looping serve container behind a never-ready Deployment."""
    client, cloud, sci, mgr = harness
    client.create(Server.new("qs", spec={
        "image": "img", "model": {"name": "qm"},
        "params": {"model": "llama2-70b", "quantize": "int3"}}).obj)
    mgr.reconcile_until_stable()
    cur = Server(get(client, "Server", "qs"))
    c = ko.get_condition(cur.obj, cond.SERVING)
    assert c["status"] == "False"
    assert c["reason"] == cond.REASON_INVALID_PARAMS
    assert "int3" in c["message"]
    # Fixing the spec clears the gate (proceeds to the model gate).
    cur.obj["spec"]["params"]["quantize"] = "int4"
    client.update(cur.obj)
    mgr.reconcile_until_stable()
    c = ko.get_condition(Server(get(client, "Server", "qs")).obj,
                         cond.SERVING)
    assert c["reason"] != cond.REASON_INVALID_PARAMS


def test_model_preemption_restart_policy_knob(harness):
    """Train Jobs get a restart-on-preemption policy wired to the trainer's
    exit codes (docs/fault-tolerance.md): spec.params.preemption_restarts
    sets the in-place budget; the podFailurePolicy restarts on preemption-
    shaped exits (42/143, and node DisruptionTarget for free) but fails
    the Job on any other error instead of blind-retrying a TPU slice."""
    from runbooks_tpu.utils.contract import EXIT_PREEMPTED

    client, cloud, sci, mgr = harness
    client.create(Model.new("pr", spec={
        "image": "trainer",
        "params": {"model": "debug", "preemptionRestarts": 5},
        "resources": {"tpu": {"type": "v5e", "topology": "2x2"}}}).obj)
    mgr.reconcile_until_stable()
    job = client.get("batch/v1", "Job", "default", "pr-modeller")
    spec = job["spec"]
    assert spec["backoffLimit"] == 5  # single-host 2x2: no host scaling
    rules = spec["podFailurePolicy"]["rules"]
    assert rules[0]["action"] == "Ignore"
    assert rules[0]["onPodConditions"][0]["type"] == "DisruptionTarget"
    assert rules[1]["action"] == "Count"
    assert EXIT_PREEMPTED in rules[1]["onExitCodes"]["values"]
    assert rules[2]["action"] == "FailJob"
    assert rules[2]["onExitCodes"]["operator"] == "NotIn"
    assert EXIT_PREEMPTED in rules[2]["onExitCodes"]["values"]


def test_model_invalid_preemption_restarts_surfaces_condition(harness):
    """A bad spec.params.preemption_restarts value must become an
    InvalidParams condition, not a crash-looping Job."""
    client, cloud, sci, mgr = harness
    client.create(Model.new("prbad", spec={
        "image": "trainer",
        "params": {"model": "debug", "preemption_restarts": "lots"}}).obj)
    mgr.reconcile_until_stable()
    c = ko.get_condition(Model(get(client, "Model", "prbad")).obj,
                         cond.COMPLETE)
    assert c["status"] == "False"
    assert c["reason"] == cond.REASON_INVALID_PARAMS
    assert "preemption_restarts" in c["message"]

    cur = Model(get(client, "Model", "prbad"))
    cur.obj["spec"]["params"] = {"model": "debug",
                                 "preemption_restarts": -1}
    client.update(cur.obj)
    mgr.reconcile_until_stable()
    c = ko.get_condition(Model(get(client, "Model", "prbad")).obj,
                         cond.COMPLETE)
    assert c["reason"] == cond.REASON_INVALID_PARAMS
    assert ">= 0" in c["message"]

    # Valid value clears the gate and lands on the Job.
    cur = Model(get(client, "Model", "prbad"))
    cur.obj["spec"]["params"] = {"model": "debug", "preemption_restarts": 0}
    client.update(cur.obj)
    mgr.reconcile_until_stable()
    c = ko.get_condition(Model(get(client, "Model", "prbad")).obj,
                         cond.COMPLETE)
    assert c["reason"] != cond.REASON_INVALID_PARAMS
