"""In-process port-forward tests against a fake Kubernetes websocket
endpoint (server side of v4.channel.k8s.io implemented on the stdlib, like
the client). Covers: RFC6455 handshake + masking, the per-channel port
headers, bidirectional data pumping, and the error channel."""

import base64
import hashlib
import socket
import ssl
import struct
import threading
import time

import pytest

from runbooks_tpu.k8s.client import KubeConfig
from runbooks_tpu.k8s.portforward import _WS_GUID, PortForwarder, WebSocket


class FakeWsPodServer:
    """Accepts the portforward websocket upgrade and echoes channel-0 data
    uppercased; can emit an error-channel message instead."""

    def __init__(self, remote_port: int, error: bytes = b""):
        self.remote_port = remote_port
        self.error = error
        self.requests = []
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        threading.Thread(target=self._serve, daemon=True).start()

    # -- server-side frame helpers (unmasked) -----------------------------

    @staticmethod
    def _send(conn, payload, opcode=0x2):
        header = bytes([0x80 | opcode])
        n = len(payload)
        if n < 126:
            header += bytes([n])
        else:
            header += bytes([126]) + struct.pack(">H", n)
        conn.sendall(header + payload)

    @staticmethod
    def _recv(conn):
        def read(n):
            out = b""
            while len(out) < n:
                chunk = conn.recv(n - len(out))
                if not chunk:
                    raise ConnectionError
                out += chunk
            return out
        b0, b1 = read(2)
        opcode, n = b0 & 0x0F, b1 & 0x7F
        if n == 126:
            n = struct.unpack(">H", read(2))[0]
        mask = read(4) if b1 & 0x80 else b""
        payload = read(n)
        if mask:
            payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        return opcode, payload

    def _serve(self):
        self._srv.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed during shutdown
            threading.Thread(target=self._session, args=(conn,),
                             daemon=True).start()

    def _session(self, conn):
        data = b""
        while b"\r\n\r\n" not in data:
            data += conn.recv(4096)
        head = data.split(b"\r\n\r\n")[0].decode()
        self.requests.append(head)
        key = next(l.split(":", 1)[1].strip() for l in head.split("\r\n")
                   if l.lower().startswith("sec-websocket-key"))
        accept = base64.b64encode(
            hashlib.sha1((key + _WS_GUID).encode()).digest()).decode()
        conn.sendall((
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {accept}\r\n"
            "Sec-WebSocket-Protocol: v4.channel.k8s.io\r\n\r\n").encode())
        # Port headers on data + error channels (uint16 LE).
        self._send(conn, b"\x00" + struct.pack("<H", self.remote_port))
        self._send(conn, b"\x01" + struct.pack("<H", self.remote_port))
        if self.error:
            self._send(conn, b"\x01" + self.error)
            return
        try:
            while True:
                opcode, payload = self._recv(conn)
                if opcode == 0x8:
                    return
                if opcode == 0x2 and payload and payload[0] == 0:
                    self._send(conn, b"\x00" + payload[1:].upper())
        except ConnectionError:
            pass

    def close(self):
        self._stop.set()
        self._srv.close()


def kubeconfig_for(server: FakeWsPodServer) -> KubeConfig:
    return KubeConfig(f"http://127.0.0.1:{server.port}",
                      ssl.create_default_context(),
                      {"Authorization": "Bearer test-token"})


def test_port_forward_roundtrip():
    backend = FakeWsPodServer(remote_port=8080)
    ready = threading.Event()
    bound = {}

    def on_ready(port):
        bound["port"] = port
        ready.set()

    pf = PortForwarder(kubeconfig_for(backend), "ns1", "pod1",
                       local_port=0, remote_port=8080, on_ready=on_ready)
    threading.Thread(target=pf.serve, daemon=True).start()
    assert ready.wait(timeout=30)

    with socket.create_connection(("127.0.0.1", bound["port"]), 30) as c:
        c.sendall(b"hello pod")
        c.settimeout(30)
        out = c.recv(1024)
    assert out == b"HELLO POD"

    # The wire request hit the right subresource with auth + subprotocol.
    head = backend.requests[0]
    assert "GET /api/v1/namespaces/ns1/pods/pod1/portforward?ports=8080" \
        in head
    assert "Authorization: Bearer test-token" in head
    assert "v4.channel.k8s.io" in head

    # A second connection dials a fresh websocket session (3 = the serve()
    # preflight + one session per TCP connection).
    with socket.create_connection(("127.0.0.1", bound["port"]), 30) as c:
        c.sendall(b"x")
        c.settimeout(30)
        assert c.recv(64) == b"X"
    assert len(backend.requests) == 3

    pf.stop()
    backend.close()


def test_port_forward_error_channel_closes_connection():
    backend = FakeWsPodServer(remote_port=9000,
                              error=b"pod not running")
    ready = threading.Event()
    bound = {}
    pf = PortForwarder(kubeconfig_for(backend), "ns1", "pod1",
                       local_port=0, remote_port=9000,
                       on_ready=lambda p: (bound.update(port=p),
                                           ready.set()))

    def serve_expecting_error():
        # serve() re-raising the apiserver error is the designed exit here;
        # the assertions below read it from pf._error.
        with pytest.raises(ConnectionError):
            pf.serve()

    threading.Thread(target=serve_expecting_error, daemon=True).start()
    assert ready.wait(timeout=30)
    with socket.create_connection(("127.0.0.1", bound["port"]), 30) as c:
        c.settimeout(30)
        assert c.recv(64) == b""  # closed after the error event
    # The apiserver's message is captured, not swallowed (serve() raises).
    deadline = time.time() + 30
    while time.time() < deadline and pf._error is None:
        time.sleep(0.05)
    assert "pod not running" in str(pf._error)
    backend.close()


def test_port_forward_preflight_rejects_bad_auth():
    """serve() fails fast (before on_ready) when the dial is rejected."""
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def reject_all():
        srv.settimeout(2)
        try:
            while True:
                conn, _ = srv.accept()
                conn.recv(4096)
                conn.sendall(b"HTTP/1.1 403 Forbidden\r\n\r\n")
                conn.close()
        except (socket.timeout, OSError):
            pass

    threading.Thread(target=reject_all, daemon=True).start()
    cfg = KubeConfig(f"http://127.0.0.1:{port}",
                     ssl.create_default_context(), {})
    pf = PortForwarder(cfg, "ns", "pod", 0, 8080,
                       on_ready=lambda p: pytest.fail("must not get ready"))
    with pytest.raises(ConnectionError, match="403"):
        pf.serve()
    srv.close()


def test_websocket_rejects_bad_handshake():
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def reject():
        conn, _ = srv.accept()
        conn.recv(4096)
        conn.sendall(b"HTTP/1.1 403 Forbidden\r\n\r\n")
        conn.close()

    threading.Thread(target=reject, daemon=True).start()
    with pytest.raises(ConnectionError, match="403"):
        WebSocket.connect(f"http://127.0.0.1:{port}/x", {}, "proto")
    srv.close()
