"""MoE + expert-parallelism tests.

Oracle strategy: expert-parallel meshes must produce bit-for-bit the same
results as replicated meshes (routing is deterministic); the cached decode
path must match the no-cache forward; and the load-balance aux loss must
reach the training objective.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.moe import moe_capacity
from runbooks_tpu.models.transformer import forward, init_params
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh
from tests.conftest import partial_manual_shard_map_broken


def moe_cfg(**over):
    kw = dict(vocab_size=64, hidden_size=32, intermediate_size=48,
              num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
              max_seq_len=32, dtype="float32", moe_num_experts=4,
              moe_top_k=2, moe_capacity_factor=4.0)  # no drops: exact math
    kw.update(over)
    return get_config("debug", **kw)


def tokens_for(cfg, b=4, s=12, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)


def test_moe_forward_and_aux():
    cfg = moe_cfg()
    params = init_params(cfg, jax.random.key(0))
    assert "moe" in params["layers"] and "mlp" not in params["layers"]
    toks = tokens_for(cfg)
    logits, _, aux = forward(cfg, params, toks, with_aux=True)
    assert logits.shape == (4, 12, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    # Switch aux loss: E * sum(me*ce) >= 1 (equality at perfect balance).
    assert float(aux) >= cfg.num_layers * 0.99


def test_moe_routing_actually_mixes_experts():
    cfg = moe_cfg()
    params = init_params(cfg, jax.random.key(0))
    toks = tokens_for(cfg, b=2, s=16)
    # Zeroing one expert's weights changes the output only if that expert
    # receives traffic.
    logits1, _ = forward(cfg, params, toks)
    broken = jax.tree.map(lambda a: a, params)
    wo = np.asarray(broken["layers"]["moe"]["wo"]).copy()
    wo[:, 0] = 0.0
    broken["layers"]["moe"]["wo"] = jnp.asarray(wo)
    logits2, _ = forward(cfg, broken, toks)
    assert not np.allclose(np.asarray(logits1), np.asarray(logits2))


def test_moe_expert_parallel_matches_replicated():
    cfg = moe_cfg()
    params = init_params(cfg, jax.random.key(0))
    toks = tokens_for(cfg, b=8, s=8)

    plain = make_mesh(MeshConfig(fsdp=8))
    with jax.set_mesh(plain):
        want, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, toks)

    ep = make_mesh(MeshConfig(data=2, expert=4, fsdp=1))
    with jax.set_mesh(ep):
        got, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, toks)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_moe_capacity_drops_tokens():
    # capacity_factor so small every expert takes ~1 token; dropped tokens
    # contribute zero from the FFN (residual stream still carries them).
    cfg = moe_cfg(moe_capacity_factor=0.01, moe_top_k=1)
    assert moe_capacity(cfg, 64) == 1
    params = init_params(cfg, jax.random.key(0))
    toks = tokens_for(cfg, b=2, s=16)
    logits, _ = forward(cfg, params, toks)
    assert np.isfinite(np.asarray(logits)).all()


def test_moe_cached_decode_matches_full_forward():
    from runbooks_tpu.serve.engine import InferenceEngine, Request

    cfg = moe_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=2)

    prompt = [5, 9, 17]
    req = Request(prompt_tokens=list(prompt), max_tokens=6, temperature=0.0)
    engine.generate([req])

    toks = list(prompt)
    for _ in range(6):
        logits, _ = forward(cfg, params, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    assert req.output_tokens == toks[len(prompt):]


def test_moe_train_step_learns_and_balances():
    from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
    from runbooks_tpu.train.step import create_train_state, make_train_step

    cfg = moe_cfg()
    mesh = make_mesh(MeshConfig(data=2, expert=2, fsdp=1, tensor=2))
    opt = make_optimizer(OptimizerConfig(total_steps=6, warmup_steps=0,
                                         learning_rate=1e-2))
    state, shardings = create_train_state(cfg, opt, mesh, jax.random.key(0))
    step = make_train_step(cfg, opt, mesh, shardings)

    # Expert weights sharded over the expert axis (the memory win of EP).
    wi = state.params["layers"]["moe"]["wi_gate"]
    assert wi.sharding.spec[1] == "expert"

    rng = np.random.default_rng(0)
    data = rng.integers(0, cfg.vocab_size, (8, 13)).astype(np.int32)
    batch = {"tokens": data[:, :-1], "targets": data[:, 1:],
             "loss_mask": np.ones((8, 12), np.float32)}
    losses = []
    with jax.set_mesh(mesh):
        for _ in range(5):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


@pytest.mark.skipif(
    partial_manual_shard_map_broken(),
    reason="old-jaxlib SPMD PartitionId limitation: partial-manual "
           "(stage) shard_map cannot be partitioned")
def test_moe_composes_with_pipeline():
    cfg = moe_cfg(num_layers=4)
    params = init_params(cfg, jax.random.key(0))
    toks = tokens_for(cfg, b=4, s=8)

    plain = make_mesh(MeshConfig(fsdp=8))
    with jax.set_mesh(plain):
        want, _, aux_want = jax.jit(
            lambda p, t: forward(cfg, p, t, with_aux=True))(params, toks)

    pp = make_mesh(MeshConfig(stage=2, expert=2, fsdp=2))
    with jax.set_mesh(pp):
        got, _, aux_got = jax.jit(
            lambda p, t: forward(cfg, p, t, with_aux=True))(params, toks)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # aux under PP is a mean of per-microbatch balance losses — close to
    # but not identical to the full-batch loss (nonlinear in the batch).
    assert np.isfinite(float(aux_got))
    assert abs(float(aux_got) - float(aux_want)) / float(aux_want) < 0.25


def test_moe_sharded_serving_matches_unsharded():
    """The serving engine under an expert+tensor mesh produces the same
    greedy decode as unsharded (EP in the decode path)."""
    from runbooks_tpu.serve.engine import InferenceEngine, Request

    cfg = moe_cfg()
    params = init_params(cfg, jax.random.key(0))
    prompts = [[5, 9, 17], [3, 4, 5, 6]]

    plain = InferenceEngine(cfg, params, max_slots=2)
    plain_reqs = [Request(prompt_tokens=list(p), max_tokens=6,
                          temperature=0.0) for p in prompts]
    plain.generate(plain_reqs)

    mesh = make_mesh(MeshConfig(data=1, expert=4, fsdp=1, tensor=2))
    sharded = InferenceEngine(cfg, params, max_slots=2, mesh=mesh)
    shard_reqs = [Request(prompt_tokens=list(p), max_tokens=6,
                          temperature=0.0) for p in prompts]
    sharded.generate(shard_reqs)

    for a, b in zip(plain_reqs, shard_reqs):
        assert a.output_tokens == b.output_tokens
    wi = sharded.params["layers"]["moe"]["wi_gate"]
    assert wi.sharding.spec[1] == "expert"
