"""Worker process for the multi-process jax.distributed test.

Launched by tests/test_distributed.py with the exact env the operator's
fan-out injects (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID — cloud/resources.py:distributed_env). Forms the runtime via
parallel.distributed.initialize(), then proves the collectives work:

1. pmap psum across all processes' devices;
2. a global-mesh jit train step on a tiny model, with the batch assembled
   from per-process shards (the real multi-host input path).

Prints one JSON line for the parent to assert on.
"""

import json
import os
import sys

# Launched as `python tests/distworker.py`: the repo root (not tests/) is
# what imports must resolve against.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from runbooks_tpu.parallel.distributed import (  # noqa: E402
    initialize,
    is_primary,
    process_index,
)


def main() -> int:
    formed = initialize(timeout_s=60)
    assert formed, "initialize() returned False with slice env set"
    nproc = int(os.environ["JAX_NUM_PROCESSES"])
    assert jax.process_count() == nproc, (
        jax.process_count(), nproc)
    assert jax.process_index() == process_index()

    # 1. Cross-process psum: every local device contributes 1.
    local = jax.local_device_count()
    total = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(
        jnp.ones((local,)))
    world = int(np.asarray(total)[0])
    assert world == jax.device_count(), (world, jax.device_count())

    # 2. One train step over a global data-parallel mesh.
    from runbooks_tpu.models.config import get_config
    from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh
    from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
    from runbooks_tpu.train.step import create_train_state, make_train_step

    cfg = get_config("debug", vocab_size=64, hidden_size=32,
                     intermediate_size=64, num_layers=1, num_heads=4,
                     num_kv_heads=4, head_dim=8, max_seq_len=16,
                     dtype="float32")
    mesh = make_mesh(MeshConfig(data=jax.device_count()))
    opt = make_optimizer(OptimizerConfig(total_steps=2, warmup_steps=0))
    state, shardings = create_train_state(cfg, opt, mesh, jax.random.key(0))
    step = make_train_step(cfg, opt, mesh, shardings)

    # Per-process local shard -> global array (the multi-host input path).
    from jax.sharding import NamedSharding, PartitionSpec as P

    global_bs, seq = jax.device_count(), 8
    rng = np.random.default_rng(0)  # same seed everywhere; slice per proc
    all_tokens = rng.integers(0, cfg.vocab_size,
                              (global_bs, seq + 1)).astype(np.int32)
    per = global_bs // jax.process_count()
    lo = jax.process_index() * per

    def globalize(arr):
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(("data",))), arr[lo:lo + per])

    batch = {
        "tokens": globalize(all_tokens[:, :-1]),
        "targets": globalize(all_tokens[:, 1:]),
        "loss_mask": globalize(
            np.ones((global_bs, seq), np.float32)),
    }
    with jax.set_mesh(mesh):
        state, metrics = step(state, batch)
        loss = float(metrics["loss"])
    assert np.isfinite(loss), loss

    print(json.dumps({"ok": True, "process": jax.process_index(),
                      "world_devices": world, "loss": round(loss, 4),
                      "primary": is_primary()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
