"""Loader workload tests: import -> artifact checkpoint -> serving restore
(the /content handoff between Model and Server resources)."""

import json
import os

import jax
import numpy as np
import pytest
import torch


def test_loader_random_then_serve_restore(tmp_path, monkeypatch):
    monkeypatch.setenv("RBT_CONTENT_DIR", str(tmp_path))
    os.makedirs(tmp_path / "artifacts", exist_ok=True)
    (tmp_path / "params.json").write_text(json.dumps({
        "model": "debug", "source": "random",
        "model_overrides": {"dtype": "float32"},
    }))
    import importlib

    from runbooks_tpu.utils import contract
    importlib.reload(contract)  # re-read RBT_CONTENT_DIR
    from runbooks_tpu.models import loader

    assert loader.main() == 0
    assert (tmp_path / "artifacts" / "model.json").exists()
    assert (tmp_path / "artifacts" / "checkpoints" / "0").exists()

    # Server-side restore finds the loader's params.
    from runbooks_tpu.serve.api import load_model

    cfg, params = load_model({
        "model": "debug", "model_overrides": {"dtype": "float32"},
        "checkpoint": str(tmp_path / "artifacts")})
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    assert n == cfg.num_params


def test_loader_from_hf_dir(tmp_path, monkeypatch):
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = LlamaForCausalLM(hf_cfg)
    model_dir = tmp_path / "model"
    hf.save_pretrained(model_dir, safe_serialization=False)

    content = tmp_path / "content"
    os.makedirs(content / "artifacts")
    (content / "params.json").write_text(json.dumps({
        "model": "debug",
        "model_overrides": {
            "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
            "num_layers": 2, "num_heads": 4, "num_kv_heads": 2,
            "head_dim": 16, "dtype": "float32", "tie_embeddings": False,
        },
        "source": "dir", "dir": str(model_dir),
    }))
    monkeypatch.setenv("RBT_CONTENT_DIR", str(content))
    import importlib

    from runbooks_tpu.utils import contract
    importlib.reload(contract)
    from runbooks_tpu.models import loader

    assert loader.main() == 0
    meta = json.loads((content / "artifacts" / "model.json").read_text())
    assert meta["source"] == "dir"
    assert meta["num_params"] > 0
