"""QoS classes + host-RAM KV swap tier (ISSUE 19).

Covers: the HostPagePool staging tier (bit-identical store/load round
trips, deterministic slot handout, double-free detection), swap-out on
radix eviction and swap-in on a returning session's admission (token-
identical with the no-cache greedy oracle), slot preemption under class
pressure with loss-free resume, class-ordered admission queues and
per-class queue shares, the load-derived Retry-After hint, swapfail
fault injection degrading to drop/recompute without crashing or leaking
either tier, exact refcount balance across both tiers after deadline
expiry of a preempted request, zero unexpected XLA compiles in a steady
loop with live swap + preemption traffic, and the HTTP surface
(priority validation, X-Priority header, swap/preemption metric
families, /debug/memory host census, 429 Retry-After).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import forward, init_params
from runbooks_tpu.serve.engine import (
    PRIORITY_RANK,
    EngineOverloaded,
    InferenceEngine,
    Request,
)
from runbooks_tpu.serve.paging import (
    HostPagePool,
    PagedInferenceEngine,
)


def tiny_cfg(**over):
    base = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                max_seq_len=64, dtype="float32")
    base.update(over)
    return dataclasses.replace(get_config("llama2-7b"), **base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.key(0))


def greedy_rollout(cfg, params, prompt, n):
    toks = list(prompt)
    for _ in range(n):
        logits, _ = forward(cfg, params, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


# ---------------------------------------------------------------------------
# HostPagePool
# ---------------------------------------------------------------------------

def test_host_pool_alloc_store_load_invariants():
    cfg = tiny_cfg()
    pool = HostPagePool(cfg, host_pages=2, page_size=16)
    assert (pool.free_count, pool.used_count) == (2, 0)
    # ascending deterministic handout; exhaustion returns None, never
    # raises (the caller chooses evict_host vs degrade-to-drop)
    a, b = pool.alloc(), pool.alloc()
    assert (a, b) == (0, 1)
    assert pool.alloc() is None
    page_shape = (cfg.num_layers, 16, cfg.num_kv_heads, cfg.head_dim)
    k = np.random.default_rng(0).standard_normal(page_shape).astype(
        np.float32)
    v = np.random.default_rng(1).standard_normal(page_shape).astype(
        np.float32)
    pool.store(a, k, v)
    lk, lv = pool.load(a)
    # bit-identical round trip: swap-in must reproduce the evicted
    # page's K/V exactly, or resumed decodes drift from the oracle
    assert np.array_equal(lk, k) and np.array_equal(lv, v)
    pool.free(a)
    assert (pool.free_count, pool.used_count) == (1, 1)
    with pytest.raises(RuntimeError):
        pool.free(a)                 # double-free is a bug, not a no-op
    with pytest.raises(RuntimeError):
        pool.load(a)                 # load of a freed slot likewise
    with pytest.raises(RuntimeError):
        pool.store(a, k, v)
    with pytest.raises(ValueError):
        HostPagePool(cfg, host_pages=0, page_size=16)


# ---------------------------------------------------------------------------
# Swap round trip: evict to host, return, swap back in
# ---------------------------------------------------------------------------

def test_swap_roundtrip_matches_oracle(model):
    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                                  num_pages=5, kv_host_pages=4)
    shared = list(range(1, 33))
    engine.register_prefix(shared)    # 2 tree pages resident in HBM
    assert engine.pager.occupancy()["pages_shared"] == 2
    # a non-matching max-reservation request forces eviction; with the
    # host tier wired, evicted prefix pages COPY to host instead of
    # dropping
    big = Request(prompt_tokens=list(range(90, 122)), max_tokens=32,
                  temperature=0.0)
    engine.generate([big])
    occ = engine.pager.occupancy()
    assert occ["swap_out_pages_total"] >= 1
    assert occ["host_pages_used"] >= 1
    # the returning session swaps its prefix back into HBM — admission
    # rides the normal radix-match path, paying a device_put instead of
    # recomputing the prefill — and the tokens are identical to the
    # no-cache oracle
    r = Request(prompt_tokens=shared + [50], max_tokens=5,
                temperature=0.0)
    engine.generate([r])
    assert r.output_tokens == greedy_rollout(cfg, params, shared + [50],
                                             5)
    occ = engine.pager.occupancy()
    assert occ["swap_in_pages_total"] >= 1


# ---------------------------------------------------------------------------
# Preemption: displace batch for interactive, resume with no token loss
# ---------------------------------------------------------------------------

def test_preemption_resumes_without_token_loss(model):
    cfg, params = model
    # decode_chunk=2 keeps the batch request mid-flight for several
    # steps regardless of the platform tuning table
    engine = PagedInferenceEngine(cfg, params, max_slots=1, page_size=16,
                                  num_pages=5, kv_host_pages=8,
                                  preemption="swap", decode_chunk=2)
    batch = Request(prompt_tokens=list(range(1, 33)), max_tokens=16,
                    temperature=0.0, priority="batch")
    engine.submit(batch)
    for _ in range(3):                # admit + decode a few tokens
        engine.step()
    assert engine.active.any() and not batch.finished
    inter = Request(prompt_tokens=list(range(90, 106)), max_tokens=8,
                    temperature=0.0, priority="interactive")
    engine.submit(inter)
    engine.step()
    # the only slot held a strictly-worse class while interactive waited
    # on capacity: the batch request was displaced at the step boundary
    assert engine.preemptions == 1
    assert not batch.finished         # re-queued, not shed
    while engine.has_work():
        engine.step()
    assert engine.preempted_resumed == 1
    # loss-free resume: the preempted request's final output is token-
    # identical to an undisturbed greedy run, finish_reason unchanged
    assert batch.output_tokens == greedy_rollout(
        cfg, params, batch.prompt_tokens, 16)
    assert batch.finish_reason == "length"
    assert inter.output_tokens == greedy_rollout(
        cfg, params, inter.prompt_tokens, 8)


# ---------------------------------------------------------------------------
# QoS admission: class-ordered queue, per-class shares, Retry-After
# ---------------------------------------------------------------------------

def test_queue_class_ordering_and_shares(model):
    cfg, params = model
    engine = InferenceEngine(cfg, params, max_slots=1, max_queue=10,
                             queue_shares={"batch": 0.2})
    # batch's share bounds it to ceil(0.2 * 10) = 2 queued entries —
    # the third sheds while other classes keep their queue room
    mk = lambda pri, t: Request(prompt_tokens=[t, t + 1], max_tokens=2,
                                temperature=0.0, priority=pri)
    engine.submit(mk("batch", 1))
    engine.submit(mk("batch", 3))
    with pytest.raises(EngineOverloaded, match="batch queue share"):
        engine.submit(mk("batch", 5))
    engine.submit(mk("standard", 7))
    engine.submit(mk("interactive", 9))
    # class-ordered queue: interactive ahead of standard ahead of batch,
    # FIFO within a class
    assert [q.priority for q in engine.queue] == \
        ["interactive", "standard", "batch", "batch"]
    assert [q.prompt_tokens[0] for q in engine.queue[2:]] == [1, 3]
    # load-derived Retry-After: queue depth in slot-drain units,
    # clamped to [1, 30]
    assert engine.retry_after_hint() == 4
    for t in range(6):
        engine.submit(mk("standard", 20 + 2 * t))
    assert engine.retry_after_hint() == 10
    engine.queue.extend(engine.queue[:1] * 90)   # synthetic deep backlog
    assert engine.retry_after_hint() == 30
    engine.queue.clear()
    assert engine.retry_after_hint() == 1


def test_qos_validation_is_typed():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    with pytest.raises(ValueError, match="unknown class"):
        InferenceEngine(cfg, params, max_slots=1,
                        queue_shares={"urgent": 0.5})
    with pytest.raises(ValueError, match="in \\(0, 1\\]"):
        InferenceEngine(cfg, params, max_slots=1,
                        queue_shares={"batch": 0.0})
    # the dense engine has no pages to swap: preemption=swap is a typed
    # construction error pointing at kv_paging, not a silent no-op
    with pytest.raises(ValueError, match="kv_paging: paged"):
        InferenceEngine(cfg, params, max_slots=1, preemption="swap")
    with pytest.raises(ValueError, match="preemption"):
        InferenceEngine(cfg, params, max_slots=1, preemption="maybe")
    engine = InferenceEngine(cfg, params, max_slots=1)
    with pytest.raises(ValueError, match="priority"):
        engine.validate(Request(prompt_tokens=[1, 2], max_tokens=2,
                                priority="urgent"))


# ---------------------------------------------------------------------------
# Fault injection: swap copies fail, the engine degrades, nothing leaks
# ---------------------------------------------------------------------------

def test_swapfail_degrades_swap_out_to_drop(model, monkeypatch):
    cfg, params = model
    monkeypatch.setenv("RBT_FAULT_INJECT", "swapfail:1")
    engine = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                                  num_pages=5, kv_host_pages=4)
    shared = list(range(1, 33))
    engine.register_prefix(shared)
    big = Request(prompt_tokens=list(range(90, 122)), max_tokens=32,
                  temperature=0.0)
    engine.generate([big])            # first swap copy fails -> drop
    occ = engine.pager.occupancy()
    assert occ["swap_dropped_pages_total"] >= 1
    # the dropped prefix recomputes; correctness is unaffected
    r = Request(prompt_tokens=shared + [50], max_tokens=5,
                temperature=0.0)
    engine.generate([r])
    assert r.output_tokens == greedy_rollout(cfg, params, shared + [50],
                                             5)


def test_swapfail_degrades_swap_in_to_recompute(model):
    cfg, params = model
    # a roomy pool: the returning admission below must need NO eviction,
    # so the armed fault lands on its swap-in, not an eviction's swap-out
    engine = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                                  num_pages=8, kv_host_pages=4)
    shared = list(range(1, 33))
    engine.register_prefix(shared)
    # push the idle prefix to the host tier (healthy swap-outs)
    assert engine.pager.radix.evict(2) == 2
    assert engine.pager.occupancy()["host_pages_used"] == 2
    # arm the injector: the next copy attempt is the returning session's
    # swap-in, which must roll back the admission (failed node dropped
    # from the tree) and recompute — degrade, never crash or leak
    engine._swap_fault = 1
    r = Request(prompt_tokens=shared + [50], max_tokens=5,
                temperature=0.0)
    engine.generate([r])
    assert r.output_tokens == greedy_rollout(cfg, params, shared + [50],
                                             5)
    assert engine.pager.occupancy()["swap_in_pages_total"] == 0
    # both tiers drain to exactly zero: every reference taken during the
    # rolled-back admission was returned
    engine.pager.radix.evict(10 ** 6)
    engine.pager.radix.evict_host(10 ** 6)
    assert engine.pager.allocator.used_count == 0
    assert engine.host_pool.used_count == 0


def test_swapfail_spec_is_validated(monkeypatch):
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    monkeypatch.setenv("RBT_FAULT_INJECT", "swapfail:0")
    with pytest.raises(ValueError, match="K must be >= 1"):
        InferenceEngine(cfg, params, max_slots=1)
    monkeypatch.setenv("RBT_FAULT_INJECT", "swapfail:soon")
    with pytest.raises(ValueError, match="swapfail:K"):
        InferenceEngine(cfg, params, max_slots=1)


# ---------------------------------------------------------------------------
# Release guarantees: deadline expiry of a preempted request
# ---------------------------------------------------------------------------

def test_preempted_deadline_expiry_balances_both_tiers(model):
    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=1, page_size=16,
                                  num_pages=5, kv_host_pages=4,
                                  preemption="swap", decode_chunk=2)
    batch = Request(prompt_tokens=list(range(1, 33)), max_tokens=16,
                    temperature=0.0, priority="batch", deadline_s=30.0)
    engine.submit(batch)
    for _ in range(3):
        engine.step()
    inter = Request(prompt_tokens=list(range(90, 106)), max_tokens=8,
                    temperature=0.0, priority="interactive")
    engine.submit(inter)
    engine.step()
    assert engine.preemptions == 1 and not batch.finished
    # the preempted request's deadline expires while it waits in the
    # queue (a disconnecting client rides the same expiry path): it
    # finishes empty-handed and its adopted pages stay shareable tree
    # state, owned by the hierarchy — not leaked to a dead request
    batch.deadline_s = 0.0
    engine.step()
    assert batch.finish_reason == "deadline"
    while engine.has_work():
        engine.step()
    assert inter.finish_reason == "length"
    occ = engine.pager.occupancy()
    assert occ["pages_used"] == occ["pages_shared"]
    # evict everything from both tiers: the refcounts balance exactly —
    # zero pages held on either tier once the trees are emptied
    engine.pager.radix.evict(10 ** 6)
    engine.pager.radix.evict_host(10 ** 6)
    assert engine.pager.allocator.used_count == 0
    assert engine.host_pool.used_count == 0


# ---------------------------------------------------------------------------
# Compile discipline with live swap + preemption traffic
# ---------------------------------------------------------------------------

def test_zero_unexpected_compiles_with_swap_and_preemption(model):
    from runbooks_tpu.obs import device as obs_device

    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                                  num_pages=5, kv_host_pages=8,
                                  preemption="swap", decode_chunk=2)
    try:
        engine.warmup()
        census = engine.warmup_census
        # one warmed program per swap direction, page index traced
        assert census["swap_programs"] == 2
        assert census["kv_host_pages"] == 8
        sentinel = obs_device.SENTINEL
        before = sentinel.unexpected
        # steady traffic across every tier transition: eviction-driven
        # swap-out, returning-session swap-in, preemption adoption, and
        # preempted-resume
        shared = list(range(1, 33))
        engine.register_prefix(shared)
        big = Request(prompt_tokens=list(range(90, 122)), max_tokens=32,
                      temperature=0.0)
        engine.generate([big])
        back = Request(prompt_tokens=shared + [50], max_tokens=5,
                       temperature=0.0)
        engine.generate([back])
        batches = [Request(prompt_tokens=list(range(40 + 8 * i,
                                                    56 + 8 * i)),
                           max_tokens=16, temperature=0.0,
                           priority="batch") for i in range(2)]
        for b in batches:
            engine.submit(b)
        for _ in range(3):
            engine.step()
        inter = Request(prompt_tokens=list(range(70, 86)), max_tokens=8,
                        temperature=0.0, priority="interactive")
        engine.submit(inter)
        while engine.has_work():
            engine.step()
        assert all(r.finished for r in batches + [inter, big, back])
        occ = engine.pager.occupancy()
        assert occ["swap_out_pages_total"] >= 1
        assert occ["swap_in_pages_total"] >= 1
        assert engine.preemptions >= 1
        assert engine.preemptions == engine.preempted_resumed
        assert sentinel.unexpected == before, sentinel.recent_unexpected()
    finally:
        engine.release_steady()


# ---------------------------------------------------------------------------
# HTTP surface: priority plumbing, metric families, host census
# ---------------------------------------------------------------------------

def test_http_qos_and_host_tier_surface(model):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg, params = model
    app = create_server(cfg, params, max_slots=2, kv_paging=True,
                        page_size=16, num_pages=5, kv_host_pages=2,
                        preemption="swap",
                        queue_shares={"batch": 0.5})

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "hello", "max_tokens": 2, "temperature": 0.0,
                "priority": "urgent"})
            assert r.status == 400
            body = await r.json()
            assert "priority" in body["error"]["message"]
            # body field beats the X-Priority header; either spelling of
            # a valid class is accepted case-insensitively
            r = await client.post(
                "/v1/completions",
                json={"prompt": "hello", "max_tokens": 2,
                      "temperature": 0.0, "priority": "Batch"},
                headers={"X-Priority": "interactive"})
            assert r.status == 200
            r = await client.post(
                "/v1/completions",
                json={"prompt": "hello again", "max_tokens": 2,
                      "temperature": 0.0},
                headers={"X-Priority": "interactive"})
            assert r.status == 200
            r = await client.get("/metrics")
            text = await r.text()
            for fam in ("serve_kv_host_pages_used",
                        "serve_kv_host_pages_free",
                        "serve_kv_swap_out_pages_total",
                        "serve_kv_swap_in_pages_total",
                        "serve_kv_swap_dropped_pages_total",
                        "serve_preemptions_total",
                        "serve_preempted_resumed_total"):
                assert f"\n{fam} " in text or text.startswith(
                    f"{fam} "), fam
            r = await client.get("/debug/memory")
            occ = (await r.json())["kv_occupancy"]
            assert occ["host_pages_total"] == 2
            assert occ["host_pages_used"] + occ["host_pages_free"] == 2

    asyncio.run(drive())


def test_http_shed_carries_load_derived_retry_after(model):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg, params = model
    app = create_server(cfg, params, max_slots=1, max_queue=0)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "shed me", "max_tokens": 2})
            assert r.status == 429
            # load-derived hint, not a hardcoded constant: an empty
            # queue drains in one slot turn
            assert r.headers.get("Retry-After") == "1"

    asyncio.run(drive())


# ---------------------------------------------------------------------------
# Controller validation
# ---------------------------------------------------------------------------

def test_validate_params_kv_tier():
    from runbooks_tpu.controller.common import validate_params

    assert validate_params({"kv_paging": "paged", "kv_host_pages": 64,
                            "preemption": "swap",
                            "queue_share_batch": 0.25}) is None
    assert validate_params({"kvPaging": "paged",
                            "kvHostPages": 8}) is None
    # typed errors, never a silent default
    assert "preemption" in validate_params({"kv_paging": "paged",
                                            "preemption": "swa"})
    assert "kv_host_pages" in validate_params({"kv_paging": "paged",
                                               "kv_host_pages": -1})
    assert "kv_host_pages" in validate_params({"kv_paging": "paged",
                                               "kv_host_pages": "many"})
    assert "queue_share_batch" in validate_params(
        {"queue_share_batch": 0})
    assert "queueShareInteractive" in validate_params(
        {"queueShareInteractive": 1.5})
    # cross-field: both features swap radix PAGES — they need the paged
    # engine, and the error says so
    err = validate_params({"kv_host_pages": 4})
    assert "kv_paging: paged" in err
    err = validate_params({"preemption": "swap"})
    assert "kv_paging: paged" in err
    assert PRIORITY_RANK == {"interactive": 0, "standard": 1, "batch": 2}
