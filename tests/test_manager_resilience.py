"""Manager/leader behavior under apiserver failure.

r4 verdict, Weak #2: one failed LIST killed the manager thread while the
leader lease kept renewing — a dead leader that looked alive. These tests
kill the wire apiserver mid-run and assert the manager resumes, and kill
the manager under leader election and assert a standby takes over
immediately (lease released, not waited out).
"""

import ssl
import threading
import time

import pytest

from runbooks_tpu.api.types import API_VERSION
from runbooks_tpu.controller.leader import LEASE_API, LeaderElector
from runbooks_tpu.controller.main import run_with_leader_election
from runbooks_tpu.controller.manager import Ctx, Manager, Result
from runbooks_tpu.k8s import objects as ko
from runbooks_tpu.k8s.client import K8sClient, KubeConfig
from runbooks_tpu.k8s.fake import FakeCluster
from runbooks_tpu.k8s.httpfake import FakeApiServer


class Recorder:
    kind = "Model"

    def __init__(self):
        self.seen = []

    def reconcile(self, ctx, obj):
        self.seen.append(ko.name(obj))
        return Result()


def model(name):
    return {"apiVersion": API_VERSION, "kind": "Model",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"image": "img"}}


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return cond()


def test_manager_survives_apiserver_restart():
    cluster = FakeCluster()
    srv = FakeApiServer(cluster)
    srv.__enter__()
    port = int(srv.url.rsplit(":", 1)[1])
    client = K8sClient(KubeConfig(srv.url, ssl.create_default_context(), {}))

    rec = Recorder()
    mgr = Manager(Ctx(client=client, cloud=None, sci=None), [rec])
    stop = threading.Event()
    t = threading.Thread(target=mgr.run, args=(stop,),
                         kwargs={"resync_seconds": 0.3, "max_backoff": 0.5},
                         daemon=True)
    t.start()
    try:
        client.create(model("m1"))
        assert _wait(lambda: "m1" in rec.seen), "manager never reconciled m1"

        # Apiserver dies. The manager loop must keep running (log+backoff),
        # not die with an unhandled URLError out of a LIST/watch.
        srv.__exit__()
        time.sleep(1.0)
        assert t.is_alive(), "manager thread died while apiserver was down"

        # Apiserver comes back at the SAME address with the same objects
        # plus a new one created while the manager reconnects.
        srv2 = FakeApiServer(cluster, port=port)
        srv2.__enter__()
        try:
            client.create(model("m2"))
            assert _wait(lambda: "m2" in rec.seen), (
                "manager did not resume reconciling after apiserver restart")
            assert t.is_alive()
        finally:
            srv2.__exit__()
    finally:
        stop.set()
        t.join(timeout=5)


def test_standby_takes_over_immediately_when_manager_dies():
    client = FakeCluster()
    leader = LeaderElector(client, lease_duration_s=30.0, renew_s=0.05)
    leader.run()
    assert leader.is_leader.wait(timeout=3)
    standby = LeaderElector(client, lease_duration_s=30.0, renew_s=0.05)
    standby.run()
    time.sleep(0.3)
    assert not standby.is_leader.is_set()

    class Boom:
        def run(self, stop, resync_seconds=30.0):
            raise RuntimeError("manager exploded")

    # The leader's manager dies: run_with_leader_election must release the
    # lease (standby takes over well before the 30s lease duration) and
    # re-raise so the process crashes and restarts.
    with pytest.raises(RuntimeError, match="manager exploded"):
        run_with_leader_election(Boom(), leader, stop=threading.Event(),
                                 poll_s=0.05)
    assert standby.is_leader.wait(timeout=5), (
        "standby did not take over after the leader's manager died")
    lease = client.get(LEASE_API, "Lease", standby.namespace, standby.name)
    assert lease["spec"]["holderIdentity"] == standby.identity
    standby.stop()


def test_done_false_requeues_through_a_floor():
    """Result(done=False) must requeue with a floor, not a 0.0s due-time
    (an always-not-done reconciler would busy-spin the apiserver)."""

    class NotDone:
        kind = "Model"

        def reconcile(self, ctx, obj):
            return Result(done=False)

    cluster = FakeCluster()
    mgr = Manager(Ctx(client=cluster, cloud=None, sci=None), [NotDone()])
    obj = cluster.create(model("m1"))
    pending = {}
    t0 = time.monotonic()
    mgr._reconcile_one("Model", obj, pending)
    key = ("Model", "default", "m1")
    assert key in pending
    assert pending[key] - t0 >= 0.4, "immediate requeue has no floor"


class _BoomClient:
    """ApiClient stub whose watch() raises a deterministic non-connectivity
    error (a stand-in for a programming bug in the loop's own plumbing)."""

    def __init__(self, exc_factory):
        self._exc_factory = exc_factory

    def watch(self, *a, **k):
        raise self._exc_factory()

    def get(self, *a, **k):
        return None

    def list(self, *a, **k):
        return []


def test_watch_loop_crashes_after_repeated_identical_bug():
    """ADVICE r5: the blanket retry must not hide deterministic bugs —
    after N consecutive identical non-connectivity failures the loop
    re-raises so the process restarts visibly."""
    mgr = Manager(Ctx(client=_BoomClient(lambda: RuntimeError("bug!")),
                      cloud=None, sci=None), [Recorder()])
    with pytest.raises(RuntimeError, match="bug!"):
        mgr.run(threading.Event(), resync_seconds=3600.0,
                max_backoff=0.02, crash_after=3)


@pytest.mark.parametrize("exc_factory", [
    lambda: ConnectionRefusedError("refused"),
    # The wire client's typed non-404/409 HTTP error: a sustained apiserver
    # 503 (rolling restart) repeats identically and must retry forever, not
    # trip the crash-after-N-identical-bugs heuristic.
    lambda: __import__("runbooks_tpu.k8s.fake", fromlist=["ApiServerError"])
    .ApiServerError("GET /apis -> 503: apiserver is shutting down",
                    code=503),
], ids=["refused", "apiserver-503"])
def test_watch_loop_retries_connectivity_errors_forever(exc_factory):
    """Connectivity-shaped errors keep the retry-with-backoff behavior —
    the loop must NOT crash."""
    mgr = Manager(
        Ctx(client=_BoomClient(exc_factory),
            cloud=None, sci=None), [Recorder()])
    stop = threading.Event()
    t = threading.Thread(target=mgr.run, args=(stop,),
                         kwargs={"resync_seconds": 3600.0,
                                 "max_backoff": 0.02},
                         daemon=True)
    t.start()
    time.sleep(0.5)
    assert t.is_alive(), "manager crashed on a connectivity error"
    stop.set()
    t.join(timeout=5)
    assert not t.is_alive()
