"""Input-pipeline extras: prompt templating and the dataset-loader workload.

Reference analogs: the trainer images' prompt_template param
(reference: examples/falcon-7b-instruct/finetuned-model-custom-prompt.yaml)
and the dataset-loader-http image (reference: examples/datasets/
k8s-instructions.yaml)."""

import json

from runbooks_tpu.train import data as data_mod
from runbooks_tpu.train import dataset_loader


def test_read_documents_prompt_template(tmp_path):
    f = tmp_path / "d.jsonl"
    rows = [{"prompt": "make a pod", "completion": "kind: Pod"},
            {"prompt": "no completion field"},
            {"text": "plain"}]
    f.write_text("\n".join(json.dumps(r) for r in rows))

    tmpl = "## Instruction\n{prompt}\n## Response:\n{completion}"
    docs = list(data_mod.read_documents(str(f), prompt_template=tmpl))
    # Rows missing a referenced field are skipped, not crashed on.
    assert docs == ["## Instruction\nmake a pod\n## Response:\nkind: Pod"]

    # Without a template, text_key selects the field.
    assert list(data_mod.read_documents(str(f))) == ["plain"]
    assert list(data_mod.read_documents(str(f), text_key="prompt")) == \
        ["make a pod", "no completion field"]


def test_dataset_loader_writes_manifest(tmp_path, monkeypatch):
    src = tmp_path / "src.jsonl"
    src.write_text('{"text": "a"}\n{"text": "b"}\nnot json\n')
    out = tmp_path / "artifacts"

    monkeypatch.setattr(dataset_loader.contract, "load_params",
                        lambda: {"paths": [str(src)],
                                 "artifacts_dir": str(out)})
    assert dataset_loader.main() == 0

    copied = out / "src.jsonl"
    assert copied.read_text() == src.read_text()
    manifest = json.loads((out / "dataset.json").read_text())
    assert manifest["total_rows"] == 2
    assert manifest["files"][0]["file"] == "src.jsonl"
    assert manifest["total_bytes"] == src.stat().st_size


def test_dataset_loader_file_url(tmp_path, monkeypatch):
    src = tmp_path / "u.txt"
    src.write_text("hello\nworld\n")
    out = tmp_path / "artifacts"
    monkeypatch.setattr(dataset_loader.contract, "load_params",
                        lambda: {"urls": f"file://{src}",
                                 "artifacts_dir": str(out)})
    assert dataset_loader.main() == 0
    assert (out / "u.txt").read_text() == "hello\nworld\n"
    manifest = json.loads((out / "dataset.json").read_text())
    assert manifest["total_rows"] == 2  # .txt rows = line count


def test_load_tokenizer_default_is_byte():
    tok = data_mod.load_tokenizer(None)
    assert isinstance(tok, data_mod.ByteTokenizer)


def test_load_tokenizer_raises_on_broken_path(tmp_path):
    """A REQUESTED tokenizer that fails to load must raise, not silently
    degrade to the 258-symbol byte fallback (VERDICT r5 Weak-2: the silent
    swap changes the token space under the model)."""
    import pytest

    broken = tmp_path / "not-a-tokenizer"
    broken.mkdir()
    with pytest.raises(RuntimeError, match="could not be loaded"):
        data_mod.load_tokenizer(str(broken))
    # Explicit opt-in restores the old degrade behavior.
    tok = data_mod.load_tokenizer(str(broken), allow_byte_fallback=True)
    assert isinstance(tok, data_mod.ByteTokenizer)
