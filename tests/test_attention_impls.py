"""Model-level equivalence of attention implementations: xla vs flash vs
ring (sequence-parallel over the mesh)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import forward, init_params
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh


def cfg_with(impl):
    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=128, dtype="float32",
        attention_impl=impl,
    )


def test_flash_impl_matches_xla():
    cfg_x, cfg_f = cfg_with("xla"), cfg_with("flash")
    params = init_params(cfg_x, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_x.vocab_size)
    lx, _ = forward(cfg_x, params, toks)
    lf, _ = forward(cfg_f, params, toks)
    np.testing.assert_allclose(lx, lf, rtol=2e-4, atol=2e-4)


def test_flash_impl_with_packing():
    cfg_x, cfg_f = cfg_with("xla"), cfg_with("flash")
    params = init_params(cfg_x, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_x.vocab_size)
    segs = jnp.asarray(np.repeat([[1, 2, 3, 0]], 16, axis=1).reshape(1, 64)
                       .repeat(2, 0))
    pos = jnp.asarray(np.tile(np.arange(16), 4)[None].repeat(2, 0),
                      jnp.int32)
    lx, _ = forward(cfg_x, params, toks, positions=pos, segment_ids=segs)
    lf, _ = forward(cfg_f, params, toks, positions=pos, segment_ids=segs)
    # Compare only non-pad rows (pad logits differ: oracle zeroes them).
    valid = np.asarray(segs) != 0
    np.testing.assert_allclose(np.asarray(lx)[valid], np.asarray(lf)[valid],
                               rtol=2e-4, atol=2e-4)


def test_ring_impl_matches_xla_on_sequence_mesh():
    cfg_x, cfg_r = cfg_with("xla"), cfg_with("ring")
    params = init_params(cfg_x, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_x.vocab_size)
    mesh = make_mesh(MeshConfig(data=2, fsdp=1, sequence=4, tensor=1))

    lx, _ = forward(cfg_x, params, toks)

    @jax.jit
    def f(params, toks):
        logits, _ = forward(cfg_r, params, toks)
        return logits

    with jax.set_mesh(mesh):
        lr = f(params, toks)
    np.testing.assert_allclose(lx, np.asarray(lr), rtol=2e-4, atol=2e-4)


def test_ring_impl_gradients_match():
    cfg_x, cfg_r = cfg_with("xla"), cfg_with("ring")
    params = init_params(cfg_x, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_x.vocab_size)
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, sequence=4, tensor=2))

    def loss(cfg):
        def inner(params):
            logits, _ = forward(cfg, params, toks)
            return jnp.mean(jax.nn.log_softmax(logits) ** 2)
        return inner

    gx = jax.grad(loss(cfg_x))(params)
    with jax.set_mesh(mesh):
        gr = jax.jit(jax.grad(loss(cfg_r)))(params)
    for a, b in zip(jax.tree.leaves(gx), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)
