"""Model-level equivalence of attention implementations: xla vs flash vs
ring (sequence-parallel over the mesh)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import forward, init_params
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh


def cfg_with(impl):
    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=128, dtype="float32",
        attention_impl=impl,
    )


def test_flash_impl_matches_xla():
    cfg_x, cfg_f = cfg_with("xla"), cfg_with("flash")
    params = init_params(cfg_x, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_x.vocab_size)
    lx, _ = forward(cfg_x, params, toks)
    lf, _ = forward(cfg_f, params, toks)
    np.testing.assert_allclose(lx, lf, rtol=2e-4, atol=2e-4)


def test_flash_impl_with_packing():
    cfg_x, cfg_f = cfg_with("xla"), cfg_with("flash")
    params = init_params(cfg_x, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_x.vocab_size)
    segs = jnp.asarray(np.repeat([[1, 2, 3, 0]], 16, axis=1).reshape(1, 64)
                       .repeat(2, 0))
    pos = jnp.asarray(np.tile(np.arange(16), 4)[None].repeat(2, 0),
                      jnp.int32)
    lx, _ = forward(cfg_x, params, toks, positions=pos, segment_ids=segs)
    lf, _ = forward(cfg_f, params, toks, positions=pos, segment_ids=segs)
    # Compare only non-pad rows (pad logits differ: oracle zeroes them).
    valid = np.asarray(segs) != 0
    np.testing.assert_allclose(np.asarray(lx)[valid], np.asarray(lf)[valid],
                               rtol=2e-4, atol=2e-4)


def test_ring_impl_matches_xla_on_sequence_mesh():
    cfg_x, cfg_r = cfg_with("xla"), cfg_with("ring")
    params = init_params(cfg_x, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_x.vocab_size)
    mesh = make_mesh(MeshConfig(data=2, fsdp=1, sequence=4, tensor=1))

    lx, _ = forward(cfg_x, params, toks)

    @jax.jit
    def f(params, toks):
        logits, _ = forward(cfg_r, params, toks)
        return logits

    with jax.set_mesh(mesh):
        lr = f(params, toks)
    np.testing.assert_allclose(lx, np.asarray(lr), rtol=2e-4, atol=2e-4)


def test_ring_impl_gradients_match():
    cfg_x, cfg_r = cfg_with("xla"), cfg_with("ring")
    params = init_params(cfg_x, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_x.vocab_size)
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, sequence=4, tensor=2))

    def loss(cfg):
        def inner(params):
            logits, _ = forward(cfg, params, toks)
            return jnp.mean(jax.nn.log_softmax(logits) ** 2)
        return inner

    gx = jax.grad(loss(cfg_x))(params)
    with jax.set_mesh(mesh):
        gr = jax.jit(jax.grad(loss(cfg_r)))(params)
    for a, b in zip(jax.tree.leaves(gx), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_ring_flash_inner_matches_xla_inner():
    """SPxflash composition (r4 verdict #5): the flash-kernel-per-block
    ring (out/lse merge fwd, hand-written ring bwd with global lse) must
    match the autodiff XLA-inner ring and the single-device oracle."""
    cfg_x = cfg_with("xla")
    cfg_rf = dataclasses.replace(cfg_with("ring"), ring_flash_inner=True,
                                 flash_block_q=16, flash_block_k=16)
    params = init_params(cfg_x, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_x.vocab_size)
    mesh = make_mesh(MeshConfig(data=2, fsdp=1, sequence=4, tensor=1))

    lx, _ = forward(cfg_x, params, toks)
    with jax.set_mesh(mesh):
        lr = jax.jit(lambda p, t: forward(cfg_rf, p, t)[0])(params, toks)
    np.testing.assert_allclose(lx, np.asarray(lr), rtol=2e-4, atol=2e-4)


def test_ring_flash_inner_gradients_match():
    cfg_x = cfg_with("xla")
    cfg_rf = dataclasses.replace(cfg_with("ring"), ring_flash_inner=True,
                                 flash_block_q=16, flash_block_k=16)
    params = init_params(cfg_x, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_x.vocab_size)
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, sequence=4, tensor=2))

    def loss(cfg):
        def inner(params):
            logits, _ = forward(cfg, params, toks)
            return jnp.mean(jax.nn.log_softmax(logits) ** 2)
        return inner

    gx = jax.grad(loss(cfg_x))(params)
    with jax.set_mesh(mesh):
        gr = jax.jit(jax.grad(loss(cfg_rf)))(params)
    for a, b in zip(jax.tree.leaves(gx), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_ring_flash_inner_with_packing():
    """Packed segments cross shard boundaries; the flash inner must mask
    identically to the XLA inner under rotation."""
    cfg_r = cfg_with("ring")
    cfg_rf = dataclasses.replace(cfg_r, ring_flash_inner=True,
                                 flash_block_q=16, flash_block_k=16)
    params = init_params(cfg_r, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_r.vocab_size)
    segs = jnp.asarray(np.repeat([[1, 2, 3, 0]], 16, axis=1).reshape(1, 64)
                       .repeat(2, 0))
    pos = jnp.asarray(np.tile(np.arange(16), 4)[None].repeat(2, 0),
                      jnp.int32)
    mesh = make_mesh(MeshConfig(data=2, fsdp=1, sequence=4, tensor=1))
    with jax.set_mesh(mesh):
        l_xla = jax.jit(lambda p, t: forward(
            cfg_r, p, t, positions=pos, segment_ids=segs)[0])(params, toks)
        l_fl = jax.jit(lambda p, t: forward(
            cfg_rf, p, t, positions=pos, segment_ids=segs)[0])(params, toks)
    valid = np.asarray(segs) != 0
    np.testing.assert_allclose(np.asarray(l_xla)[valid],
                               np.asarray(l_fl)[valid],
                               rtol=2e-4, atol=2e-4)


def test_ring_flash_save_attn_out_skips_fwd_ring_recompute():
    """The ring's (out, lse) are tagged OUTSIDE the custom_vjp and the
    shard_map (names nested in either are invisible to checkpoint
    policies), so save_attn_out must drop the forward-ring re-run from
    the backward pass. Pallas call SITES in the grad jaxpr:
    nothing_saveable = 8 (fwd local+scan, recomputed fwd local+scan,
    bwd local dq+dkv, bwd scan dq+dkv); save_attn_out = 6."""
    from tests.test_flash_attention import _count_pallas_calls

    base = dataclasses.replace(cfg_with("ring"), ring_flash_inner=True,
                               flash_block_q=16, flash_block_k=16)
    tokens = jnp.zeros((2, 64), jnp.int32)
    mesh = make_mesh(MeshConfig(data=1, fsdp=2, sequence=4, tensor=1))
    counts = {}
    with jax.set_mesh(mesh):
        for policy in ("nothing_saveable", "save_attn_out"):
            cfg = dataclasses.replace(base, remat_policy=policy)
            params = init_params(cfg, jax.random.key(0))

            def loss(p, cfg=cfg):
                logits, _ = forward(cfg, p, tokens, remat=True)
                return jnp.mean(logits)

            jaxpr = jax.make_jaxpr(jax.grad(loss))(params)
            counts[policy] = _count_pallas_calls(jaxpr.jaxpr)
    assert counts["nothing_saveable"] == 8, counts
    assert counts["save_attn_out"] == 6, counts
