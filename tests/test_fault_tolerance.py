"""Deterministic fault-injection harness (docs/fault-tolerance.md).

The property everything else hangs off: kill training at an arbitrary step,
restart, and the resumed per-step loss history must match an uninterrupted
run batch-for-batch (the checkpoint carries the data cursor, restore picks
the newest intact checkpoint, and the data pipeline fast-forwards to the
exact batch the next step would have consumed). Faults are injected through
the trainer's RBT_FAULT_INJECT hook so every run is reproducible.

All tests here are tier-1 (fast, CPU, not slow).
"""

import dataclasses
import json
import os
import signal

import numpy as np
import pytest

from runbooks_tpu.parallel.mesh import MeshConfig
from runbooks_tpu.train.checkpoint import CheckpointManager
from runbooks_tpu.train.optimizer import OptimizerConfig
from runbooks_tpu.train.trainer import (
    SimulatedFault,
    TrainJobConfig,
    exit_code_for,
    run_training,
)
from runbooks_tpu.utils.contract import EXIT_PREEMPTED

MESH = MeshConfig(data=2, fsdp=2, sequence=1, tensor=2)


def job(artifacts, steps=8, checkpoint_every=3, **kw):
    return TrainJobConfig(
        model="debug", model_overrides={"dtype": "float32"},
        mesh=MESH,
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=0,
                                  total_steps=100, schedule="constant"),
        batch_size=4, seq_len=32, steps=steps,
        checkpoint_every=checkpoint_every, log_every=1,
        artifacts_dir=str(artifacts), **kw,
    )


def losses(summary):
    return {e["step"]: e["loss"] for e in summary["history"]}


def assert_matching_tail(base, resumed):
    """Every step the resumed run logged must match the uninterrupted run's
    loss at the same step (fp tolerance on CPU)."""
    want, got = losses(base), losses(resumed)
    assert got, "resumed run logged no steps"
    for step in got:
        assert abs(got[step] - want[step]) < 2e-4, (
            step, got[step], want[step])


# ---------------------------------------------------------------------------
# Step-exact resume
# ---------------------------------------------------------------------------

def test_step_exact_resume_after_kill(tmp_path, monkeypatch):
    """Kill at step k, restart: steps k'..N (k' = last checkpoint + 1) land
    on exactly the batches — and thus the losses — of an uninterrupted
    run, instead of replaying the data stream from batch 0."""
    base = run_training(job(tmp_path / "base"))

    monkeypatch.setenv("RBT_FAULT_INJECT", "kill:5")
    with pytest.raises(SimulatedFault):
        run_training(job(tmp_path / "faulted"))
    monkeypatch.delenv("RBT_FAULT_INJECT")

    resumed = run_training(job(tmp_path / "faulted"))
    # Last periodic checkpoint before the kill was step 3.
    assert sorted(losses(resumed)) == [4, 5, 6, 7, 8]
    assert_matching_tail(base, resumed)
    assert resumed["batches_consumed"] == base["batches_consumed"] == 8


def test_step_exact_resume_with_accum_prefetch_and_jsonl(tmp_path,
                                                         monkeypatch):
    """The same property with gradient accumulation, the async prefetcher,
    and a real jsonl dataset (the cursor must replay tokenize/pack state,
    not just a synthetic RNG stream). Batches the prefetcher had in flight
    beyond the cursor at kill time are regenerated, not double-consumed."""
    data = tmp_path / "data"
    os.makedirs(data)
    rng = np.random.default_rng(0)
    with open(data / "docs.jsonl", "w") as f:
        for i in range(40):
            words = " ".join(f"w{i}x{j}" for j in range(int(rng.integers(
                4, 40))))
            f.write(json.dumps({"text": words}) + "\n")
    kw = dict(data_path=str(data), accumulate_steps=2, prefetch_depth=2)

    base = run_training(job(tmp_path / "base", **kw))
    monkeypatch.setenv("RBT_FAULT_INJECT", "kill:4")
    with pytest.raises(SimulatedFault):
        run_training(job(tmp_path / "faulted", **kw))
    monkeypatch.delenv("RBT_FAULT_INJECT")
    resumed = run_training(job(tmp_path / "faulted", **kw))
    assert sorted(losses(resumed)) == [4, 5, 6, 7, 8]
    assert_matching_tail(base, resumed)


# ---------------------------------------------------------------------------
# SIGTERM -> emergency checkpoint + documented exit code
# ---------------------------------------------------------------------------

def test_sigterm_emergency_checkpoint_and_exit_code(tmp_path, monkeypatch):
    # checkpoint_every past the horizon: the only checkpoint is the
    # emergency one the handler forces.
    monkeypatch.setenv("RBT_FAULT_INJECT", "sigterm:5")
    summary = run_training(job(tmp_path, steps=10, checkpoint_every=100))
    assert summary["exit_reason"] == "sigterm"
    assert exit_code_for(summary) == EXIT_PREEMPTED
    # Handlers restored after the run (pytest's own handlers survive).
    assert signal.getsignal(signal.SIGTERM) in (signal.SIG_DFL,
                                                signal.default_int_handler)

    ckpt = CheckpointManager(str(tmp_path))
    try:
        assert ckpt.latest_intact_step() == 5
        assert ckpt.read_cursor(5) == {"batches_consumed": 5}
    finally:
        ckpt.close()

    monkeypatch.delenv("RBT_FAULT_INJECT")
    # And the emergency checkpoint resumes step-exactly.
    base = run_training(job(tmp_path / "base", steps=10,
                            checkpoint_every=100))
    resumed = run_training(job(tmp_path, steps=10, checkpoint_every=100))
    assert sorted(losses(resumed)) == [6, 7, 8, 9, 10]
    assert_matching_tail(base, resumed)
    assert exit_code_for(resumed) == 0


def test_maintenance_event_poller_stops_training(tmp_path, monkeypatch):
    """A pending GCE maintenance event (served by a local metadata fake)
    is treated like SIGTERM: emergency checkpoint + preempted exit."""
    import http.server
    import threading

    class Fake(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = (b"TERMINATE_ON_HOST_MAINTENANCE"
                    if "maintenance-event" in self.path else b"")
            self.send_response(200)
            self.send_header("Metadata-Flavor", "Google")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Fake)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        monkeypatch.setenv("GCE_METADATA_HOST",
                           f"127.0.0.1:{srv.server_address[1]}")
        summary = run_training(job(tmp_path, steps=500,
                                   checkpoint_every=1000,
                                   maintenance_poll_s=0.2))
        assert summary["exit_reason"] == "maintenance"
        assert exit_code_for(summary) == EXIT_PREEMPTED
        ckpt = CheckpointManager(str(tmp_path))
        try:
            assert ckpt.latest_intact_step() is not None
        finally:
            ckpt.close()
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Non-finite guard
# ---------------------------------------------------------------------------

def test_nonfinite_step_leaves_params_bitwise_unchanged():
    """A NaN-poisoned batch must skip the update wholesale: params AND
    optimizer state bitwise identical, step counter advanced, and training
    continues to learn on the next good batch."""
    import jax
    import jax.numpy as jnp

    from runbooks_tpu.models.config import get_config
    from runbooks_tpu.parallel.mesh import make_mesh
    from runbooks_tpu.train.optimizer import make_optimizer
    from runbooks_tpu.train.step import create_train_state, make_train_step

    cfg = get_config("debug", dtype="float32")
    mesh = make_mesh(MESH)
    opt = make_optimizer(OptimizerConfig(learning_rate=1e-3, warmup_steps=0,
                                         total_steps=100,
                                         schedule="constant"))
    state, shardings = create_train_state(cfg, opt, mesh, jax.random.key(0))
    step = make_train_step(cfg, opt, mesh, shardings)
    toks = np.random.default_rng(0).integers(
        1, cfg.vocab_size, (4, 33), dtype=np.int32)
    good = {"tokens": toks[:, :-1], "targets": toks[:, 1:],
            "loss_mask": np.ones((4, 32), np.float32)}
    bad = dict(good)
    bad["loss_mask"] = good["loss_mask"] * np.float32("nan")

    with jax.set_mesh(mesh):
        state, m = step(state, good)
        assert float(m["nonfinite"]) == 0
        before = jax.tree.map(np.asarray, state.params)
        step_before = int(state.step)

        state, m = step(state, bad)
        assert float(m["nonfinite"]) == 1
        assert not np.isfinite(float(m["loss"]))
        after = jax.tree.map(np.asarray, state.params)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b), before, after)
        assert int(state.step) == step_before + 1  # counter still advances

        state, m = step(state, good)
        assert float(m["nonfinite"]) == 0
        changed = jax.tree.leaves(jax.tree.map(
            lambda a, b: not np.array_equal(a, np.asarray(b)),
            before, state.params))
        assert any(changed)  # good batch trains again


def test_lora_nonfinite_guard():
    import jax

    from runbooks_tpu.models.config import get_config
    from runbooks_tpu.models.transformer import init_params
    from runbooks_tpu.parallel.mesh import make_mesh
    from runbooks_tpu.train.lora import (
        LoraConfig,
        create_lora_train_state,
        make_lora_train_step,
    )
    from runbooks_tpu.train.optimizer import make_optimizer
    from runbooks_tpu.train.step import infer_state_shardings  # noqa: F401
    from runbooks_tpu.models.transformer import param_logical_axes
    from runbooks_tpu.parallel.sharding import tree_shardings

    cfg = get_config("debug", dtype="float32")
    mesh = make_mesh(MESH)
    opt = make_optimizer(OptimizerConfig(learning_rate=1e-3, warmup_steps=0,
                                         total_steps=100,
                                         schedule="constant"))
    rng = jax.random.key(0)
    base = init_params(cfg, rng)
    base_shardings = tree_shardings(jax.eval_shape(lambda: base),
                                    param_logical_axes(cfg), mesh)
    base = jax.device_put(base, base_shardings)
    lcfg = LoraConfig(rank=2)
    state, shardings = create_lora_train_state(cfg, lcfg, base, opt, mesh,
                                               rng)
    step = make_lora_train_step(cfg, lcfg, opt, mesh, shardings,
                                base_shardings)
    toks = np.random.default_rng(0).integers(
        1, cfg.vocab_size, (4, 33), dtype=np.int32)
    bad = {"tokens": toks[:, :-1], "targets": toks[:, 1:],
           "loss_mask": np.full((4, 32), np.float32("nan"))}
    with jax.set_mesh(mesh):
        before = jax.tree.map(np.asarray, state.params)
        state, m = step(state, base, bad)
        assert float(m["nonfinite"]) == 1
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                     before, jax.tree.map(np.asarray, state.params))


def test_single_nonfinite_step_training_continues(tmp_path, monkeypatch):
    monkeypatch.setenv("RBT_FAULT_INJECT", "nonfinite:2")
    summary = run_training(job(tmp_path, steps=6))
    assert summary["nonfinite_steps"] == 1
    assert summary["exit_reason"] is None
    assert np.isfinite(summary["final_loss"])


def test_consecutive_nonfinite_steps_abort(tmp_path, monkeypatch):
    monkeypatch.setenv("RBT_FAULT_INJECT", "nonfinite:2+")
    with pytest.raises(RuntimeError, match="consecutive non-finite"):
        run_training(job(tmp_path, steps=10, max_bad_steps=3))


# ---------------------------------------------------------------------------
# Checkpoint integrity: corrupt-latest fallback, cross-mesh cursor
# ---------------------------------------------------------------------------

def _truncate_step_dir(step_dir):
    for root, _, files in os.walk(step_dir):
        for name in files:
            with open(os.path.join(root, name), "w"):
                pass  # truncate to 0 bytes


def test_corrupt_latest_checkpoint_falls_back(tmp_path, capsys):
    """Preemption mid-async-save: the newest step dir is garbage; restore
    must pick the previous intact one and say so."""
    run_training(job(tmp_path, steps=6))  # checkpoints at 3 and 6
    _truncate_step_dir(tmp_path / "checkpoints" / "6")

    ckpt = CheckpointManager(str(tmp_path))
    try:
        state, cursor, step = ckpt.restore_with_cursor(None)
    finally:
        ckpt.close()
    assert step == 3
    assert cursor == {"batches_consumed": 3}
    out = capsys.readouterr().out
    assert "falling back" in out

    # And the trainer resumes from it end-to-end (steps 4..8 rerun).
    summary = run_training(job(tmp_path))
    assert sorted(losses(summary)) == [4, 5, 6, 7, 8]


def test_partial_save_without_marker_is_skipped(tmp_path, capsys):
    """A step directory that never got its integrity marker (the save was
    cut mid-flight) is not even attempted when an older intact one
    exists."""
    run_training(job(tmp_path, steps=6))
    marker = tmp_path / "checkpoints" / "6" / CheckpointManager.MARKER
    os.remove(marker)

    ckpt = CheckpointManager(str(tmp_path))
    try:
        assert ckpt.intact_steps() == [3]
        state, cursor, step = ckpt.restore_with_cursor(None)
    finally:
        ckpt.close()
    assert step == 3
    assert "ignoring partial step dir" in capsys.readouterr().out


def test_cursor_survives_restore_onto_different_mesh(tmp_path):
    """Restore onto a different mesh layout reshards the arrays but must
    leave the data-cursor payload untouched."""
    import jax

    from runbooks_tpu.models.config import get_config
    from runbooks_tpu.parallel.mesh import make_mesh
    from runbooks_tpu.train.optimizer import make_optimizer
    from runbooks_tpu.train.step import create_train_state

    cfg = get_config("debug", dtype="float32")
    opt = make_optimizer(OptimizerConfig(learning_rate=1e-3, warmup_steps=0,
                                         total_steps=100,
                                         schedule="constant"))
    mesh_a = make_mesh(MESH)
    state_a, _ = create_train_state(cfg, opt, mesh_a, jax.random.key(0))
    ckpt = CheckpointManager(str(tmp_path))
    try:
        ckpt.save(7, state_a, cursor={"batches_consumed": 7})
        ckpt.wait()
    finally:
        ckpt.close()

    mesh_b = make_mesh(MeshConfig(data=1, fsdp=8, sequence=1, tensor=1))
    state_b, _ = create_train_state(cfg, opt, mesh_b, jax.random.key(1))
    ckpt = CheckpointManager(str(tmp_path))
    try:
        restored, cursor, step = ckpt.restore_with_cursor(state_b)
    finally:
        ckpt.close()
    assert step == 7 and cursor == {"batches_consumed": 7}
    np.testing.assert_allclose(
        np.asarray(restored.params["embed"]),
        np.asarray(state_a.params["embed"]), rtol=0, atol=0)


# ---------------------------------------------------------------------------
# Serving: backpressure, deadlines, graceful drain
# ---------------------------------------------------------------------------

def _tiny_cfg():
    from runbooks_tpu.models.config import get_config

    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype="float32")


def test_engine_bounded_queue_raises_typed_overload():
    import jax

    from runbooks_tpu.models.transformer import init_params
    from runbooks_tpu.serve.engine import (
        EngineOverloaded,
        InferenceEngine,
        Request,
    )

    cfg = _tiny_cfg()
    engine = InferenceEngine(cfg, init_params(cfg, jax.random.key(0)),
                             max_slots=1, max_queue=2)
    engine.submit(Request(prompt_tokens=[1, 2], max_tokens=2))
    engine.submit(Request(prompt_tokens=[1, 2], max_tokens=2))
    with pytest.raises(EngineOverloaded, match="queue full"):
        engine.submit(Request(prompt_tokens=[1, 2], max_tokens=2))
    # The bound rejects; it never truncates what was admitted.
    assert len(engine.queue) == 2


def test_engine_deadline_expiry_between_chunks():
    import time

    import jax

    from runbooks_tpu.models.transformer import init_params
    from runbooks_tpu.serve.engine import InferenceEngine, Request

    cfg = _tiny_cfg()
    engine = InferenceEngine(cfg, init_params(cfg, jax.random.key(0)),
                             max_slots=2)
    # Queued expiry: never admitted, finishes empty-handed.
    r_queued = Request(prompt_tokens=[1, 2], max_tokens=5, deadline_s=1e-4)
    engine.submit(r_queued)
    time.sleep(0.01)
    engine.step()
    assert r_queued.finished and r_queued.finish_reason == "deadline"
    assert r_queued.output_tokens == []

    # Mid-generation expiry: keeps the tokens it had.
    r_mid = Request(prompt_tokens=[1, 2], max_tokens=10_000,
                    deadline_s=0.05)
    engine.submit(r_mid)
    while engine.has_work():
        engine.step()
        time.sleep(0.02)
    assert r_mid.finish_reason == "deadline"
    assert 0 < len(r_mid.output_tokens) < 10_000
    assert engine.deadline_expired == 2


def test_worker_drain_finishes_inflight_then_rejects():
    """The SIGTERM drain path, on the engine smoke harness: stop admitting,
    finish every in-flight request, then reject with the typed draining
    error."""
    import jax

    from runbooks_tpu.models.transformer import init_params
    from runbooks_tpu.serve.api import EngineWorker
    from runbooks_tpu.serve.engine import (
        EngineDraining,
        InferenceEngine,
        Request,
    )

    cfg = _tiny_cfg()
    engine = InferenceEngine(cfg, init_params(cfg, jax.random.key(0)),
                             max_slots=2)
    worker = EngineWorker(engine)
    futs = [worker.submit(Request(prompt_tokens=[1, 2, 3], max_tokens=5))
            for _ in range(3)]
    assert worker.drain(timeout_s=120)
    assert all(f.done() for f in futs)
    assert all(len(f.result().output_tokens) == 5 for f in futs)
    with pytest.raises(EngineDraining):
        worker.submit(Request(prompt_tokens=[1], max_tokens=1))
    worker.stop()


def test_http_429_retry_after_and_503_draining():
    import asyncio

    import jax

    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.models.transformer import init_params
    from runbooks_tpu.serve.api import create_server

    cfg = _tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    # max_queue=0: every admission is an overload — deterministic 429.
    app = create_server(cfg, params, max_slots=1, max_queue=0)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "hello", "max_tokens": 2})
            assert r.status == 429
            assert r.headers["Retry-After"] == "1"
            body = await r.json()
            assert body["error"]["type"] == "overloaded"

            r = await client.get("/metrics")
            text = await r.text()
            assert "serve_requests_rejected_total 1" in text
            assert "serve_queue_limit 0" in text

            # Draining: 503 (terminal for this replica, not a retry-here).
            app["worker"]._draining = True
            r = await client.post("/v1/completions", json={
                "prompt": "hello", "max_tokens": 2})
            assert r.status == 503
            assert (await r.json())["error"]["type"] == "draining"

    asyncio.run(drive())


def test_http_request_timeout_deadline():
    import asyncio

    import jax

    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.models.transformer import init_params
    from runbooks_tpu.serve.api import create_server

    cfg = _tiny_cfg()
    app = create_server(cfg, init_params(cfg, jax.random.key(0)),
                        max_slots=1)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "hi", "max_tokens": 10_000, "timeout": 0.15})
            assert r.status == 200
            body = await r.json()
            assert body["choices"][0]["finish_reason"] in ("deadline",
                                                           "length")
            r = await client.post("/v1/completions", json={
                "prompt": "hi", "max_tokens": 2, "timeout": -1})
            assert r.status == 400

    asyncio.run(drive())
