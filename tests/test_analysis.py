"""Static analyzer (`rbt check`) tests: every lint rule and every
program-contract check proven to FIRE on a seeded violation and to stay
QUIET on clean code, plus the tier-1 gate that the repo itself audits
clean (docs/static-analysis.md).
"""

import json
import os
import textwrap

import pytest

from runbooks_tpu.analysis.findings import (
    Finding,
    Suppression,
    apply_baseline,
    load_baseline,
)
from runbooks_tpu.analysis.lint import lint_source


def _lint(src: str, rel: str = "runbooks_tpu/some/module.py"):
    return lint_source(textwrap.dedent(src), rel)


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

LOCKED_CLASS = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._jobs = []  # guarded-by: _lock

        def add(self, j):
            with self._lock:
                self._jobs.append(j)
"""


def test_lock_discipline_fires_on_unguarded_access():
    findings = _lint(LOCKED_CLASS + """
        def steal(self):
            return list(self._jobs)
    """)
    assert _rules(findings) == ["lock-discipline"]
    assert "_jobs" in findings[0].message
    assert "with self._lock" in findings[0].message


def test_lock_discipline_quiet_when_guarded():
    assert _lint(LOCKED_CLASS) == []


def test_lock_discipline_init_exempt():
    # __init__ assigns guarded attrs before any other thread exists.
    assert _lint(LOCKED_CLASS) == []


def test_lock_discipline_nested_with_and_release():
    findings = _lint(LOCKED_CLASS + """
        def late(self):
            with self._lock:
                ok = self._jobs
            return self._jobs  # lock released above
    """)
    assert _rules(findings) == ["lock-discipline"]


def test_lock_discipline_lock_held_helper_annotation():
    findings = _lint(LOCKED_CLASS + """
        def _drain_locked(self):  # guarded-by: _lock
            self._jobs.clear()
    """)
    assert findings == []


def test_lock_discipline_inline_ignore_with_reason():
    findings = _lint(LOCKED_CLASS + """
        def peek(self):
            # rbt-check: ignore[lock-discipline] len() is GIL-atomic here
            return len(self._jobs)
    """)
    assert findings == []


def test_unannotated_attrs_not_audited():
    findings = _lint("""
        import threading

        class Free:
            def __init__(self):
                self._lock = threading.Lock()
                self._jobs = []

            def steal(self):
                return list(self._jobs)
    """)
    assert findings == []


# ---------------------------------------------------------------------------
# async-blocking
# ---------------------------------------------------------------------------

def test_async_blocking_fires_on_time_sleep():
    findings = _lint("""
        import time

        async def handler(request):
            time.sleep(1)
    """)
    assert _rules(findings) == ["async-blocking"]
    assert "time.sleep" in findings[0].message


@pytest.mark.parametrize("call", [
    "fut.result()",
    "worker._thread.join()",
    "subprocess.run(cmd)",
    "requests.get(url)",
    "urllib.request.urlopen(url)",
])
def test_async_blocking_fires_on(call):
    findings = _lint(f"""
        async def handler(fut, worker, cmd, url):
            {call}
    """)
    assert _rules(findings) == ["async-blocking"]


def test_async_blocking_quiet_on_clean_async():
    findings = _lint("""
        import asyncio

        async def handler(request, fut):
            await asyncio.sleep(1)
            await asyncio.wrap_future(fut)
            return "-".join(["a", "b"])
    """)
    assert findings == []


def test_async_blocking_nested_sync_def_exempt():
    # A sync def nested in a coroutine runs in an executor/thread.
    findings = _lint("""
        import time

        async def handler(loop):
            def blocking():
                time.sleep(1)
            await loop.run_in_executor(None, blocking)
    """)
    assert findings == []


def test_async_blocking_nested_async_def_reported_once():
    # The nested coroutine gets its own visitor pass; the outer pass
    # must not descend into it too (double-reporting would let one
    # baseline suppression silently cover both copies).
    findings = _lint("""
        import time

        async def outer():
            async def inner():
                time.sleep(1)
            await inner()
    """)
    assert _rules(findings) == ["async-blocking"]


def test_sync_def_not_audited_for_blocking():
    findings = _lint("""
        import time

        def worker():
            time.sleep(1)
    """)
    assert findings == []


# ---------------------------------------------------------------------------
# device-sync
# ---------------------------------------------------------------------------

HOT_SYNC = """
    import numpy as np

    def step(self, x):
        return np.asarray(x)
"""


def test_device_sync_fires_on_hot_paths():
    for rel in ("runbooks_tpu/serve/engine.py", "runbooks_tpu/train/step.py"):
        findings = _lint(HOT_SYNC, rel)
        assert _rules(findings) == ["device-sync"], rel


@pytest.mark.parametrize("call", [
    "x.item()", "x.block_until_ready()", "jax.block_until_ready(x)",
    "jax.device_get(x)",
])
def test_device_sync_variants(call):
    findings = _lint(f"""
        import jax

        def step(x):
            return {call}
    """, "runbooks_tpu/serve/engine.py")
    assert _rules(findings) == ["device-sync"]


def test_device_sync_quiet_off_hot_path():
    assert _lint(HOT_SYNC, "runbooks_tpu/train/trainer.py") == []


def test_device_sync_inline_ignore():
    findings = _lint("""
        import numpy as np

        def step(self, x):
            # rbt-check: ignore[device-sync] dispatch boundary
            return np.asarray(x)
    """, "runbooks_tpu/serve/engine.py")
    assert findings == []


# ---------------------------------------------------------------------------
# rng-layout
# ---------------------------------------------------------------------------

RNG_JIT = """
    import jax

    def make(shardings):
        def init_fn(rng):
            return jax.random.normal(rng, (4, 4))
        return jax.jit(init_fn, out_shardings=shardings)
"""


def test_rng_layout_fires_outside_scope():
    findings = _lint(RNG_JIT)
    assert _rules(findings) == ["rng-layout"]
    assert "layout_invariant_init" in findings[0].message


def test_rng_layout_quiet_inside_scope():
    findings = _lint("""
        import jax

        def make(shardings):
            def init_fn(rng):
                return jax.random.normal(rng, (4, 4))
            with layout_invariant_init():
                return jax.jit(init_fn, out_shardings=shardings)
    """)
    assert findings == []


def test_rng_layout_quiet_without_out_shardings():
    findings = _lint("""
        import jax

        def make():
            def init_fn(rng):
                return jax.random.normal(rng, (4, 4))
            return jax.jit(init_fn)
    """)
    assert findings == []


def test_rng_layout_quiet_for_non_rng_body():
    findings = _lint("""
        import jax

        def make(shardings):
            def step_fn(x):
                return x + 1
            return jax.jit(step_fn, out_shardings=shardings)
    """)
    assert findings == []


def test_rng_layout_covers_init_callees():
    findings = _lint("""
        import jax

        def make(cfg, shardings):
            def init_fn(rng):
                return init_params(cfg, rng)
            return jax.jit(init_fn, out_shardings=shardings)
    """)
    assert _rules(findings) == ["rng-layout"]


# ---------------------------------------------------------------------------
# bare-except / swallowed-error / ignore-reason
# ---------------------------------------------------------------------------

def test_bare_except_fires():
    findings = _lint("""
        def f():
            try:
                g()
            except:
                return None
    """)
    assert _rules(findings) == ["bare-except"]


def test_swallowed_error_fires_on_silent_broad_except():
    findings = _lint("""
        def f():
            try:
                g()
            except Exception:
                pass
    """)
    assert _rules(findings) == ["swallowed-error"]


def test_swallowed_error_quiet_with_justifying_comment():
    for handler in ("    except Exception:  # probe only\n        pass\n",
                    "    except Exception:\n        pass  # probe only\n"):
        src = "def f():\n    try:\n        g()\n" + handler
        findings = lint_source(src, "runbooks_tpu/some/module.py")
        assert findings == [], handler


def test_swallowed_error_quiet_when_handled():
    findings = _lint("""
        def f():
            try:
                g()
            except Exception as exc:
                log(exc)
    """)
    assert findings == []


def test_narrow_except_not_audited():
    findings = _lint("""
        def f():
            try:
                g()
            except OSError:
                pass
    """)
    assert findings == []


def test_ignore_without_reason_is_flagged():
    findings = _lint("""
        import time

        async def handler():
            time.sleep(1)  # rbt-check: ignore[async-blocking]
    """)
    assert _rules(findings) == ["ignore-reason"]


def test_syntax_error_reported_not_raised():
    findings = lint_source("def f(:\n", "runbooks_tpu/x.py")
    assert _rules(findings) == ["syntax"]


# ---------------------------------------------------------------------------
# findings model: baseline suppression
# ---------------------------------------------------------------------------

def _finding(rule="lock-discipline", path="runbooks_tpu/a.py",
             message="self._x accessed outside lock"):
    return Finding(rule=rule, path=path, line=3, message=message)


def test_apply_baseline_suppresses_and_reports_stale():
    hit = Suppression(rule="lock-discipline", path="runbooks_tpu/a.py",
                      reason="intentional")
    stale = Suppression(rule="device-sync", path="runbooks_tpu/b.py",
                        reason="fixed long ago")
    active, suppressed, stale_out = apply_baseline(
        [_finding(), _finding(rule="bare-except")], [hit, stale])
    assert _rules(active) == ["bare-except"]
    assert _rules(suppressed) == ["lock-discipline"]
    assert stale_out == [stale]


def test_baseline_contains_scopes_suppression():
    s = Suppression(rule="lock-discipline", path="runbooks_tpu/a.py",
                    reason="r", contains="_y")
    active, suppressed, _ = apply_baseline([_finding()], [s])
    assert len(active) == 1 and not suppressed


def test_load_baseline_rejects_reasonless_entries(tmp_path):
    p = tmp_path / "check_baseline.json"
    p.write_text(json.dumps(
        {"suppressions": [{"rule": "x", "path": "y"}]}))
    with pytest.raises(ValueError, match="reason"):
        load_baseline(str(p))


def test_load_baseline_missing_file_is_empty(tmp_path):
    assert load_baseline(str(tmp_path / "nope.json")) == []


# ---------------------------------------------------------------------------
# program contracts (synthetic seeded violations)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def jnp():
    return pytest.importorskip("jax.numpy")


def _audit(fn, *args):
    import jax

    from runbooks_tpu.analysis.program import AuditSettings, audit_jaxpr

    closed = jax.make_jaxpr(fn)(*args)
    settings = AuditSettings(f32_upcast_bytes=1 << 12,
                             const_bytes=1 << 12)
    return audit_jaxpr(closed, "test/prog", settings)


def test_program_callback_fires(jnp):
    import jax

    def f(x):
        jax.debug.print("x={x}", x=x)
        return x + 1

    findings, flags = _audit(f, jnp.zeros((4,), jnp.float32))
    assert "program-callback" in _rules(findings)
    assert flags["callbacks"] >= 1


def test_program_dtype_fires_on_large_bf16_upcast(jnp):
    def f(x):
        return x.astype(jnp.float32) * 2.0  # 64*64*4 B > 4 KiB threshold

    findings, flags = _audit(f, jnp.zeros((64, 64), jnp.bfloat16))
    assert "program-dtype" in _rules(findings)
    assert flags["f32_upcasts"] == 1


def test_program_dtype_quiet_on_small_accumulator(jnp):
    def f(x):
        # A scalar-ish LSE/norm accumulator: upcast under the threshold.
        return x.astype(jnp.float32).sum()

    findings, flags = _audit(f, jnp.zeros((8,), jnp.bfloat16))
    assert findings == []
    assert flags["f32_upcasts"] == 0


def test_program_dtype_quiet_on_f32_inputs(jnp):
    def f(x):
        return x.astype(jnp.float32) * 2.0

    findings, _ = _audit(f, jnp.zeros((64, 64), jnp.float32))
    assert findings == []


def test_program_const_fires_on_big_embedded_constant(jnp):
    import numpy as np

    table = jnp.asarray(np.ones((64, 64), np.float32))  # 16 KiB closure

    def f(x):
        return x + table

    findings, flags = _audit(f, jnp.zeros((64, 64), jnp.float32))
    assert "program-const" in _rules(findings)
    assert flags["const_bytes_max"] >= 64 * 64 * 4


def test_program_clean_fn_is_quiet(jnp):
    def f(x, w):
        return x @ w

    findings, flags = _audit(f, jnp.zeros((8, 8), jnp.bfloat16),
                             jnp.zeros((8, 8), jnp.bfloat16))
    assert findings == []
    assert flags == {"callbacks": 0, "f32_upcasts": 0,
                     "const_bytes_max": 0}


def test_program_callback_found_inside_scan(jnp):
    import jax

    def f(x):
        def body(c, _):
            jax.debug.print("c={c}", c=c)
            return c + 1, c
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    findings, _ = _audit(f, jnp.zeros((), jnp.float32))
    assert "program-callback" in _rules(findings)


# ---------------------------------------------------------------------------
# census drift
# ---------------------------------------------------------------------------

def _census(sigs=3, flags=None):
    return {"settings": {"config": "debug"},
            "programs": [{"component": "serve", "name": "prefill",
                          "signatures": sigs,
                          "flags": flags or {"callbacks": 0,
                                             "f32_upcasts": 0,
                                             "const_bytes_max": 0}}]}


def test_diff_census_missing_baseline():
    from runbooks_tpu.analysis.program import diff_census

    findings = diff_census(_census(), None, "config/program_baseline.json")
    assert _rules(findings) == ["program-census-drift"]
    assert "missing" in findings[0].message


def test_diff_census_clean_on_match():
    from runbooks_tpu.analysis.program import diff_census

    assert diff_census(_census(), _census(), "b.json") == []


def test_diff_census_flags_signature_growth():
    from runbooks_tpu.analysis.program import diff_census

    findings = diff_census(_census(sigs=5), _census(sigs=3), "b.json")
    assert _rules(findings) == ["program-census-drift"]
    assert "drifted" in findings[0].message


def test_diff_census_flags_new_and_vanished_programs():
    from runbooks_tpu.analysis.program import diff_census

    grown = _census()
    grown["programs"].append({"component": "serve", "name": "decode_v2",
                              "signatures": 1, "flags": None})
    findings = diff_census(grown, _census(), "b.json")
    assert any("new program" in f.message for f in findings)
    findings = diff_census(_census(), grown, "b.json")
    assert any("vanished" in f.message for f in findings)


# ---------------------------------------------------------------------------
# the repo itself: `rbt check --strict` is clean, abstract, and cheap
# ---------------------------------------------------------------------------

def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_audits_clean_with_zero_compiles():
    """The tier-1 gate behind `make check`: the repo at HEAD has no
    active findings, no stale suppressions, and the program audit
    performs ZERO XLA backend compiles (sentinel-verified abstract
    tracing)."""
    from runbooks_tpu.analysis.check import run_check

    report = run_check(_repo_root())
    assert report.active == [], "\n".join(f.render() for f in report.active)
    assert report.stale == []
    assert report.compiles == 0
    assert report.exit_code(strict=True) == 0
    # The committed baseline covers exactly the audited program set.
    names = {(p["component"], p["name"])
             for p in report.census["programs"]}
    assert ("serve", "prefill") in names
    assert ("train", "train_step") in names
    assert ("train", "lora_step") in names


def test_program_baseline_roundtrip(tmp_path):
    """--write-baseline then re-check: drift gate green immediately
    after regeneration, red after tampering."""
    from runbooks_tpu.analysis.program import (
        diff_census,
        load_program_baseline,
        write_program_baseline,
    )

    path = str(tmp_path / "program_baseline.json")
    census = _census()
    write_program_baseline(path, census)
    assert diff_census(census, load_program_baseline(path), path) == []
    tampered = load_program_baseline(path)
    tampered["programs"][0]["signatures"] += 1
    assert diff_census(census, tampered, path) != []


def test_cli_check_strict_exits_zero(capsys, monkeypatch):
    from runbooks_tpu.cli.main import main

    monkeypatch.chdir(_repo_root())
    rc = main(["check", "--strict", "--no-programs"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 active" in out


def test_cli_check_json_reports_census(capsys, monkeypatch):
    from runbooks_tpu.cli.main import main

    monkeypatch.chdir(_repo_root())
    rc = main(["check", "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert data["active"] == []
    assert data["compiles"] == 0
    assert len(data["census"]["programs"]) >= 6


def test_cli_check_nonzero_on_seeded_violation(tmp_path, capsys,
                                               monkeypatch):
    """A fresh violation fails the gate: seeded repo with one blocking
    call in an async handler -> exit 1 and the finding rendered."""
    from runbooks_tpu.cli.main import main

    (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
    pkg = tmp_path / "runbooks_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import time\n\n\nasync def handler():\n    time.sleep(1)\n")
    monkeypatch.chdir(tmp_path)
    rc = main(["check", "--no-programs"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "async-blocking" in out


def test_monitoring_outage_is_not_a_vacuous_pass(monkeypatch, capsys):
    """When jax.monitoring is unavailable the zero-compile assertion
    cannot be verified: the report says so and `rbt check` prints
    UNVERIFIED instead of a confident 0."""
    from runbooks_tpu.analysis.check import run_check
    from runbooks_tpu.cli.main import main
    from runbooks_tpu.obs import device as obs_device

    monkeypatch.setattr(obs_device.SENTINEL, "install", lambda: False)
    report = run_check(_repo_root(), lint=False)
    assert report.monitoring is False
    monkeypatch.chdir(_repo_root())
    assert main(["check", "--no-lint"]) == 0  # findings still gate
    assert "UNVERIFIED" in capsys.readouterr().out


def test_strict_flags_stale_suppression(tmp_path, monkeypatch, capsys):
    """A suppression whose violation was fixed must be removed: --strict
    exits 2 on it, non-strict stays green."""
    from runbooks_tpu.cli.main import main

    (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
    (tmp_path / "runbooks_tpu").mkdir()
    cfg = tmp_path / "config"
    cfg.mkdir()
    (cfg / "check_baseline.json").write_text(json.dumps({
        "suppressions": [{"rule": "async-blocking",
                          "path": "runbooks_tpu/gone.py",
                          "reason": "was fixed; entry forgotten"}]}))
    monkeypatch.chdir(tmp_path)
    assert main(["check", "--no-programs"]) == 0
    assert main(["check", "--no-programs", "--strict"]) == 2
    assert "stale suppression" in capsys.readouterr().out
