"""Multi-replica serving data plane tests (ISSUE 10).

Covers: prefix-aware routing (longest shadow match beats least-loaded;
load breaks ties; deep queues spill), consistent-hash session affinity
surviving replica-set changes, deadline-aware 429/503 failover through
the real HTTP proxy, the shadow index tracking scraped eviction/restart,
the controller-driven autoscaler (1→N on sustained queue wait, N→min on
idle, cooldown and scrape-staleness holds), the serving-gate fixes
(gateway-ready requirement; scale-in transitions), spec validation, the
fleet-state retain fix, and zero unexpected XLA compiles on replicas
under routed traffic.
"""

import asyncio
import dataclasses
import json
import time

import pytest

from runbooks_tpu.api import conditions as cond
from runbooks_tpu.api.types import API_VERSION, Model, Server
from runbooks_tpu.cloud.base import CommonConfig
from runbooks_tpu.cloud.local import LocalCloud
from runbooks_tpu.controller import autoscale as autoscale_mod
from runbooks_tpu.controller import fleet as fl
from runbooks_tpu.controller.common import (
    validate_autoscale,
    validate_gateway,
)
from runbooks_tpu.controller.manager import Ctx, Manager
from runbooks_tpu.controller.model import ModelReconciler
from runbooks_tpu.controller.server import ServerReconciler
from runbooks_tpu.k8s import objects as ko
from runbooks_tpu.k8s.fake import FakeCluster
from runbooks_tpu.obs import metrics as obs_metrics
from runbooks_tpu.sci.base import FakeSCI
from runbooks_tpu.serve.gateway import (
    MetricsPoller,
    Router,
    ShadowIndex,
    create_gateway,
    text_blocks,
    token_blocks,
)

pytestmark = pytest.mark.filterwarnings(
    "ignore::DeprecationWarning")


# ---------------------------------------------------------------------------
# Router unit tests (no HTTP)
# ---------------------------------------------------------------------------

def fams(**values):
    """A parse_exposition-shaped dict from plain gauges/counters."""
    out = {}
    for name, v in values.items():
        fam = obs_metrics.ParsedFamily(name, "gauge")
        fam.samples[()] = float(v)
        out[name] = fam
    return out


def test_prefix_match_beats_least_loaded():
    r = Router({"a": "http://a", "b": "http://b"})
    blocks = text_blocks("x" * 640)
    # b is idle, a holds the prefix but carries more load.
    r.record_route("a", blocks)
    r.observe_metrics("a", fams(serve_active_slots=3, serve_queue_depth=2))
    r.observe_metrics("b", fams(serve_active_slots=0, serve_queue_depth=0))
    picks = r.pick(blocks)
    assert picks[0] == ("a", "prefix")
    assert picks[1] == ("b", "failover")


def test_load_breaks_prefix_ties():
    r = Router({"a": "http://a", "b": "http://b", "c": "http://c"})
    blocks = text_blocks("y" * 640)
    # No shadow entries anywhere: pure load routing.
    r.observe_metrics("a", fams(serve_active_slots=4, serve_queue_depth=0))
    r.observe_metrics("b", fams(serve_active_slots=1, serve_queue_depth=0))
    r.observe_metrics("c", fams(serve_active_slots=2, serve_queue_depth=3))
    name, reason = r.pick(blocks)[0]
    assert (name, reason) == ("b", "load")
    # Equal prefix on two replicas: the less-loaded one (a: load 4 vs
    # c: load 5) wins the tie.
    r.record_route("a", blocks)
    r.record_route("c", blocks)
    name, reason = r.pick(blocks)[0]
    assert (name, reason) == ("a", "prefix")


def test_deep_queue_forfeits_prefix_preference():
    r = Router({"a": "http://a", "b": "http://b"})
    blocks = text_blocks("z" * 640)
    r.record_route("a", blocks)
    # a's queue is past the spill threshold: re-prefilling on idle b is
    # cheaper than queueing behind 20 requests.
    r.observe_metrics("a", fams(serve_active_slots=8,
                                serve_queue_depth=20))
    r.observe_metrics("b", fams(serve_active_slots=0,
                                serve_queue_depth=0))
    name, reason = r.pick(blocks)[0]
    assert name == "b" and reason == "load"


def test_spill_threshold_scales_with_qos_class():
    r = Router({"a": "http://a", "b": "http://b"})
    blocks = text_blocks("z" * 640)
    r.record_route("a", blocks)
    r.observe_metrics("b", fams(serve_active_slots=0,
                                serve_queue_depth=0))
    # Depth 5 on the prefix holder: batch spills at half the base
    # threshold (8 * 0.5 = 4), standard still rides its prefix hit.
    r.observe_metrics("a", fams(serve_active_slots=8,
                                serve_queue_depth=5))
    assert r.pick(blocks, priority="batch")[0] == ("b", "load")
    assert r.pick(blocks, priority="standard")[0] == ("a", "prefix")
    # Depth 12: standard spills past 8, interactive holds its cache
    # locality to twice the base depth (TTFT is its SLO).
    r.observe_metrics("a", fams(serve_active_slots=8,
                                serve_queue_depth=12))
    assert r.pick(blocks, priority="standard")[0] == ("b", "load")
    assert r.pick(blocks, priority="interactive")[0] == ("a", "prefix")
    # An unknown class routes with the standard threshold.
    assert r.pick(blocks, priority="urgent")[0] == ("b", "load")


def test_session_affinity_survives_replica_set_changes():
    r = Router({f"r{i}": f"http://r{i}" for i in range(4)})
    blocks = text_blocks("w" * 640)
    owner = r.pick(blocks, session_key="sess-42")[0]
    assert owner[1] == "affinity"
    # Removing an UNRELATED replica must not remap the session
    # (consistent hashing: only the removed replica's sessions move).
    survivors = {n: f"http://{n}" for n in r.replica_names()
                 if n != owner[0]}
    victim = next(n for n in r.replica_names()
                  if n != owner[0])
    kept = {n: f"http://{n}" for n in r.replica_names() if n != victim}
    r.set_replicas(kept)
    assert r.pick(blocks, session_key="sess-42")[0][0] == owner[0]
    # Removing the owner reassigns the session to a survivor.
    r.set_replicas({n: u for n, u in survivors.items()})
    new_owner = r.pick(blocks, session_key="sess-42")[0]
    assert new_owner[0] != owner[0]
    assert new_owner[0] in survivors


def test_shadow_tracks_eviction_and_restart():
    r = Router({"a": "http://a"})
    long_blocks = text_blocks("q" * 64 * 10)
    r.record_route("a", long_blocks)
    with r._lock:
        assert r._replicas["a"].shadow.blocks == 10
    # The replica's scraped shared-page count says it evicted to 4
    # pages: the shadow trims to match (LRU), so the gateway stops
    # expecting hits the replica can no longer serve.
    r.observe_metrics("a", fams(serve_kv_pages_shared=4,
                                serve_requests_total=100))
    with r._lock:
        assert r._replicas["a"].shadow.blocks == 4
    # A serve_requests_total counter RESET (replica restarted, caches
    # gone) clears the shadow entirely.
    r.observe_metrics("a", fams(serve_kv_pages_shared=4,
                                serve_requests_total=3))
    with r._lock:
        assert r._replicas["a"].shadow.blocks == 0


def test_shadow_index_match_and_trim():
    s = ShadowIndex(max_blocks=8)
    a = token_blocks(list(range(64)), 16)
    b = token_blocks(list(range(48)) + [999] * 16, 16)
    s.record(a)
    assert s.match(a) == 4
    assert s.match(b) == 3  # shared 3-page prefix
    s.record(b)
    assert s.blocks == 5
    s.trim(2)
    assert s.blocks == 2


def test_unhealthy_replicas_never_picked():
    r = Router({"a": "http://a", "b": "http://b"})
    r.observe_metrics("a", None)  # scrape failed
    picks = r.pick(text_blocks("p" * 640))
    assert [n for n, _ in picks] == ["b"]
    r.observe_metrics("b", None)
    assert r.pick(text_blocks("p" * 640)) == []
    assert r.healthy_count() == 0


def test_random_policy_routes_everywhere():
    r = Router({f"r{i}": f"http://r{i}" for i in range(3)},
               policy="random")
    blocks = text_blocks("r" * 640)
    r.record_route("r0", blocks)  # a shadow hit must NOT bias random
    seen = {r.pick(blocks)[0][0] for _ in range(64)}
    assert seen == {"r0", "r1", "r2"}


# ---------------------------------------------------------------------------
# HTTP gateway: proxy, failover, deadline
# ---------------------------------------------------------------------------

def fake_replica(name, behavior):
    """A fake replica app: POST /v1/completions runs `behavior(body)` ->
    (status, payload); /metrics renders a private registry."""
    from aiohttp import web

    reg = obs_metrics.Registry()
    app = web.Application()
    app["hits"] = []

    async def completions(request):
        body = await request.json()
        app["hits"].append(body)
        status, payload = behavior(body)
        headers = ({"Retry-After": "1"} if status in (429, 503)
                   else {})  # like serve/api.py's _reject
        return web.json_response(payload, status=status, headers=headers)

    async def metrics(request):
        return web.Response(body=reg.render().encode(),
                            headers={"Content-Type":
                                     obs_metrics.CONTENT_TYPE})

    app.router.add_post("/v1/completions", completions)
    app.router.add_get("/metrics", metrics)
    app["registry"] = reg
    return app


def ok_behavior(body):
    return 200, {"choices": [{"text": "ok", "finish_reason": "stop"}],
                 "echo_timeout": body.get("timeout")}


def run(coro):
    return asyncio.run(coro)


def test_gateway_proxies_and_fails_over_preserving_deadline():
    from aiohttp.test_utils import TestClient, TestServer

    async def drive():
        from aiohttp import web

        overloaded = fake_replica("a", lambda b: (429, {
            "error": {"message": "full", "type": "overloaded"}}))
        srv_a = TestServer(overloaded)
        await srv_a.start_server()
        # Second replica answers after a short delay so the forwarded
        # deadline shrink is measurable.
        srv_b_app = web.Application()
        srv_b_app["hits"] = []

        async def completions_b(request):
            body = await request.json()
            srv_b_app["hits"].append(body)
            await asyncio.sleep(0.1)
            return web.json_response(ok_behavior(body)[1])

        srv_b_app.router.add_post("/v1/completions", completions_b)
        srv_b = TestServer(srv_b_app)
        await srv_b.start_server()

        reg = obs_metrics.Registry()
        gw = create_gateway(
            {"a": f"http://127.0.0.1:{srv_a.port}",
             "b": f"http://127.0.0.1:{srv_b.port}"},
            scrape_interval_s=0,  # no poller thread in tests
            registry=reg)
        # Pin the routing order: 'a' holds the prefix, so the first pick
        # is the overloaded replica and the request must fail over.
        prompt = "s" * 640
        gw["router"].record_route("a", text_blocks(prompt))
        async with TestClient(TestServer(gw)) as client:
            t0 = time.monotonic()
            resp = await client.post("/v1/completions", json={
                "prompt": prompt, "max_tokens": 4, "timeout": 5.0})
            assert resp.status == 200
            data = await resp.json()
            assert resp.headers["X-Gateway-Replica"] == "b"
            # Deadline-aware retry: the hop to b carries the REMAINING
            # budget, not the original 5 s.
            elapsed = time.monotonic() - t0
            assert data["echo_timeout"] is not None
            assert data["echo_timeout"] < 5.0
            assert data["echo_timeout"] >= 5.0 - elapsed - 0.05
            # The overloaded replica saw the request first.
            assert len(overloaded["hits"]) == 1
        # Metrics: one failover retry, decisions for both replicas.
        assert reg.counter_value("gateway_retries_total",
                                 reason="overloaded") == 1
        assert reg.counter_value("gateway_route_decisions_total",
                                 reason="prefix", backend="a") == 1
        assert reg.counter_value("gateway_route_decisions_total",
                                 reason="failover", backend="b") == 1
        await srv_a.close()
        await srv_b.close()

    run(drive())


def test_gateway_exhausted_deadline_is_504():
    from aiohttp.test_utils import TestClient, TestServer

    async def drive():
        overloaded = fake_replica("a", lambda b: (429, {
            "error": {"message": "full"}}))
        srv = TestServer(overloaded)
        await srv.start_server()
        gw = create_gateway({"a": f"http://127.0.0.1:{srv.port}",
                             "a2": f"http://127.0.0.1:{srv.port}"},
                            scrape_interval_s=0)
        async with TestClient(TestServer(gw)) as client:
            resp = await client.post("/v1/completions", json={
                "prompt": "x", "timeout": 0.02})
            # Budget burned before any replica accepted: 504 with the
            # deadline type, NOT a silent unbounded retry loop.
            assert resp.status in (429, 504)
            if resp.status == 504:
                data = await resp.json()
                assert data["error"]["type"] == "deadline"
        await srv.close()

    run(drive())


def test_gateway_all_replicas_overloaded_propagates_429():
    from aiohttp.test_utils import TestClient, TestServer

    async def drive():
        apps = [fake_replica(n, lambda b: (429, {
            "error": {"message": "full", "type": "overloaded"}}))
            for n in ("a", "b")]
        servers = []
        for app in apps:
            srv = TestServer(app)
            await srv.start_server()
            servers.append(srv)
        gw = create_gateway(
            {n: f"http://127.0.0.1:{s.port}"
             for n, s in zip(("a", "b"), servers)},
            scrape_interval_s=0)
        async with TestClient(TestServer(gw)) as client:
            resp = await client.post("/v1/completions",
                                     json={"prompt": "x"})
            assert resp.status == 429
            assert resp.headers.get("Retry-After")
            assert all(len(a["hits"]) == 1 for a in apps)
        for s in servers:
            await s.close()

    run(drive())


def test_gateway_forwards_priority_as_header():
    from aiohttp.test_utils import TestClient, TestServer

    async def drive():
        from aiohttp import web

        app = web.Application()
        seen = []

        async def completions(request):
            await request.json()
            seen.append(request.headers.get("X-Priority"))
            return web.json_response(ok_behavior({})[1])

        app.router.add_post("/v1/completions", completions)
        srv = TestServer(app)
        await srv.start_server()
        gw = create_gateway({"a": f"http://127.0.0.1:{srv.port}"},
                            scrape_interval_s=0)
        async with TestClient(TestServer(gw)) as client:
            # Body field forwards as the header the replica's admission
            # path reads; the raw header forwards verbatim too.
            r = await client.post("/v1/completions", json={
                "prompt": "x", "priority": "batch"})
            assert r.status == 200
            r = await client.post("/v1/completions", json={"prompt": "x"},
                                  headers={"X-Priority": "interactive"})
            assert r.status == 200
            r = await client.post("/v1/completions", json={"prompt": "x"})
            assert r.status == 200
        assert seen == ["batch", "interactive", None]
        await srv.close()

    run(drive())


def test_gateway_shed_retry_budget_bounds_429_failover():
    from aiohttp.test_utils import TestClient, TestServer

    async def drive():
        # Three overloaded replicas: a batch request (budget 1) burns
        # one 429-driven failover hop, then the shed passes through to
        # the client with the REPLICA's Retry-After hint — the third
        # replica never sees work the fleet just said it cannot absorb.
        apps = [fake_replica(n, lambda b: (429, {
            "error": {"message": "full", "type": "overloaded"}}))
            for n in ("a", "b", "c")]
        servers = []
        for app in apps:
            srv = TestServer(app)
            await srv.start_server()
            servers.append(srv)
        reg = obs_metrics.Registry()
        gw = create_gateway(
            {n: f"http://127.0.0.1:{s.port}"
             for n, s in zip(("a", "b", "c"), servers)},
            scrape_interval_s=0, registry=reg)
        async with TestClient(TestServer(gw)) as client:
            resp = await client.post("/v1/completions", json={
                "prompt": "x", "priority": "batch"})
            assert resp.status == 429
            # fake_replica answers Retry-After: 1; the gateway's own
            # fallthrough default is 2 — seeing 1 proves passthrough.
            assert resp.headers.get("Retry-After") == "1"
            assert sum(len(a["hits"]) for a in apps) == 2
        assert reg.counter_value("gateway_shed_passthrough_total",
                                 **{"class": "batch"}) == 1
        # An interactive request gets the full replica sweep: budget 3
        # covers both failover hops before candidates run out.
        async with TestClient(TestServer(gw)) as client:
            resp = await client.post("/v1/completions", json={
                "prompt": "y", "priority": "interactive"})
            assert resp.status == 429
            assert sum(len(a["hits"]) for a in apps) == 5
        assert reg.counter_value("gateway_shed_passthrough_total",
                                 **{"class": "interactive"}) == 0
        for s in servers:
            await s.close()

    run(drive())


def test_gateway_unready_without_backends():
    from aiohttp.test_utils import TestClient, TestServer

    async def drive():
        gw = create_gateway({}, scrape_interval_s=0)
        async with TestClient(TestServer(gw)) as client:
            resp = await client.get("/")
            # Readiness fails while the gateway cannot route anywhere —
            # the Serving gate depends on this (controller/server.py).
            assert resp.status == 503
            m = await client.get("/metrics")
            text = await m.text()
            assert "gateway_replicas_healthy 0" in text

    run(drive())


def test_metrics_poller_updates_router():
    from aiohttp.test_utils import TestServer

    async def drive():
        app = fake_replica("a", ok_behavior)
        app["registry"].set_gauge("serve_active_slots", 5)
        app["registry"].set_gauge("serve_queue_depth", 2)
        srv = TestServer(app)
        await srv.start_server()
        router = Router({"a": f"http://127.0.0.1:{srv.port}",
                         "dead": "http://127.0.0.1:1"})
        poller = MetricsPoller(router, timeout_s=1.0)
        # poll_once is the poller THREAD's body (blocking urllib); off
        # the loop or the scrape of the in-loop TestServer deadlocks.
        ok = await asyncio.get_running_loop().run_in_executor(
            None, poller.poll_once)
        assert ok == 1
        with router._lock:
            assert router._replicas["a"].active_slots == 5
            assert router._replicas["a"].queue_depth == 2
            assert router._replicas["a"].healthy
            assert not router._replicas["dead"].healthy
        await srv.close()

    run(drive())


# ---------------------------------------------------------------------------
# Validation
# ---------------------------------------------------------------------------

def test_validate_gateway_and_autoscale():
    assert validate_gateway(None) is None
    assert validate_gateway({"enabled": True, "replicas": 2,
                             "policy": "prefix"}) is None
    assert "unknown field" in validate_gateway({"enable": True})
    assert "not one of" in validate_gateway({"policy": "roundrobin"})
    assert ">= 1" in validate_gateway({"replicas": 0})
    assert "must be a mapping" in validate_gateway("yes")

    assert validate_autoscale(None) is None
    assert validate_autoscale({"minReplicas": 1, "maxReplicas": 4}) is None
    assert "required" in validate_autoscale({"minReplicas": 2})
    assert ">= minReplicas" in validate_autoscale(
        {"minReplicas": 3, "maxReplicas": 2})
    assert "unknown field" in validate_autoscale(
        {"maxReplicas": 2, "queueWait": 5})
    assert "not a number" in validate_autoscale(
        {"maxReplicas": 2, "queueWaitP90Ms": "fast"})
    assert "> 0" in validate_autoscale(
        {"maxReplicas": 2, "queueWaitP90Ms": 0})


def test_invalid_gateway_block_surfaces_condition(harness):
    client, ctx, mgr = harness
    client.create(Server.new("bad", spec={
        "image": "img", "model": {"name": "m"},
        "gateway": {"policy": "nope"}}).obj)
    mgr.reconcile_until_stable()
    srv = client.get(API_VERSION, "Server", "default", "bad")
    c = ko.get_condition(srv, cond.SERVING)
    assert c["status"] == "False"
    assert c["reason"] == cond.REASON_INVALID_PARAMS
    assert "spec.gateway.policy" in c["message"]


# ---------------------------------------------------------------------------
# Controller: gateway deployment + serving gate
# ---------------------------------------------------------------------------

@pytest.fixture()
def harness(tmp_path):
    client = FakeCluster()
    cloud = LocalCloud(CommonConfig(
        cluster_name="testcluster",
        artifact_bucket_url=f"file://{tmp_path}/bucket",
        registry_url="registry.local:5000"))
    ctx = Ctx(client=client, cloud=cloud, sci=FakeSCI())
    mgr = Manager(ctx, [ModelReconciler(), ServerReconciler()])
    return client, ctx, mgr


@pytest.fixture(autouse=True)
def clean_state():
    fl.FLEET.reset()
    autoscale_mod.AUTOSCALE.reset()
    yield
    fl.FLEET.reset()
    autoscale_mod.AUTOSCALE.reset()


def ready_model_server(client, mgr, spec_extra, pods=("srv-0",)):
    client.create(Model.new("m", spec={"image": "loader"}).obj)
    client.create(Server.new("srv", spec={
        "image": "img", "model": {"name": "m"}, **spec_extra}).obj)
    # Replica pods exist so the reconciler's fleet-retain pass (which
    # drops samples for vanished pods) keeps the seeded FLEET samples.
    for pod in pods:
        client.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": pod, "namespace": "default",
                         "labels": {"server": "srv", "role": "run"}},
            "spec": {}, "status": {"phase": "Running",
                                   "podIP": "10.0.0.1"}})
    mgr.reconcile_until_stable()
    client.mark_job_complete("default", "m-modeller")
    mgr.reconcile_until_stable()


def test_gateway_deployment_and_serving_gate(harness):
    client, ctx, mgr = harness
    ready_model_server(client, mgr, {"gateway": {"enabled": True,
                                                 "replicas": 2}})
    gw = client.get("apps/v1", "Deployment", "default", "srv-gateway")
    assert gw is not None
    assert gw["spec"]["replicas"] == 2
    tmpl = gw["spec"]["template"]
    assert tmpl["metadata"]["labels"]["role"] == "gateway"
    container = tmpl["spec"]["containers"][0]
    assert container["command"] == ["python", "-m",
                                    "runbooks_tpu.serve.gateway"]
    env = {e["name"]: e.get("value") for e in container["env"]}
    assert env["RBT_GATEWAY_SERVER"] == "srv"
    svc = client.get("v1", "Service", "default", "srv-gateway")
    assert svc["spec"]["selector"] == {"server": "srv",
                                      "role": "gateway"}

    # Replicas ready but the gateway is not: the only ingress path is
    # down, so the Server must NOT report serving (satellite fix).
    client.mark_deployment_ready("default", "srv")
    mgr.reconcile_until_stable()
    srv = client.get(API_VERSION, "Server", "default", "srv")
    c = ko.get_condition(srv, cond.SERVING)
    assert c["status"] == "False"
    assert "gateway" in c["message"]

    client.mark_deployment_ready("default", "srv-gateway")
    mgr.reconcile_until_stable()
    srv = client.get(API_VERSION, "Server", "default", "srv")
    c = ko.get_condition(srv, cond.SERVING)
    assert c["status"] == "True"
    assert "gateway ready" in c["message"]


def test_scale_in_transition_keeps_serving(harness):
    """spec.replicas=3 but the autoscaler has scaled the Deployment to 1
    (>= minReplicas): ready_replicas=1 < spec.replicas must NOT read as
    not-serving (the old gate compared against spec.replicas)."""
    client, ctx, mgr = harness
    ready_model_server(client, mgr, {
        "replicas": 3,
        "autoscale": {"minReplicas": 1, "maxReplicas": 3}})
    # Autoscaler holds at 3 (no telemetry -> stale hold), Deployment=3.
    dep = client.get("apps/v1", "Deployment", "default", "srv")
    assert dep["spec"]["replicas"] == 3
    # Force the book's desired down to 1 (as a sustained-idle run would).
    st = autoscale_mod.AUTOSCALE.state_for(("default", "srv"))
    st.desired = 1
    client.mark_deployment_ready("default", "srv", replicas=1)
    mgr.process_event("Server",
                      client.get(API_VERSION, "Server", "default", "srv"))
    srv = client.get(API_VERSION, "Server", "default", "srv")
    assert ko.is_condition_true(srv, cond.SERVING)
    dep = client.get("apps/v1", "Deployment", "default", "srv")
    assert dep["spec"]["replicas"] == 1


# ---------------------------------------------------------------------------
# Autoscaler
# ---------------------------------------------------------------------------

def load_sample(replica, qw_s=0.0, n=20, active=0, queue=0, slots=4,
                last_success=None):
    """An up replica sample with a queue-wait histogram centered near
    qw_s plus slot/queue gauges."""
    import bisect

    families = {}
    if qw_s > 0:
        fam = obs_metrics.ParsedFamily("serve_queue_wait_seconds",
                                       "histogram")
        hist = obs_metrics.ParsedHistogram()
        hist.bounds = list(obs_metrics.DEFAULT_BUCKETS)
        idx = bisect.bisect_left(hist.bounds, qw_s)
        cum, acc = [], 0
        for i in range(len(hist.bounds)):
            if i == idx:
                acc = n
            cum.append(acc)
        hist.cumulative = cum
        hist.count = n
        hist.sum = qw_s * n
        fam.histograms[()] = hist
        families["serve_queue_wait_seconds"] = fam
    for name, val in (("serve_active_slots", active),
                      ("serve_queue_depth", queue),
                      ("serve_slots_total", slots),
                      ("serve_requests_total", n)):
        fam = obs_metrics.ParsedFamily(name, "gauge")
        fam.samples[()] = float(val)
        families[name] = fam
    return fl.ReplicaSample(
        replica, up=True,
        last_success=(last_success if last_success is not None
                      else time.monotonic()),
        families=families)


FAST = {"minReplicas": 1, "maxReplicas": 3, "queueWaitP90Ms": 50,
        "scaleOutSustainS": 0, "scaleInSustainS": 0, "cooldownS": 0}


def test_autoscaler_scales_out_and_back_controller_driven(harness):
    """Acceptance: 1 -> N on sustained queue wait, N -> min on idle,
    through the real reconciler and the real Deployment object."""
    client, ctx, mgr = harness
    ready_model_server(client, mgr, {"autoscale": dict(FAST)})
    from runbooks_tpu.controller.metrics import REGISTRY

    out_before = REGISTRY.counter_value(
        "controller_autoscale_actions_total", server="srv",
        namespace="default", direction="out")
    in_before = REGISTRY.counter_value(
        "controller_autoscale_actions_total", server="srv",
        namespace="default", direction="in")
    key = ("Server", "default", "srv")
    # Sustained queue-wait p90 (~400 ms >> 50 ms target).
    fl.FLEET.update(key, load_sample("srv-0", qw_s=0.4, active=4, queue=6))
    for want in (2, 3, 3):  # capped at maxReplicas
        mgr.process_event(
            "Server", client.get(API_VERSION, "Server", "default", "srv"))
        dep = client.get("apps/v1", "Deployment", "default", "srv")
        assert dep["spec"]["replicas"] == want
    srv = client.get(API_VERSION, "Server", "default", "srv")
    autoscale_status = ko.deep_get(srv, "status", "autoscale")
    assert autoscale_status["desiredReplicas"] == 3
    assert autoscale_status["lastAction"] == "out"
    assert REGISTRY.counter_value(
        "controller_autoscale_actions_total", server="srv",
        namespace="default", direction="out") == out_before + 2

    # Load drains: queue empty, slots idle -> back down to min.
    fl.FLEET.update(key, load_sample("srv-0", qw_s=0.0, active=0, queue=0))
    for want in (2, 1, 1):  # floored at minReplicas
        mgr.process_event(
            "Server", client.get(API_VERSION, "Server", "default", "srv"))
        dep = client.get("apps/v1", "Deployment", "default", "srv")
        assert dep["spec"]["replicas"] == want
    assert REGISTRY.counter_value(
        "controller_autoscale_actions_total", server="srv",
        namespace="default", direction="in") == in_before + 2


def test_autoscaler_cooldown_limits_action_rate(harness):
    client, ctx, mgr = harness
    spec = dict(FAST, cooldownS=3600)
    ready_model_server(client, mgr, {"autoscale": spec})
    key = ("Server", "default", "srv")
    fl.FLEET.update(key, load_sample("srv-0", qw_s=0.4, queue=6))
    for _ in range(3):
        mgr.process_event(
            "Server", client.get(API_VERSION, "Server", "default", "srv"))
    dep = client.get("apps/v1", "Deployment", "default", "srv")
    # One action, then the cooldown holds every subsequent reconcile.
    assert dep["spec"]["replicas"] == 2


def test_autoscaler_sustain_requires_duration(monkeypatch):
    """The overload signal must HOLD for scaleOutSustainS before an
    action fires (a one-scrape blip is not sustained load)."""
    clock = [1000.0]
    monkeypatch.setattr(autoscale_mod, "_now", lambda: clock[0])
    spec = {"minReplicas": 1, "maxReplicas": 3, "queueWaitP90Ms": 50,
            "scaleOutSustainS": 30, "cooldownS": 0}
    summary = {"replicasUp": 1, "queueWaitP90Ms": 400.0,
               "activeSlots": 4, "queueDepth": 6, "slotsTotal": 4}
    desired, action = autoscale_mod.evaluate(
        ("ns", "s"), spec, {}, summary, False, 1.0, 20.0, 1)
    assert (desired, action) == (1, None)  # onset recorded, not acted
    clock[0] += 31
    desired, action = autoscale_mod.evaluate(
        ("ns", "s"), spec, {}, summary, False, 1.0, 20.0, 1)
    assert desired == 2 and action["direction"] == "out"
    assert "queueWaitP90Ms" in action["reason"]


def test_autoscaler_holds_on_stale_telemetry(monkeypatch):
    """Never act on a scrape older than 2 intervals — and a staleness
    window must also reset the sustain onset (no banked pressure)."""
    clock = [1000.0]
    monkeypatch.setattr(autoscale_mod, "_now", lambda: clock[0])
    spec = {"minReplicas": 1, "maxReplicas": 3, "queueWaitP90Ms": 50,
            "scaleOutSustainS": 10, "cooldownS": 0}
    summary = {"replicasUp": 1, "queueWaitP90Ms": 400.0,
               "activeSlots": 4, "queueDepth": 6, "slotsTotal": 4}
    autoscale_mod.evaluate(("ns", "h"), spec, {}, summary, False,
                           1.0, 20.0, 1)  # onset at t=1000
    clock[0] += 60
    # Stale scrape (age 100 > 20): hold, despite 60 s of "pressure".
    desired, action = autoscale_mod.evaluate(
        ("ns", "h"), spec, {}, summary, False, 100.0, 20.0, 1)
    assert (desired, action) == (1, None)
    st = autoscale_mod.AUTOSCALE.state_for(("ns", "h"))
    assert st.held_stale and st.out_since is None
    # Fresh again: the sustain clock restarts from now.
    desired, action = autoscale_mod.evaluate(
        ("ns", "h"), spec, {}, summary, False, 1.0, 20.0, 1)
    assert (desired, action) == (1, None)
    clock[0] += 11
    desired, action = autoscale_mod.evaluate(
        ("ns", "h"), spec, {}, summary, False, 1.0, 20.0, 1)
    assert desired == 2


def test_autoscaler_slo_violation_triggers_scale_out(monkeypatch):
    clock = [0.0]
    monkeypatch.setattr(autoscale_mod, "_now", lambda: clock[0])
    spec = {"minReplicas": 1, "maxReplicas": 2, "scaleOutSustainS": 0,
            "cooldownS": 0}
    summary = {"replicasUp": 1, "activeSlots": 4, "queueDepth": 2,
               "slotsTotal": 4}
    desired, action = autoscale_mod.evaluate(
        ("ns", "v"), spec, {"ttftP99Ms": 100}, summary, True, 1.0, 20.0, 1)
    assert desired == 2 and action["reason"] == "SLOViolated"


def test_autoscaler_scale_in_respects_occupancy(monkeypatch):
    """Scale-in fires only when the remaining replicas can absorb the
    active slots at the configured occupancy."""
    clock = [0.0]
    monkeypatch.setattr(autoscale_mod, "_now", lambda: clock[0])
    spec = {"minReplicas": 1, "maxReplicas": 4, "scaleInSustainS": 0,
            "cooldownS": 0}
    st = autoscale_mod.AUTOSCALE.state_for(("ns", "o"))
    st.desired = 3
    # 3 replicas x 4 slots, 5 active: (3-1)*4*0.5 = 4 < 5 -> hold.
    busy = {"replicasUp": 3, "activeSlots": 5, "queueDepth": 0,
            "slotsTotal": 12}
    desired, action = autoscale_mod.evaluate(
        ("ns", "o"), spec, {}, busy, False, 1.0, 20.0, 3)
    assert (desired, action) == (3, None)
    idle = dict(busy, activeSlots=3)  # 3 <= 4 -> scale in
    desired, action = autoscale_mod.evaluate(
        ("ns", "o"), spec, {}, idle, False, 1.0, 20.0, 3)
    assert desired == 2 and action["direction"] == "in"


def test_fleet_retain_drops_vanished_replicas(harness):
    """Satellite: stale FleetState entries for scaled-in pods must drop
    before the autoscaler reads per-replica aggregates."""
    client, ctx, mgr = harness
    ready_model_server(client, mgr, {"autoscale": dict(FAST)})
    key = ("Server", "default", "srv")
    # Two replicas scraped; srv-1's pod is gone (scale-in victim) and its
    # last sample carries the WORST queue wait.
    fl.FLEET.update(key, load_sample("srv-0", qw_s=0.0, active=0, queue=0))
    fl.FLEET.update(key, load_sample("srv-1", qw_s=2.0, active=4, queue=9))
    mgr.process_event("Server",
                      client.get(API_VERSION, "Server", "default", "srv"))
    # srv-1's sample is gone; the summary (and therefore any autoscale
    # decision) no longer sees the dead pod's 2 s queue waits.
    assert fl.FLEET.get_sample(key, "srv-1") is None
    summary = fl.FLEET.server_summary("default", "srv")
    assert summary["replicas"] == 1
    assert summary.get("queueWaitP90Ms", 0) < 1000
    # And the scale-in signal (idle survivor) can act on clean data.
    dep = client.get("apps/v1", "Deployment", "default", "srv")
    assert dep["spec"]["replicas"] == 1


def test_retain_keeps_gateway_sample(harness):
    """The reconciler's retain pass builds its live set from role=run
    pods only — the gateway pod's sample (same workload key) must
    survive it, or its mirrored series blank between scrape sweeps."""
    client, ctx, mgr = harness
    ready_model_server(client, mgr, {"autoscale": dict(FAST),
                                     "gateway": {"enabled": True}})
    key = ("Server", "default", "srv")
    fl.FLEET.update(key, load_sample("srv-0", qw_s=0.0))
    gw = load_sample("srv-gateway-x", qw_s=0.0)
    fl.FLEET.update(key, dataclasses.replace(gw, role="gateway"))
    mgr.process_event("Server",
                      client.get(API_VERSION, "Server", "default", "srv"))
    assert fl.FLEET.get_sample(key, "srv-gateway-x") is not None
    # And the load aggregates still exclude it (role filter).
    assert fl.FLEET.server_summary("default", "srv")["replicas"] == 1


def test_autoscaler_survives_controller_restart(harness):
    """The in-process AUTOSCALE book dies with the controller; the
    .status.autoscale mirror must re-seed the next process's target so
    a restart does not snap a scaled-out Deployment back to
    spec.replicas under load."""
    client, ctx, mgr = harness
    ready_model_server(client, mgr, {"autoscale": dict(FAST)})
    key = ("Server", "default", "srv")
    fl.FLEET.update(key, load_sample("srv-0", qw_s=0.4, active=4, queue=6))
    for _ in range(2):
        mgr.process_event(
            "Server", client.get(API_VERSION, "Server", "default", "srv"))
    dep = client.get("apps/v1", "Deployment", "default", "srv")
    assert dep["spec"]["replicas"] == 3
    # "Restart": fresh book, same cluster state.
    autoscale_mod.AUTOSCALE.reset()
    mgr.process_event("Server",
                      client.get(API_VERSION, "Server", "default", "srv"))
    dep = client.get("apps/v1", "Deployment", "default", "srv")
    assert dep["spec"]["replicas"] == 3  # not back to spec.replicas=1


def test_disabling_gateway_deletes_deployment(harness):
    """Flipping spec.gateway.enabled off must remove the gateway
    Deployment + Service (a stale gateway would keep routing with
    frozen config)."""
    client, ctx, mgr = harness
    ready_model_server(client, mgr, {"gateway": {"enabled": True}})
    assert client.get("apps/v1", "Deployment", "default",
                      "srv-gateway") is not None
    srv = client.get(API_VERSION, "Server", "default", "srv")
    srv["spec"]["gateway"] = {"enabled": False}
    client.update(srv)
    mgr.reconcile_until_stable()
    assert client.get("apps/v1", "Deployment", "default",
                      "srv-gateway") is None
    assert client.get("v1", "Service", "default", "srv-gateway") is None


def test_scraper_skips_terminating_pods(harness):
    """A Terminating pod still reports phase=Running; the scraper must
    leave it out of discovery (and FleetState) immediately."""
    client, ctx, _ = harness
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    from runbooks_tpu.obs.metrics import Registry, serve_metrics

    reg = Registry()
    reg.set_counter("serve_requests_total", 5)
    httpd = serve_metrics(0, reg)
    for name, deleting in (("srv-0", False), ("srv-1", True)):
        meta = {"name": name, "namespace": "default",
                "labels": {"server": "srv", "role": "run"},
                "annotations": {fl.METRICS_PORT_ANNOTATION:
                                str(httpd.server_address[1])}}
        if deleting:
            meta["deletionTimestamp"] = "2026-08-03T00:00:00Z"
        client.create({"apiVersion": "v1", "kind": "Pod",
                       "metadata": meta, "spec": {},
                       "status": {"phase": "Running",
                                  "podIP": "127.0.0.1"}})
    registry, state = Registry(), fl.FleetState()
    scraper = fl.FleetScraper(ctx, state=state, registry=registry)
    try:
        assert scraper.scrape_once() == 1
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert state.get_sample(("Server", "default", "srv"), "srv-0") \
        is not None
    assert state.get_sample(("Server", "default", "srv"), "srv-1") is None


def test_fleet_scrapes_gateway_pods_separately(harness):
    """Gateway pods mirror their gateway_* families under the Server's
    workload key but stay OUT of the load/SLO aggregates."""
    client, ctx, _ = harness
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    from runbooks_tpu.obs.metrics import Registry, serve_metrics

    rep_reg = Registry()
    rep_reg.set_gauge("serve_active_slots", 3)
    rep_reg.set_counter("serve_requests_total", 5)
    gw_reg = Registry()
    gw_reg.inc("gateway_requests_total", 7)
    gw_reg.set_gauge("gateway_replicas_healthy", 1)
    httpd_rep = serve_metrics(0, rep_reg)
    httpd_gw = serve_metrics(0, gw_reg)
    for name, role, port in (
            ("srv-0", "run", httpd_rep.server_address[1]),
            ("srv-gateway-abc", "gateway", httpd_gw.server_address[1])):
        client.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default",
                         "labels": {"server": "srv", "role": role},
                         "annotations": {fl.METRICS_PORT_ANNOTATION:
                                         str(port)}},
            "spec": {}, "status": {"phase": "Running",
                                   "podIP": "127.0.0.1"}})
    registry, state = Registry(), fl.FleetState()
    scraper = fl.FleetScraper(ctx, state=state, registry=registry)
    try:
        assert scraper.scrape_once() == 2
    finally:
        for h in (httpd_rep, httpd_gw):
            h.shutdown()
            h.server_close()
    text = registry.render()
    assert 'gateway_requests_total{kind="Server",name="srv",' \
           'namespace="default",replica="srv-gateway-abc"} 7' in text
    summary = state.server_summary("default", "srv")
    # The gateway pod is not serving capacity.
    assert summary["replicas"] == 1 and summary["replicasUp"] == 1
    assert summary["activeSlots"] == 3


def test_rbt_top_renders_gateway_row(capsys):
    from runbooks_tpu.cli import main as cli
    from runbooks_tpu.obs.metrics import Registry, serve_metrics

    reg = Registry()
    lbl = dict(kind="Server", namespace="default", name="srv",
               replica="srv-gateway-x")
    reg.set_gauge("fleet_scrape_up", 1, **lbl)
    reg.set_gauge("fleet_scrape_age_seconds", 0.0, **lbl)
    reg.set_counter("gateway_requests_total", 42, **lbl)
    reg.set_gauge("gateway_replicas_healthy", 3, **lbl)
    reg.set_counter("gateway_affinity_requests_total", 10, **lbl)
    reg.set_counter("gateway_affinity_hits_total", 9, **lbl)
    reg.set_counter("gateway_retries_total", 2,
                    reason="overloaded", **lbl)
    httpd = serve_metrics(0, reg)
    try:
        assert cli.main(["top", "--once", "--url",
                         f"http://127.0.0.1:{httpd.server_address[1]}"]) \
            == 0
    finally:
        httpd.shutdown()
        httpd.server_close()
    out = capsys.readouterr().out
    row = next(ln for ln in out.splitlines() if "srv-gateway-x" in ln)
    assert "routed=42" in row and "backends=3" in row
    assert "affinity=90%" in row and "retries=2" in row


# ---------------------------------------------------------------------------
# End to end: real engines behind the gateway, zero unexpected compiles
# ---------------------------------------------------------------------------

def tiny_cfg():
    from runbooks_tpu.models.config import get_config

    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype="float32")


def test_routed_traffic_compiles_nothing_unexpected():
    """Two real (warmed) replicas behind the gateway: routed traffic —
    including shared-prefix repeats and a failover-shaped burst — must
    not trigger a single unexpected XLA compile on either replica."""
    import jax
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.models.transformer import init_params
    from runbooks_tpu.obs import device as obs_device
    from runbooks_tpu.serve.api import create_server

    cfg = tiny_cfg()
    params = jax.jit(lambda r: init_params(cfg, r))(jax.random.key(0))

    async def drive():
        apps = [create_server(cfg, params, max_slots=2, warmup=True)
                for _ in range(2)]
        servers = []
        for app in apps:
            srv = TestServer(app)
            await srv.start_server()
            servers.append(srv)
        gw = create_gateway(
            {f"r{i}": f"http://127.0.0.1:{s.port}"
             for i, s in enumerate(servers)},
            scrape_interval_s=0)
        unexpected_before = obs_device.SENTINEL.unexpected
        # Byte tokenizer + 64-token context: prompts must stay short.
        shared = "All work and no play makes Jack"
        async with TestClient(TestServer(gw)) as client:
            for i in range(6):
                resp = await client.post("/v1/completions", json={
                    "prompt": shared + f" request {i}",
                    "max_tokens": 3})
                assert resp.status == 200
                data = await resp.json()
                assert data["choices"][0]["text"] is not None
                assert "X-Gateway-Replica" in resp.headers
        assert obs_device.SENTINEL.unexpected == unexpected_before, \
            "routed traffic must stay inside the warmed program set"
        for s in servers:
            await s.close()

    run(drive())
