"""LoRA training, checkpoint/resume, and data-pipeline tests."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import forward, init_params, param_logical_axes
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh
from runbooks_tpu.parallel.sharding import tree_shardings
from runbooks_tpu.train import data as data_mod
from runbooks_tpu.train.lora import (
    LoraConfig,
    apply_lora,
    create_lora_train_state,
    init_lora,
    make_lora_train_step,
    trainable_param_count,
)
from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
from runbooks_tpu.train.step import create_train_state, make_train_step


def tiny_cfg():
    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=32, dtype="float32",
    )


# ---------------------------------------------------------------------------
# LoRA
# ---------------------------------------------------------------------------

def test_lora_zero_delta_at_init():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    lcfg = LoraConfig(rank=4)
    lora = init_lora(params, lcfg, jax.random.key(1))
    merged = apply_lora(params, lora, lcfg)
    toks = jax.random.randint(jax.random.key(2), (1, 8), 0, cfg.vocab_size)
    l0, _ = forward(cfg, params, toks)
    l1, _ = forward(cfg, merged, toks)
    np.testing.assert_allclose(l0, l1, rtol=1e-6, atol=1e-6)
    assert trainable_param_count(lora) < cfg.num_params * 0.05


def test_lora_trains_with_frozen_base():
    cfg = tiny_cfg()
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
    base = init_params(cfg, jax.random.key(0))
    base_shardings = tree_shardings(
        jax.eval_shape(lambda: base), param_logical_axes(cfg), mesh)
    base = jax.device_put(base, base_shardings)
    lcfg = LoraConfig(rank=4)
    opt = make_optimizer(OptimizerConfig(learning_rate=5e-3, warmup_steps=0,
                                         total_steps=50, schedule="constant"))
    state, shardings = create_lora_train_state(
        cfg, lcfg, base, opt, mesh, jax.random.key(1))
    step = make_lora_train_step(cfg, lcfg, opt, mesh, shardings, base_shardings)

    toks = jax.random.randint(jax.random.key(3), (8, 17), 0, cfg.vocab_size)
    batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
    losses = []
    with jax.set_mesh(mesh):
        for _ in range(5):
            state, m = step(state, base, batch)
            losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses


# ---------------------------------------------------------------------------
# Checkpoint / resume
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_checkpoint_roundtrip(tmp_path):
    from runbooks_tpu.train.checkpoint import CheckpointManager

    cfg = tiny_cfg()
    mesh = make_mesh(MeshConfig(data=1, fsdp=8, sequence=1, tensor=1))
    opt = make_optimizer(OptimizerConfig())
    state, shardings = create_train_state(cfg, opt, mesh, jax.random.key(0))
    step = make_train_step(cfg, opt, mesh, shardings)
    toks = jax.random.randint(jax.random.key(1), (8, 17), 0, cfg.vocab_size)
    batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
    with jax.set_mesh(mesh):
        state, _ = step(state, batch)

    mgr = CheckpointManager(str(tmp_path), async_save=False)
    assert mgr.save(int(state.step), state)
    mgr.wait()
    assert mgr.latest_step() == 1

    restored = mgr.restore(state)
    assert int(restored.step) == int(state.step)
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored arrays keep their shardings
    wq = restored.params["layers"]["attn"]["wq"]
    assert wq.sharding == state.params["layers"]["attn"]["wq"].sharding
    mgr.close()


# ---------------------------------------------------------------------------
# Data pipeline
# ---------------------------------------------------------------------------

def test_pack_documents_shapes_and_isolation():
    docs = [[256, 10, 11, 12, 257], [256, 20, 21, 257], [256, 30, 257],
            list(range(256, 256 + 1)) + list(range(40, 60))]
    rows = list(data_mod.pack_documents(docs, seq_len=8))
    assert all(r["tokens"].shape == (8,) for r in rows)
    for r in rows:
        # positions restart at each segment start
        segs, pos = r["segment_ids"], r["positions"]
        for i in range(1, 8):
            if segs[i] != 0 and segs[i] != segs[i - 1]:
                assert pos[i] == 0 or pos[i] > 0  # continuation rows keep pos
        # loss_mask zero on padding
        assert all(r["loss_mask"][segs[:8] == 0] == 0.0)


def test_pack_long_doc_splits_and_positions_continue():
    doc = list(range(1, 25))  # 24 tokens, seq_len 8 -> spans multiple rows
    rows = list(data_mod.pack_documents([doc], seq_len=8))
    assert len(rows) >= 2
    # first row positions 0..7, second row continues 9.. (9 tokens consumed)
    assert rows[0]["positions"][0] == 0
    assert rows[1]["positions"][0] == 9


def test_dataset_end_to_end(tmp_path):
    p = os.path.join(tmp_path, "docs.jsonl")
    with open(p, "w") as f:
        for i in range(20):
            f.write('{"text": "hello world %d"}\n' % i)
    batches = list(data_mod.dataset(p, seq_len=32, batch_size=2, epochs=1))
    assert batches
    b = batches[0]
    assert b["tokens"].shape == (2, 32)
    assert b["targets"].shape == (2, 32)
    assert set(b) == {"tokens", "targets", "segment_ids", "positions",
                      "loss_mask"}


def test_byte_tokenizer_roundtrip():
    tok = data_mod.ByteTokenizer()
    ids = tok.encode("héllo ✓")
    assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
    assert tok.decode(ids) == "héllo ✓"
