"""Test harness: force an 8-device virtual CPU platform before JAX initializes.

Multi-chip TPU hardware is not available in CI; all sharding logic is tested on
a virtual 8-device CPU mesh (the same technique the driver's dryrun_multichip
uses). Mirrors the reference's strategy of testing the whole operator loop
without cloud dependencies (SURVEY.md §4: envtest + kind cloud).
"""

import os
import sys

# Must be set before jax is imported anywhere. Forced (not setdefault): the
# repo image pins JAX_PLATFORMS=axon (the TPU relay plugin) in the ambient
# env, and a bare `pytest tests/` must not dial the relay — the relay is
# single-client and may be down. Set RBT_TEST_PLATFORM to override.
# The pinning recipe lives in benchkit.apply_cpu_env (also clears
# PALLAS_AXON_POOL_IPS so test subprocesses skip the relay hook too).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from benchkit import apply_cpu_env  # noqa: E402

if os.environ.get("RBT_TEST_PLATFORM", "cpu") == "cpu":
    apply_cpu_env(n_devices=8)
else:
    os.environ["JAX_PLATFORMS"] = os.environ["RBT_TEST_PLATFORM"]

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Belt-and-braces: pytest loads installed plugins BEFORE conftest, and
    # some of them import jax — which latches the ambient JAX_PLATFORMS
    # (axon) at import time, making the env override above a no-op and
    # hanging the first jax.devices() on the dead relay. The config update
    # still works as long as no backend has been initialized yet.
    jax.config.update("jax_platforms", "cpu")

# Exact-math tests: JAX's *default* matmul precision may round inputs to
# bf16 even for f32 arrays, which makes results shape-dependent (full matmul
# vs sliced matmul accumulate differently). Pin highest precision in tests;
# production code on TPU keeps the fast default (bf16 on the MXU).
jax.config.update("jax_default_matmul_precision", "highest")

import functools  # noqa: E402
import tempfile  # noqa: E402

import pytest  # noqa: E402

# Keep the container contract's /content out of test runs: always-on
# paths (flight-recorder tail sampling, incident capture) default their
# output under contract.artifacts_dir(), and a test that exercises them
# without monkeypatching RBT_CONTENT_DIR must land in a throwaway dir,
# never in a real /content (tests may run as root, where the mkdir
# would succeed).
os.environ.setdefault(
    "RBT_CONTENT_DIR", tempfile.mkdtemp(prefix="rbt-test-content-"))


@functools.lru_cache(maxsize=None)
def partial_manual_shard_map_broken() -> bool:
    """Capability probe for the old-jaxlib SPMD limitation: a PARTIAL-manual
    shard_map (manual over one mesh axis, GSPMD-auto over the rest) fails to
    partition on jaxlib 0.4.x — "PartitionId instruction is not supported
    for SPMD partitioning" (and some shapes hard-CHECK in
    spmd_partitioner.cc). The pipeline's stage-manual tests skipif on this
    so tier-1 stays green instead of carrying known-red tests; full-manual
    regions (ring attention, ops/collective_matmul.py) are unaffected."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import runbooks_tpu  # noqa: F401 — installs the jax.shard_map compat shim
    from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=2, stage=2, fsdp=2))
    try:
        with jax.set_mesh(mesh):
            jax.jit(jax.shard_map(
                lambda a: a + jax.lax.axis_index("stage").astype(jnp.float32),
                mesh=mesh, in_specs=P("stage"), out_specs=P("stage"),
                axis_names={"stage"}, check_vma=False,
            ))(jnp.zeros(8, jnp.float32)).block_until_ready()
        return False
    except Exception as exc:  # noqa: BLE001
        # Only the two known partitioner signatures mean "broken" —
        # anything else (e.g. too few devices for the probe mesh) must not
        # silently skip the whole pipeline suite on a healthy jaxlib.
        return "PartitionId" in str(exc) or "manual_axes" in str(exc)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _reset_fleet_history():
    """The fleet history (obs/history.py HISTORY) is process-wide state
    written by every FleetScraper sweep and read by the burn-rate SLO
    evaluator and the autoscaler's windowed p90 — one test's appended
    rings must never leak a computable window into another test's
    reconciles (the windows key off REAL wall-clock time, so leakage
    would be order- and wall-time-dependent flakiness)."""
    from runbooks_tpu.obs.history import HISTORY

    HISTORY.reset()
    yield
    HISTORY.reset()
