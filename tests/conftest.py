"""Test harness: force an 8-device virtual CPU platform before JAX initializes.

Multi-chip TPU hardware is not available in CI; all sharding logic is tested on
a virtual 8-device CPU mesh (the same technique the driver's dryrun_multichip
uses). Mirrors the reference's strategy of testing the whole operator loop
without cloud dependencies (SURVEY.md §4: envtest + kind cloud).
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Exact-math tests: JAX's *default* matmul precision may round inputs to
# bf16 even for f32 arrays, which makes results shape-dependent (full matmul
# vs sliced matmul accumulate differently). Pin highest precision in tests;
# production code on TPU keeps the fast default (bf16 on the MXU).
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
