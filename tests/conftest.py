"""Test harness: force an 8-device virtual CPU platform before JAX initializes.

Multi-chip TPU hardware is not available in CI; all sharding logic is tested on
a virtual 8-device CPU mesh (the same technique the driver's dryrun_multichip
uses). Mirrors the reference's strategy of testing the whole operator loop
without cloud dependencies (SURVEY.md §4: envtest + kind cloud).
"""

import os
import sys

# Must be set before jax is imported anywhere. Forced (not setdefault): the
# repo image pins JAX_PLATFORMS=axon (the TPU relay plugin) in the ambient
# env, and a bare `pytest tests/` must not dial the relay — the relay is
# single-client and may be down. Set RBT_TEST_PLATFORM to override.
# The pinning recipe lives in benchkit.apply_cpu_env (also clears
# PALLAS_AXON_POOL_IPS so test subprocesses skip the relay hook too).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from benchkit import apply_cpu_env  # noqa: E402

if os.environ.get("RBT_TEST_PLATFORM", "cpu") == "cpu":
    apply_cpu_env(n_devices=8)
else:
    os.environ["JAX_PLATFORMS"] = os.environ["RBT_TEST_PLATFORM"]

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Belt-and-braces: pytest loads installed plugins BEFORE conftest, and
    # some of them import jax — which latches the ambient JAX_PLATFORMS
    # (axon) at import time, making the env override above a no-op and
    # hanging the first jax.devices() on the dead relay. The config update
    # still works as long as no backend has been initialized yet.
    jax.config.update("jax_platforms", "cpu")

# Exact-math tests: JAX's *default* matmul precision may round inputs to
# bf16 even for f32 arrays, which makes results shape-dependent (full matmul
# vs sliced matmul accumulate differently). Pin highest precision in tests;
# production code on TPU keeps the fast default (bf16 on the MXU).
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
