"""Speculative decoding on the chunked decode path (serve/engine.py
make_verify_fn + serve/paging.py make_paged_verify_fn +
serve/speculative.py prompt-lookup drafting).

Correctness bar: greedy outputs must be token-for-token IDENTICAL with
speculation on vs off (dense AND paged, plain AND int8-KV), and
temperature sampling's emitted-token marginal must equal the engine's
own ``sample`` distribution (exact rejection sampling). The drafter is
allowed to be arbitrarily wrong — a bad draft may cost throughput,
never content.
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.controller.common import validate_params
from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import init_params
from runbooks_tpu.ops.sampling import sample, speculative_verify
from runbooks_tpu.serve.engine import InferenceEngine, Request
from runbooks_tpu.serve.paging import PagedInferenceEngine
from runbooks_tpu.serve.speculative import NgramDraftIndex


def tiny_cfg(**over):
    base = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                max_seq_len=64, dtype="float32")
    base.update(over)
    return dataclasses.replace(get_config("llama2-7b"), **base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.key(0))


# A prompt with internal repetition: the trailing n-gram recurs, so the
# prompt-lookup drafter fires from the first decode step.
REP_PROMPT = [5, 6, 7, 8] * 5 + [5, 6]
RND_PROMPT = list(np.random.default_rng(7).integers(1, 128, 18))


def drive(engine, reqs, max_steps=800):
    """Step until every (already submitted) request finishes."""
    for _ in range(max_steps):
        engine.step()
        if all(r.finished for r in reqs):
            return
    raise AssertionError("requests did not finish")


def run_all(engine, reqs, max_steps=800):
    for r in reqs:
        engine.submit(r)
    drive(engine, reqs, max_steps)


def greedy_reqs(prompts, max_tokens=12, **kw):
    return [Request(prompt_tokens=list(p), max_tokens=max_tokens,
                    temperature=0.0, **kw) for p in prompts]


# ---------------------------------------------------------------------------
# Prompt-lookup index
# ---------------------------------------------------------------------------

def test_ngram_index_basics():
    idx = NgramDraftIndex(2, ngram_max=3, ngram_min=1)
    idx.begin(0, [1, 2, 3, 4, 1, 2, 3])
    # trailing 3-gram [1,2,3] occurred at 0..2; continuation starts at 3
    assert idx.draft(0, 4) == [4, 1, 2, 3]
    assert idx.draft(0, 2) == [4, 1]
    # longer n wins over a shorter-n match elsewhere
    idx2 = NgramDraftIndex(1, ngram_max=2, ngram_min=1)
    idx2.begin(0, [9, 1, 2, 7, 1, 2])
    assert idx2.draft(0, 1) == [7]        # 2-gram [1,2] -> 7
    # extend shifts the trailing gram; generated tokens are indexed too
    idx2.extend(0, 7)                     # ctx ...1,2,7 ; [2,7] known -> 1
    assert idx2.draft(0, 2) == [1, 2]
    # no match -> empty draft
    idx3 = NgramDraftIndex(1, ngram_max=3, ngram_min=2)
    idx3.begin(0, [1, 2, 3, 4, 5])
    assert idx3.draft(0, 4) == []
    idx.clear(0)
    assert idx.draft(0, 4) == []


def test_ngram_index_trailing_gram_never_matches_itself():
    # Registration is delayed one token: the trailing unigram [3] must
    # not "match" its own occurrence at the end (which would propose an
    # empty continuation); only the earlier occurrence counts.
    idx = NgramDraftIndex(1, ngram_max=1, ngram_min=1)
    idx.begin(0, [3, 9, 3])
    assert idx.draft(0, 2) == [9, 3]
    # a token seen only at the very end has no known continuation yet
    idx.begin(0, [1, 2, 3])
    assert idx.draft(0, 2) == []
    assert idx.context_len(0) == 3


def test_ngram_index_validation():
    with pytest.raises(ValueError, match="ngram"):
        NgramDraftIndex(1, ngram_max=2, ngram_min=3)
    with pytest.raises(ValueError, match="ngram"):
        NgramDraftIndex(1, ngram_max=0, ngram_min=0)


# ---------------------------------------------------------------------------
# Verify-sampling math (ops/sampling.speculative_verify)
# ---------------------------------------------------------------------------

def test_speculative_verify_greedy_math():
    logits = jnp.asarray(np.random.default_rng(0).normal(
        size=(2, 3, 16)).astype(np.float32))
    argmax = np.asarray(jnp.argmax(logits, axis=-1))
    # row 0 drafts the exact argmax chain; row 1 drafts wrong tokens
    drafts = np.zeros((2, 2), np.int32)
    drafts[0] = argmax[0, :2]
    drafts[1] = (argmax[1, :2] + 1) % 16
    accept, resid, full = speculative_verify(
        logits, jnp.asarray(drafts), jax.random.key(0),
        jnp.zeros(2), jnp.zeros(2, jnp.int32), jnp.ones(2))
    accept, resid, full = (np.asarray(accept), np.asarray(resid),
                           np.asarray(full))
    assert accept[0].all() and not accept[1].any()
    # greedy correction/bonus are the argmax everywhere
    np.testing.assert_array_equal(resid, argmax[:, :2])
    np.testing.assert_array_equal(full, argmax)


def test_speculative_verify_temperature_marginal_matches_sample():
    """Distribution exactness: the emitted token at a verify position
    (accepted draft, else residual) must be distributed exactly like a
    plain sample() draw — including top-k lane truncation."""
    vocab, n = 12, 4000
    logits = jnp.asarray(
        np.random.default_rng(1).normal(size=(1, 2, vocab))
        .astype(np.float32))
    draft = jnp.asarray([[3]], jnp.int32)   # a mid-probability token
    temps = jnp.asarray([0.9])
    top_ks = jnp.asarray([6], jnp.int32)
    top_ps = jnp.asarray([1.0])
    keys = jax.random.split(jax.random.key(2), n)

    @jax.jit
    def one(key):
        accept, resid, _ = speculative_verify(
            logits, draft, key, temps, top_ks, top_ps)
        return jnp.where(accept[0, 0], draft[0, 0], resid[0, 0])

    emitted = np.asarray(jax.vmap(one)(keys))

    @jax.jit
    def ref(key):
        return sample(logits[:, 0], key, temps, top_ks, top_ps)[0]

    reference = np.asarray(jax.vmap(ref)(jax.random.split(
        jax.random.key(3), n)))
    emp = np.bincount(emitted, minlength=vocab) / n
    exp = np.bincount(reference, minlength=vocab) / n
    # both are n-sample empirical draws from the same distribution
    assert np.abs(emp - exp).max() < 0.05, (emp, exp)
    # tokens outside the top-6 lane must never be emitted
    lane = set(np.asarray(jax.lax.top_k(logits[0, 0], 6)[1]).tolist())
    assert set(np.unique(emitted)).issubset(lane)


def test_speculative_verify_accept_probability_is_pi_draft():
    vocab, n = 8, 4000
    logits = jnp.asarray(
        np.random.default_rng(4).normal(size=(1, 2, vocab))
        .astype(np.float32))
    temp = 0.7
    pi = np.asarray(jax.nn.softmax(logits[0, 0] / temp))
    draft = jnp.asarray([[int(np.argsort(pi)[-2])]], jnp.int32)
    keys = jax.random.split(jax.random.key(5), n)

    @jax.jit
    def one(key):
        accept, _, _ = speculative_verify(
            logits, draft, key, jnp.asarray([temp]),
            jnp.zeros(1, jnp.int32), jnp.ones(1))
        return accept[0, 0]

    rate = float(np.asarray(jax.vmap(one)(keys)).mean())
    assert abs(rate - pi[int(draft[0, 0])]) < 0.04


# ---------------------------------------------------------------------------
# Greedy parity: speculation must never change greedy output
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("quantize_kv", [False, True],
                         ids=["kv-native", "kv-int8"])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_greedy_parity_dense(dtype, quantize_kv):
    cfg = tiny_cfg(dtype=dtype)
    params = init_params(cfg, jax.random.key(0))
    prompts = [REP_PROMPT, RND_PROMPT]
    off = InferenceEngine(cfg, params, max_slots=2, max_seq_len=64,
                          quantize_kv=quantize_kv, speculative="off")
    reqs_off = greedy_reqs(prompts)
    run_all(off, reqs_off)
    on = InferenceEngine(cfg, params, max_slots=2, max_seq_len=64,
                         quantize_kv=quantize_kv, speculative="ngram")
    reqs_on = greedy_reqs(prompts)
    run_all(on, reqs_on)
    assert [r.output_tokens for r in reqs_on] == \
        [r.output_tokens for r in reqs_off]
    # speculation actually fired (the repetitive prompt drafts)
    assert on.spec_drafted > 0
    assert off.spec_drafted == 0 and off.spec_verify_steps == 0


@pytest.mark.parametrize("quantize_kv", [False, True],
                         ids=["kv-native", "kv-int8"])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_greedy_parity_paged(dtype, quantize_kv):
    cfg = tiny_cfg(dtype=dtype)
    params = init_params(cfg, jax.random.key(0))
    prompts = [REP_PROMPT, RND_PROMPT]
    off = PagedInferenceEngine(cfg, params, max_slots=2, max_seq_len=64,
                               page_size=8, quantize_kv=quantize_kv,
                               speculative="off")
    reqs_off = greedy_reqs(prompts)
    run_all(off, reqs_off)
    on = PagedInferenceEngine(cfg, params, max_slots=2, max_seq_len=64,
                              page_size=8, quantize_kv=quantize_kv,
                              speculative="ngram")
    reqs_on = greedy_reqs(prompts)
    run_all(on, reqs_on)
    assert [r.output_tokens for r in reqs_on] == \
        [r.output_tokens for r in reqs_off]
    assert on.spec_drafted > 0


# ---------------------------------------------------------------------------
# Batched verify semantics
# ---------------------------------------------------------------------------

class _OracleEngine(InferenceEngine):
    """Real verify path, controlled drafts: each request carries its own
    future (recorded spec-off greedy output) and a per-request accuracy;
    corrupted tokens always differ from the truth, so they are always
    rejected — accept lengths become deterministic per slot."""

    def _draft_for(self, slot, max_tokens):
        req = self.slot_req[slot]
        done = len(req.output_tokens)
        future = req._oracle[done:done + max_tokens]
        out = []
        for j, t in enumerate(future):
            if req._wrong_at is not None and done + j >= req._wrong_at:
                out.append((int(t) + 1) % self.cfg.vocab_size)
            else:
                out.append(int(t))
        return out


def test_variable_accept_lengths_in_one_batch(model):
    cfg, params = model
    prompts = [REP_PROMPT, RND_PROMPT, list(RND_PROMPT[::-1])]
    off = InferenceEngine(cfg, params, max_slots=4, max_seq_len=64,
                          speculative="off")
    reqs_off = greedy_reqs(prompts)
    run_all(off, reqs_off)
    truth = [r.output_tokens for r in reqs_off]

    on = _OracleEngine(cfg, params, max_slots=4, max_seq_len=64,
                       speculative="ngram", draft_tokens=4,
                       prefill_budget=256)
    reqs_on = greedy_reqs(prompts)
    # slot 0: perfect drafts; slot 1: first draft right then wrong;
    # slot 2: immediately rejected — three different accept lengths in
    # ONE verify dispatch (prefill_budget raised so one step admits all
    # three).
    for r, t, wrong in zip(reqs_on, truth, (None, 2, 0)):
        r._oracle, r._wrong_at = t, wrong
    for r in reqs_on:
        on.submit(r)
    on.step()   # admits all three, then runs one verify step
    lens = [len(r.output_tokens) for r in reqs_on]
    # prefill token + (accepted + 1): full accept = 1+5, reject-at-1 =
    # 1+2, reject-at-0 = 1+1
    assert lens == [6, 3, 2], lens
    drive(on, reqs_on)
    assert [r.output_tokens for r in reqs_on] == truth
    assert 0 < on.spec_accepted < on.spec_drafted


def test_no_draft_slots_ride_the_same_verify_batch(model):
    cfg, params = model
    on = _OracleEngine(cfg, params, max_slots=4, max_seq_len=64,
                       speculative="ngram", draft_tokens=4)
    off = InferenceEngine(cfg, params, max_slots=4, max_seq_len=64)
    reqs_off = greedy_reqs([REP_PROMPT, RND_PROMPT])
    run_all(off, reqs_off)
    truth = [r.output_tokens for r in reqs_off]
    reqs_on = greedy_reqs([REP_PROMPT, RND_PROMPT])
    reqs_on[0]._oracle, reqs_on[0]._wrong_at = truth[0], None
    reqs_on[1]._oracle, reqs_on[1]._wrong_at = [], None  # never drafts
    for r in reqs_on:
        on.submit(r)
    steps_before = on.spec_verify_steps
    on.step()
    # one verify step advanced BOTH slots: the drafting slot by 5, the
    # draft-less one by its plain 1 token (mixed traffic, one program)
    assert on.spec_verify_steps == steps_before + 1
    assert len(reqs_on[0].output_tokens) == 6
    assert len(reqs_on[1].output_tokens) == 2
    drive(on, reqs_on)
    assert [r.output_tokens for r in reqs_on] == truth


def test_all_slots_draftless_falls_back_to_decode_chunk(model):
    cfg, params = model
    on = _OracleEngine(cfg, params, max_slots=2, max_seq_len=64,
                       speculative="ngram", draft_tokens=4)
    reqs = greedy_reqs([RND_PROMPT])
    reqs[0]._oracle, reqs[0]._wrong_at = [], None
    run_all(on, reqs)
    # no drafts anywhere -> every step was a plain decode chunk
    assert on.spec_verify_steps == 0 and on.spec_drafted == 0
    off = InferenceEngine(cfg, params, max_slots=2, max_seq_len=64)
    reqs_off = greedy_reqs([RND_PROMPT])
    run_all(off, reqs_off)
    assert reqs[0].output_tokens == reqs_off[0].output_tokens


# ---------------------------------------------------------------------------
# Paged rollback / radix safety
# ---------------------------------------------------------------------------

class _PagedOracleEngine(PagedInferenceEngine):
    _draft_for = _OracleEngine._draft_for


def test_paged_rollback_never_corrupts_shared_pages(model):
    """Rejected-draft rollback with radix-shared prefix pages in play:
    every write must land in private pages, so followers reusing the
    shared prefix (and pages adopted from speculative finishers) decode
    the exact spec-off tokens, and page accounting balances."""
    cfg, params = model
    shared = list(range(1, 17))          # 2 full 8-token pages
    prompts = [shared + [50 + i] for i in range(3)]

    off = PagedInferenceEngine(cfg, params, max_slots=2, max_seq_len=64,
                               page_size=8, speculative="off")
    off.register_prefix(shared)
    reqs_off = greedy_reqs(prompts, max_tokens=10)
    run_all(off, reqs_off)
    truth = [r.output_tokens for r in reqs_off]

    on = _PagedOracleEngine(cfg, params, max_slots=2, max_seq_len=64,
                            page_size=8, speculative="ngram",
                            draft_tokens=4)
    on.register_prefix(shared)
    # heavy rejection traffic: every slot's drafts go wrong at token 2
    reqs_on = greedy_reqs(prompts, max_tokens=10)
    for r, t in zip(reqs_on, truth):
        r._oracle, r._wrong_at = t, 2
    run_all(on, reqs_on)
    assert [r.output_tokens for r in reqs_on] == truth
    assert 0 < on.spec_accepted < on.spec_drafted   # rejections happened
    # radix parity after rejection: a FOLLOWER admitted against the
    # tree state left by speculative finishers still matches greedy
    follower = greedy_reqs([shared + [50]], max_tokens=10)
    follower[0]._oracle, follower[0]._wrong_at = truth[0], 2
    run_all(on, follower)
    assert follower[0].output_tokens == truth[0]
    # page accounting balances: all slots free, remaining used pages
    # are exactly the radix tree's (refcount 1 each)
    occ = on.pager.occupancy()
    assert not on.active.any()
    assert occ["pages_used"] == occ["pages_shared"] == on.pager.radix.nodes
    for pages in on.pager.slot_pages:
        assert pages == []


def test_deadline_expiry_with_speculation_releases_pages(model):
    cfg, params = model
    probe = PagedInferenceEngine(cfg, params, max_slots=2,
                                 max_seq_len=64, page_size=8)
    truth = greedy_reqs([REP_PROMPT], max_tokens=30)
    run_all(probe, truth)
    on = _PagedOracleEngine(cfg, params, max_slots=2, max_seq_len=64,
                            page_size=8, speculative="ngram")
    free0 = on.pager.allocator.free_count
    req = Request(prompt_tokens=list(REP_PROMPT), max_tokens=30,
                  temperature=0.0, deadline_s=0.05)
    req._oracle, req._wrong_at = truth[0].output_tokens, None
    on.submit(req)
    on.step()                      # admit + first verify step
    assert on.spec_verify_steps >= 1 and not req.finished
    time.sleep(0.06)
    on.step()                      # deadline check runs between steps
    assert req.finished and req.finish_reason == "deadline"
    # pages released; whatever the tree adopted is tree-only (refcount 1)
    assert on.pager.slot_pages[req._slot if req._slot >= 0 else 0] == []
    assert on.pager.allocator.free_count == \
        free0 - on.pager.radix.nodes


def test_eos_inside_accepted_draft(model):
    cfg, params = model
    off = InferenceEngine(cfg, params, max_slots=2, max_seq_len=64)
    probe = greedy_reqs([REP_PROMPT], max_tokens=12)
    run_all(off, probe)
    # pick an EOS that lands mid-output, so with K=4 drafting it can sit
    # INSIDE an accepted draft run
    eos = probe[0].output_tokens[3]
    reqs_off = greedy_reqs([REP_PROMPT], max_tokens=12, eos_id=eos)
    run_all(off, reqs_off)
    on = _OracleEngine(cfg, params, max_slots=2, max_seq_len=64,
                       speculative="ngram", draft_tokens=4)
    reqs_on = greedy_reqs([REP_PROMPT], max_tokens=12, eos_id=eos)
    reqs_on[0]._oracle, reqs_on[0]._wrong_at = probe[0].output_tokens, None
    run_all(on, reqs_on)
    assert reqs_on[0].output_tokens == reqs_off[0].output_tokens
    assert reqs_on[0].finish_reason == reqs_off[0].finish_reason == "stop"
    assert reqs_on[0].output_tokens[-1] == eos
    assert on.spec_accepted > 0


def test_draft_caps_respect_budget_and_room(model):
    cfg, params = model
    on = _OracleEngine(cfg, params, max_slots=2, max_seq_len=64,
                       speculative="ngram", draft_tokens=4)
    off = InferenceEngine(cfg, params, max_slots=2, max_seq_len=64)
    reqs_off = greedy_reqs([REP_PROMPT], max_tokens=3)
    run_all(off, reqs_off)
    # max_tokens=3: after the prefill token only 2 remain, so the cap is
    # 1 draft (emitting d+1 <= remaining); output must not overshoot
    reqs_on = greedy_reqs([REP_PROMPT], max_tokens=3)
    reqs_on[0]._oracle, reqs_on[0]._wrong_at = reqs_off[0].output_tokens, \
        None
    run_all(on, reqs_on)
    assert reqs_on[0].output_tokens == reqs_off[0].output_tokens
    assert len(reqs_on[0].output_tokens) == 3
    assert reqs_on[0].finish_reason == "length"


# ---------------------------------------------------------------------------
# Compile discipline + observability
# ---------------------------------------------------------------------------

def test_zero_unexpected_compiles_in_steady_speculative_loop(model):
    from runbooks_tpu.obs import device as obs_device

    cfg, params = model
    engine = InferenceEngine(cfg, params, max_slots=2, max_seq_len=64,
                             speculative="ngram")
    try:
        engine.warmup()
        assert engine.warmup_census["verify_programs"] == \
            len(engine.view_buckets)
        assert engine.warmup_census["speculative"] == "ngram"
        sentinel = obs_device.SENTINEL
        before = sentinel.unexpected
        # steady traffic across both paths: drafting slots (verify) and
        # draft-less slots (plain chunk), several admission waves
        for _ in range(2):
            reqs = greedy_reqs([REP_PROMPT, RND_PROMPT], max_tokens=10)
            run_all(engine, reqs)
        assert engine.spec_verify_steps > 0
        assert sentinel.unexpected == before, \
            sentinel.recent_unexpected()
    finally:
        engine.release_steady()


def test_spec_metrics_and_stats(model):
    from runbooks_tpu.obs import metrics as obs_metrics

    cfg, params = model
    engine = InferenceEngine(cfg, params, max_slots=2, max_seq_len=64,
                             speculative="ngram")
    run_all(engine, greedy_reqs([REP_PROMPT], max_tokens=10))
    stats = engine.spec_stats()
    assert stats["mode"] == "ngram"
    assert stats["drafted_total"] == engine.spec_drafted > 0
    assert stats["accepted_total"] == engine.spec_accepted
    assert 0.0 <= stats["accept_rate"] <= 1.0
    buckets = stats["tokens_per_sec_by_accept_rate"]
    assert set(buckets) == {"0-25%", "25-50%", "50-75%", "75-100%"}
    assert sum(b["tokens"] for b in buckets.values()) > 0
    # the engine-side histograms exist in the process registry
    text = obs_metrics.REGISTRY.render()
    assert "serve_spec_accept_len_bucket" in text
    assert "serve_verify_dispatch_seconds_bucket" in text
    # spec-off engines report a bare mode and register no spec families
    off = InferenceEngine(cfg, params, max_slots=2, max_seq_len=64)
    assert off.spec_stats() == {"mode": "off"}


# ---------------------------------------------------------------------------
# Validation (engine + controller)
# ---------------------------------------------------------------------------

def test_engine_speculative_validation(model):
    cfg, params = model
    with pytest.raises(ValueError, match="speculative"):
        InferenceEngine(cfg, params, max_slots=2, max_seq_len=64,
                        speculative="medusa")
    with pytest.raises(ValueError, match="draft_tokens"):
        InferenceEngine(cfg, params, max_slots=2, max_seq_len=64,
                        speculative="ngram", draft_tokens=0)
    with pytest.raises(ValueError, match="ngram"):
        InferenceEngine(cfg, params, max_slots=2, max_seq_len=64,
                        speculative="ngram", ngram_max=1, ngram_min=2)
    # config-driven resolution: the engine follows cfg.speculative
    cfg_on = dataclasses.replace(cfg, speculative="ngram",
                                 draft_tokens=2)
    eng = InferenceEngine(cfg_on, params, max_slots=2, max_seq_len=64)
    assert eng.speculative == "ngram" and eng.draft_tokens == 2
    assert eng._spec_index is not None


def test_validate_params_speculative():
    assert validate_params({"speculative": "ngram"}) is None
    assert validate_params({"speculative": "off"}) is None
    err = validate_params({"speculative": "medusa"})
    assert err is not None and "speculative" in err
    err = validate_params({"draft_tokens": 0})
    assert err is not None and "draft_tokens" in err
    err = validate_params({"draftTokens": "four"})
    assert err is not None
    assert validate_params({"draftTokens": 8, "ngramMax": 4,
                            "ngramMin": 2}) is None
    err = validate_params({"ngram_min": 3, "ngram_max": 2})
    assert err is not None and "ngram_min" in err
    # a lone ngram_min above the engine default ngram_max (3) must fail
    # HERE, not crash-loop the replica at engine construction
    err = validate_params({"ngram_min": 5})
    assert err is not None and "ngram_min" in err
    assert validate_params({"ngram_min": 3}) is None
    assert validate_params({"ngram_max": 1}) is None  # default min is 1
    err = validate_params({"ngramMin": 0})
    assert err is not None
